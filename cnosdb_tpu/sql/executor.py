"""Query execution: plans → results over the coordinator + TpuExec.

Role-parity with the reference's execution layer (query_server/query/src/
execution/: SqlQueryExecution optimize→schedule→stream, execution/ddl/*
one executor per DDL op): aggregates fan out per placed vnode, each vnode
runs the fused device kernel, partials merge on the host by group key
(count/sum add, min/max combine, mean from sum+count, first/last by actual
timestamp) — the single-node form of the partial→final AggregateExec
split, with the ICI path in parallel/distributed_agg doing the same inside
one mesh.
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field

import numpy as np

from ..errors import (
    CnosError, ExecutionError, FunctionError, PlanError, QueryError,
    TableNotFound,
)
from ..models.points import WriteBatch
from ..models.predicate import TimeRanges
from ..models.schema import (
    ColumnType, DatabaseOptions, DatabaseSchema, Duration, Precision,
    TenantOptions, TskvTableSchema, ValueType,
)
from ..models.codec import Encoding
from ..models.strcol import DictArray, as_object_array
from ..ops.tpu_exec import AggSpec, TpuQuery, execute_scan_aggregate
from ..parallel.coordinator import Coordinator
from ..parallel.meta import MetaStore
from ..server import memory as memgov
from ..utils import stages
from ..utils import lockwatch
from .. import faults

faults.register_point("memory.spill", __name__,
                      desc="group-state spill file publish "
                           "(tmp+fsync+rename)")
from . import ast
from . import expr as expr_mod
from . import relational as rel
from .expr import (
    Column, Expr, Func, InList, InSubquery, Literal, Subquery, WindowFunc,
)
from .parser import parse_sql
from .planner import AGG_FUNCS, AggregatePlan, RawScanPlan, plan_select


@dataclass
class Session:
    tenant: str = "cnosdb"
    database: str = "public"
    user: str = "root"


@dataclass
class ResultSet:
    names: list[str]
    columns: list[np.ndarray]
    types: list[str] = field(default_factory=list)

    @property
    def n_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def rows(self) -> list[tuple]:
        if not self.columns:
            return []
        # float32 stays a numpy scalar so renderers can keep f32
        # precision (tolist() would widen to python float = f64)
        cols = [list(c) if getattr(c, "dtype", None) == np.float32
                else c.tolist() for c in self.columns]
        return list(zip(*cols))

    def to_dict(self) -> dict:
        return {n: c for n, c in zip(self.names, self.columns)}

    @classmethod
    def empty(cls, names=()):
        return cls(list(names), [np.empty(0, dtype=object) for _ in names])

    @classmethod
    def message(cls, text: str):
        return cls(["result"], [np.array([text], dtype=object)])


class QueryTracker:
    """Running-query registry with cooperative kill (reference
    dispatcher/query_tracker.rs:32)."""

    def __init__(self):
        import threading

        self._lock = lockwatch.Lock("executor.query_tracker")
        self._next = 1
        self.running: dict[int, dict] = {}

    def register(self, sql: str, session: "Session",
                 ctx=None) -> int:
        import time as _t

        with self._lock:
            qid = self._next
            self._next += 1
            self.running[qid] = {"sql": sql, "user": session.user,
                                 "tenant": session.tenant,
                                 "db": session.database,
                                 "start": _t.time(), "cancelled": False,
                                 "ctx": ctx}
            if ctx is not None:
                # link the request-lifecycle context (utils/deadline.py)
                # so KILL QUERY / disconnect can cancel in-flight remote
                # work, not just the between-statement checks
                ctx.qid = str(qid)
            return qid

    def finish(self, qid: int):
        with self._lock:
            self.running.pop(qid, None)

    def kill(self, qid: int) -> bool:
        with self._lock:
            q = self.running.get(qid)
            if q is None:
                return False
            q["cancelled"] = True
            ctx = q.get("ctx")
        if ctx is not None:
            ctx.cancel("killed")
        return True

    def ctx_of(self, qid: int):
        with self._lock:
            q = self.running.get(qid)
            return q.get("ctx") if q is not None else None

    def check_cancelled(self, qid: int):
        q = self.running.get(qid)
        if q is not None and q["cancelled"]:
            raise QueryError(f"query {qid} cancelled")
        ctx = q.get("ctx") if q is not None else None
        if ctx is not None:
            ctx.check()  # deadline expiry / disconnect-cancel

    def snapshot(self) -> list[tuple[int, dict]]:
        with self._lock:
            return [(qid, dict(q)) for qid, q in self.running.items()]


class QueryExecutor:
    def __init__(self, meta: MetaStore, coord: Coordinator,
                 memory_pool=None):
        import threading as _th

        from ..utils.memory_pool import DEFAULT_POOL

        self.meta = meta
        self.coord = coord
        self.tracker = QueryTracker()
        self.memory_pool = memory_pool or DEFAULT_POOL
        self._stream_engine = None
        self._stream_lock = _th.Lock()
        self._matview_engine = None
        self._matview_lock = _th.Lock()
        # planner consults materialized rollups unless disabled (the
        # rewrite is bit-identical, so this is an escape hatch, not a
        # correctness knob)
        self.matview_rewrite_enabled = \
            os.environ.get("CNOSDB_MATVIEW_REWRITE", "1") != "0"
        # serving plane (plan cache / result cache / fused batching);
        # CNOSDB_SERVING=0 restores byte-identical legacy behavior
        self.serving = None
        if os.environ.get("CNOSDB_SERVING", "1") != "0":
            from ..server.serving import ServingPlane

            self.serving = ServingPlane(self)

    # ------------------------------------------------------------------ api
    def execute_sql(self, sql: str, session: Session | None = None) -> list[ResultSet]:
        session = session or Session()
        from contextlib import nullcontext

        from ..server import trace as _trace
        from ..utils import deadline as _deadline_mod

        # adopt the ambient request context (installed at HTTP ingress);
        # embedded/direct callers without one keep today's no-deadline
        # behavior — only the cooperative kill applies
        ctx = _deadline_mod.current()
        qid = self.tracker.register(sql, session, ctx=ctx)
        import threading as _th
        import time as _t

        if not hasattr(self, "_tls"):
            self._tls = _th.local()
        prev_qid = getattr(self._tls, "qid", None)
        self._tls.qid = qid
        # always-on per-query profile: adopt an ambient one (bench /
        # EXPLAIN ANALYZE / a caller-installed scope) or own a fresh one
        prof = stages.current_profile()
        own_prof = prof is None
        if own_prof:
            prof = stages.QueryProfile(
                node_id=getattr(self.coord, "node_id", None))
        prof.qid = str(qid)
        if prof.sql is None:
            prof.sql = sql[:512]
        span = _trace.current_span()
        if span is not None:
            prof.trace_id = span.trace_id
        t0 = _t.perf_counter()
        error: str | None = None
        try:
            with (stages.profile_scope(prof) if own_prof
                  else nullcontext()):
                if self.serving is not None:
                    out = self.serving.try_execute(sql, session)
                    if out is not None:
                        self._record_query_usage(sql, session)
                        return out
                out = []
                for s in parse_sql(sql):
                    self.tracker.check_cancelled(qid)
                    out.append(self.execute_statement(s, session))
                self._record_query_usage(sql, session)
                return out
        except BaseException as e:
            error = f"{type(e).__name__}: {e}"[:200]
            raise
        finally:
            wall_ms = (_t.perf_counter() - t0) * 1e3
            try:
                self._finish_profile(prof, wall_ms, error, span, session)
            except Exception:
                stages.count_error("swallow.executor.profile")
            self._tls.qid = prev_qid
            self.tracker.finish(qid)

    def _finish_profile(self, prof, wall_ms: float, error: str | None,
                        span, session: Session) -> None:
        """Seal one query's profile: stamp wall time + device telemetry,
        publish to the bounded PROFILES ring (`GET /debug/profile`),
        attach stage timings to the root trace span, and feed the
        slow-query log. Runs in execute_sql's `finally`, so KILLed and
        deadline-exceeded queries are recorded too."""
        prof.finish(wall_ms=wall_ms, error=error)
        stages.PROFILES.record(prof)
        if span is not None:
            for k, v in prof.snapshot().items():
                span.set_tag(f"stage.{k}", v)
            span.set_tag("profile.qid", prof.qid)
        threshold = int(getattr(self, "slow_query_threshold_ms", 0) or 0)
        if threshold > 0 and wall_ms >= threshold:
            self._slow_query_log(prof, wall_ms, error, session)

    def _slow_query_log(self, prof, wall_ms: float, error: str | None,
                        session: Session) -> None:
        """usage_schema.slow_queries: one row per threshold-exceeding
        query (value = wall ms) tagged with qid/trace id/user and the
        dominant stage costs, so the log is SQL-queryable next to the
        rest of the self-telemetry plane. Never fails the query."""
        try:
            totals = prof.stage_totals()
            tags = {"tenant": session.tenant, "database": session.database,
                    "node_id": str(self.coord.node_id),
                    "user": session.user, "qid": str(prof.qid),
                    "trace_id": prof.trace_id or "",
                    "sql": (prof.sql or "")[:180],
                    "error": (error or "")[:120],
                    "decode_ms": str(totals.get("decode_ms", 0)),
                    "kernel_ms": str(totals.get("kernel_ms", 0)),
                    "merge_ms": str(totals.get("merge_ms", 0))}
            self.coord.record_usage("slow_queries", tags, int(wall_ms))
        except Exception:
            stages.count_error("swallow.executor.slow_query_log")

    def _record_query_usage(self, sql: str, session: Session):
        """usage_schema counters for the SQL plane (reference
        usage_schema.rs sql_data_in / coord_queries reporters) — 1-second
        throttled cumulative rows; never fails the query."""
        try:
            tags = {"tenant": session.tenant, "database": session.database,
                    "node_id": str(self.coord.node_id)}
            self.coord.record_usage("sql_data_in", tags, len(sql),
                                    throttle=True, cumulative=True)
            self.coord.record_usage("coord_queries", tags, 1,
                                    throttle=True, cumulative=True)
        except Exception:
            pass

    def _poll_cancel(self):
        qid = getattr(getattr(self, "_tls", None), "qid", None)
        if qid is not None:
            self.tracker.check_cancelled(qid)

    def _serving_invalidate(self, tenant: str, db: str,
                            table: str | None = None) -> None:
        """Push serving-plane eviction after a destructive mutation
        (DELETE / DROP / ALTER). Hygiene only — result-cache probes
        revalidate ScanTokens, so losing this push (fault point
        serving.invalidate, or a crash right here) can never cause a
        stale read; it just leaves dead entries for LRU to age out."""
        try:
            from ..server import serving

            serving.invalidate(tenant, db, table)
        except Exception:
            stages.count_error("serving.invalidate")

    def execute_one(self, sql: str, session: Session | None = None) -> ResultSet:
        rs = self.execute_sql(sql, session)
        return rs[-1] if rs else ResultSet.empty()

    def execute_statement(self, stmt, session: Session) -> ResultSet:
        self._check_privilege(stmt, session)
        if isinstance(stmt, ast.SelectStmt):
            return self._select(stmt, session)
        if isinstance(stmt, ast.UnionStmt):
            return self._union(stmt, session)
        if isinstance(stmt, ast.ExplainStmt):
            return self._explain(stmt, session)
        if isinstance(stmt, ast.CreateDatabase):
            return self._create_database(stmt, session)
        if isinstance(stmt, ast.AlterDatabase):
            return self._alter_database(stmt, session)
        if isinstance(stmt, ast.DropDatabase):
            self.coord.drop_database(session.tenant, stmt.name,
                                     if_exists=stmt.if_exists)
            self._serving_invalidate(session.tenant, stmt.name)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt, session)
        if isinstance(stmt, ast.CreateStreamTable):
            opts = {k.lower(): v for k, v in stmt.options.items()}
            missing = {"db", "table", "event_time_column"} - set(opts)
            if missing:
                raise ExecutionError(
                    f"CREATE STREAM TABLE requires WITH options "
                    f"{sorted(missing)}")
            if stmt.engine != "tskv":
                raise ExecutionError(
                    f"unsupported stream table engine {stmt.engine!r}")
            self.meta.create_stream_table(
                session.tenant, session.database, stmt.name,
                {"db": opts["db"], "table": opts["table"],
                 "event_time_column": opts["event_time_column"],
                 "columns": list(stmt.columns), "engine": stmt.engine},
                if_not_exists=stmt.if_not_exists)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.DropTable):
            db = stmt.database or session.database
            # an external table and a tskv table cannot share a name, so
            # whichever exists is the drop target
            if self.meta.drop_external_table(session.tenant, db, stmt.name):
                return ResultSet.message("ok")
            # a stream table only answers DROP when no tskv table claims
            # the name (the real table always wins)
            try:
                self.meta.table(session.tenant, db, stmt.name)
            except Exception:
                if self.meta.drop_stream_table(session.tenant, db,
                                               stmt.name):
                    return ResultSet.message("ok")
            self.meta.drop_table(session.tenant, db, stmt.name,
                                 if_exists=stmt.if_exists)
            self._serving_invalidate(session.tenant, db, stmt.name)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.AlterTable):
            return self._alter_table(stmt, session)
        if isinstance(stmt, ast.ShowStmt):
            return self._show(stmt, session)
        if isinstance(stmt, ast.DescribeStmt):
            return self._describe(stmt, session)
        if isinstance(stmt, ast.InsertStmt):
            return self._insert(stmt, session)
        if isinstance(stmt, ast.DeleteStmt):
            return self._delete(stmt, session)
        if isinstance(stmt, ast.UpdateStmt):
            return self._update(stmt, session)
        if isinstance(stmt, ast.CreateTenant):
            from ..models.schema import Duration
            from ..parallel.meta import build_limiter_config

            try:
                self.meta.create_tenant(stmt.name, TenantOptions(
                    comment=stmt.comment,
                    limiter=(build_limiter_config(stmt.limiter_groups)
                             if stmt.limiter_groups else None),
                    drop_after=(Duration.parse(stmt.drop_after)
                                if stmt.drop_after else None)))
            except Exception:
                if not stmt.if_not_exists:
                    raise
            return ResultSet.message("ok")
        if isinstance(stmt, ast.DropTenant):
            self.meta.drop_tenant(stmt.name, if_exists=stmt.if_exists,
                                  after=stmt.after)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.AlterTenantOpts):
            self.meta.alter_tenant_options(stmt.tenant, stmt.changes)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateUser):
            try:
                self.meta.create_user(
                    stmt.name, stmt.password, admin=stmt.granted_admin,
                    comment=stmt.comment,
                    must_change_password=stmt.must_change_password)
            except Exception:
                if not stmt.if_not_exists:
                    raise
            return ResultSet.message("ok")
        if isinstance(stmt, ast.DropUser):
            self.meta.drop_user(stmt.name, if_exists=stmt.if_exists)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.AlterUser):
            if stmt.name == "root" and session.user != "root":
                # only the initial admin may alter itself — a GRANTED
                # admin altering root would be privilege escalation
                # (dcl_user.slt pins comment/password/granted_admin)
                raise ExecutionError("only root may alter user root")
            self.meta.alter_user(stmt.name, changes=stmt.changes)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateRole):
            from ..errors import MetaError

            try:
                self.meta.create_role(session.tenant, stmt.name, stmt.inherit)
            except MetaError as e:
                # IF NOT EXISTS only forgives the already-exists case —
                # bad INHERIT or a missing tenant must still surface
                if not (stmt.if_not_exists and "exists" in str(e)):
                    raise
            return ResultSet.message("ok")
        if isinstance(stmt, ast.DropRole):
            from ..errors import MetaError

            if stmt.name not in self.meta.list_roles(session.tenant):
                if stmt.if_exists:
                    return ResultSet.message("ok")
                raise MetaError(f"unknown role {stmt.name!r}")
            self.meta.drop_role(session.tenant, stmt.name)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.GrantRevoke):
            if stmt.grant:
                self.meta.grant_db_privilege(session.tenant, stmt.role,
                                             stmt.database, stmt.level)
            else:
                self.meta.revoke_db_privilege(session.tenant, stmt.role,
                                              stmt.database)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.AlterTenantMember):
            if stmt.add:
                self.meta.add_member(stmt.tenant, stmt.user, stmt.role)
            else:
                self.meta.remove_member(stmt.tenant, stmt.user)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateExternalTable):
            xdb, xname = stmt.name.rsplit(".", 1) \
                if "." in stmt.name else (session.database, stmt.name)
            self.meta.create_external_table(
                session.tenant, xdb, xname, stmt.path,
                stmt.fmt, stmt.header, stmt.if_not_exists, stmt.options,
                stmt.columns)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CopyStmt):
            return self._copy(stmt, session)
        if isinstance(stmt, ast.VnodeAdmin):
            return self._vnode_admin(stmt)
        if isinstance(stmt, ast.RecoverStmt):
            if stmt.kind == "tenant":
                self.meta.recover_tenant(stmt.name)
            elif stmt.kind == "database":
                self.meta.recover_database(session.tenant, stmt.name)
            else:
                self.meta.recover_table(
                    session.tenant, stmt.database or session.database,
                    stmt.name)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateStream):
            return self._create_stream(stmt, session)
        if isinstance(stmt, ast.DropStream):
            se = self.stream_engine()
            if stmt.name not in se.streams and not stmt.if_exists:
                raise ExecutionError(f"unknown stream {stmt.name!r}")
            se.drop(stmt.name)
            self.meta.drop_stream(stmt.name)
            return ResultSet.message("ok")
        if isinstance(stmt, ast.CreateMatView):
            return self._create_matview(stmt, session)
        if isinstance(stmt, ast.DropMatView):
            return self._drop_matview(stmt)
        if isinstance(stmt, ast.KillQuery):
            ctx = self.tracker.ctx_of(stmt.query_id)
            ok = self.tracker.kill(stmt.query_id)
            if ok and ctx is not None:
                # fan best-effort cancel_scan out to every node still
                # working for this query, so remote vnode scans stop
                # DURING the fetch instead of running to completion
                try:
                    self.coord.cancel_remote_scans(ctx)
                except Exception:
                    pass  # kill remains cooperative-best-effort
            return ResultSet.message("ok" if ok else "no such query")
        if isinstance(stmt, ast.CompactStmt):
            self.coord.engine.compact_all()
            return ResultSet.message("ok")
        if isinstance(stmt, ast.FlushStmt):
            self.coord.engine.flush_all()
            return ResultSet.message("ok")
        if isinstance(stmt, ast.BackupStmt):
            entry = self.coord.backup_database(
                session.tenant, stmt.database,
                incremental=stmt.incremental)
            return ResultSet.message(
                f"backup {entry['id']}: {entry['vnodes']} vnodes, "
                f"{entry['objects_uploaded']} objects uploaded, "
                f"{entry['objects_reused']} reused")
        if isinstance(stmt, ast.RestoreStmt):
            out = self.coord.restore_database(
                session.tenant, stmt.database, backup_id=stmt.backup_id,
                to_ts=stmt.to_ts, new_name=stmt.new_name)
            # every cached plan/result over the target db read bytes that
            # the install just replaced
            self._serving_invalidate(session.tenant, out["database"])
            return ResultSet.message(
                f"restored {out['database']} from {out['backup_id']}: "
                f"{len(out['vnodes'])} vnodes")
        raise ExecutionError(f"unsupported statement {type(stmt).__name__}")

    # privilege needed per statement class
    _READ_STMTS = (ast.SelectStmt, ast.UnionStmt, ast.ShowStmt,
                   ast.DescribeStmt, ast.ExplainStmt)
    _WRITE_STMTS = (ast.InsertStmt, ast.DeleteStmt, ast.UpdateStmt)
    # instance-level administration: NEVER grantable through tenant roles
    # (a tenant owner resetting the system admin's password would be a
    # full privilege escalation). CopyStmt/CreateExternalTable touch the
    # server's LOCAL FILESYSTEM — that is instance scope too, or any
    # tenant owner could read /etc/passwd through an external table.
    _ADMIN_STMTS = (ast.CreateUser, ast.DropUser, ast.AlterUser,
                    ast.CreateTenant, ast.DropTenant, ast.AlterTenantOpts,
                    ast.CopyStmt, ast.CreateExternalTable,
                    # cluster-topology mutation reaches every tenant's
                    # vnodes via the global placement map: instance scope
                    ast.VnodeAdmin, ast.CompactStmt, ast.FlushStmt,
                    # BACKUP/RESTORE move whole databases through the
                    # shared archive store and wipe/install vnode dirs
                    ast.BackupStmt, ast.RestoreStmt)

    def _check_privilege(self, stmt, session: Session):
        """RBAC gate (reference auth/auth_control.rs AccessControlImpl →
        privilege checks on the logical plan): reads need read, DML needs
        write, tenant-scoped DDL needs tenant-owner, instance admin needs
        an admin user. Admin users and unauthenticated embedded sessions
        (user 'root') pass through."""
        from ..errors import AuthError

        user = session.user
        tenants = getattr(self.meta, "tenants", None)
        if tenants is not None and session.tenant not in tenants:
            # even an admin cannot act inside a tenant that does not
            # exist (cluster_schema/tenants.slt: select 1 errors)
            raise AuthError(f"tenant {session.tenant!r} not found")
        u = self.meta.users.get(user)
        if u is None or u.get("admin"):
            return  # unknown → authentication already failed upstream
        if isinstance(stmt, self._ADMIN_STMTS):
            raise AuthError(
                f"user {user!r} is not an admin (instance administration)")
        if isinstance(stmt, ast.RecoverStmt) and stmt.kind == "tenant":
            # RECOVER TABLE/DATABASE undo tenant-scoped DDL (checked below
            # like any DDL); only RECOVER TENANT is instance scope
            raise AuthError(
                f"user {user!r} is not an admin (instance administration)")
        if isinstance(stmt, ast.AlterTenantMember):
            # scope the check to the TARGET tenant, not the session's
            if not self.meta.check_db_privilege(user, stmt.tenant, "", "all"):
                raise AuthError(
                    f"user {user!r} is not an owner of tenant "
                    f"{stmt.tenant!r}")
            return
        if isinstance(stmt, self._READ_STMTS):
            if isinstance(stmt, ast.SelectStmt) and stmt.table is None \
                    and stmt.from_item is None:
                # constant SELECT (current_user() etc.) touches no
                # database resource — no privilege needed
                # (function/session.slt: a grantless member runs it)
                return
            need = "read"
        elif isinstance(stmt, self._WRITE_STMTS):
            need = "write"
        else:
            need = "all"
        db = getattr(stmt, "database", None) or session.database
        from .system_tables import is_system_db_for

        if is_system_db_for(db, session) and need == "read":
            return
        if not self.meta.check_db_privilege(user, session.tenant, db, need):
            raise AuthError(
                f"user {user!r} lacks {need} privilege on "
                f"{session.tenant}.{db}")

    # ------------------------------------------------------------------ streams
    def stream_engine(self):
        if self._stream_engine is None:
            with self._stream_lock:
                if self._stream_engine is None:
                    import os

                    from .stream import StreamEngine

                    self._stream_engine = StreamEngine(
                        self, os.path.join(self.coord.engine.data_dir, "streams"))
        return self._stream_engine

    def _create_stream(self, stmt: ast.CreateStream, session: Session,
                       persist: bool = True):
        from .stream import StreamQuery

        se = self.stream_engine()
        if stmt.name in se.streams:
            if stmt.if_not_exists:
                return ResultSet.message("ok")
            raise ExecutionError(f"stream {stmt.name!r} exists")
        # validate the template NOW: missing tables/columns must fail the
        # CREATE, not silently kill every future trigger
        db = stmt.select.database or session.database
        schema = self.meta.table(session.tenant, db, stmt.select.table)
        plan_select(stmt.select, schema)
        if persist:
            self.meta.create_stream(stmt.name, {
                "target": stmt.target, "select_sql": stmt.select_sql,
                "interval_s": stmt.interval_s, "delay_ns": stmt.delay_ns,
                "tenant": session.tenant, "database": session.database,
                "user": session.user})
        se.register(StreamQuery(
            name=stmt.name, sql=stmt.select_sql, stmt=stmt.select,
            interval_s=stmt.interval_s, delay_ns=stmt.delay_ns,
            session=Session(session.tenant, session.database, session.user),
            sink=("table", stmt.target)), start_ns=0)
        return ResultSet.message("ok")

    def restore_streams(self):
        """Re-register persisted streams on boot (watermarks resume)."""
        for name, d in list(self.meta.streams.items()):
            try:
                sel = parse_sql(d["select_sql"])[0]
                stmt = ast.CreateStream(
                    name, d["target"], sel, d["select_sql"],
                    d.get("interval_s", 10.0), d.get("delay_ns", 0))
                self._create_stream(
                    stmt, Session(d.get("tenant", "cnosdb"),
                                  d.get("database", "public"),
                                  d.get("user", "root")), persist=False)
            except Exception:
                import logging

                logging.getLogger("cnosdb.stream").exception(
                    "failed to restore stream %s", name)

    # ------------------------------------------------------- materialized views
    def matview_engine(self):
        if self._matview_engine is None:
            with self._matview_lock:
                if self._matview_engine is None:
                    from .matview import MatviewEngine

                    self._matview_engine = MatviewEngine(
                        self, os.path.join(self.coord.engine.data_dir,
                                           "matviews"))
        return self._matview_engine

    def _create_matview(self, stmt: ast.CreateMatView, session: Session):
        from .matview import compile_view

        me = self.matview_engine()
        me.sync_from_meta()
        if stmt.name in me.views:
            if stmt.if_not_exists:
                return ResultSet.message("ok")
            raise ExecutionError(
                f"materialized view {stmt.name!r} exists")
        db = stmt.select.database or session.database
        # eligibility is validated NOW (aggregate shape, mergeable
        # partials) — an ineligible view must fail the CREATE
        vdef = compile_view(stmt.name, stmt.select, stmt.select_sql,
                            stmt.delay_ns, session.tenant, db, self.meta)
        vdef.user = session.user
        self.meta.create_matview(stmt.name, vdef.definition())
        me.register(vdef)
        return ResultSet.message("ok")

    def _drop_matview(self, stmt: ast.DropMatView):
        me = self.matview_engine()
        me.sync_from_meta()
        if stmt.name not in me.views and not stmt.if_exists:
            raise ExecutionError(
                f"unknown materialized view {stmt.name!r}")
        self.meta.drop_matview(stmt.name)
        me.drop(stmt.name)
        return ResultSet.message("ok")

    def restore_matviews(self):
        """Instantiate the maintainer on boot so persisted views resume
        flush-driven maintenance (cheap: no jax imports)."""
        self.matview_engine().sync_from_meta()

    # ------------------------------------------------------------------ DDL
    def _create_database(self, stmt: ast.CreateDatabase, session: Session):
        opts = DatabaseOptions()
        o = stmt.options
        if "ttl" in o:
            opts.ttl = Duration.parse(o["ttl"])
        if "shard_num" in o:
            opts.shard_num = o["shard_num"]
        if "vnode_duration" in o:
            opts.vnode_duration = Duration.parse(o["vnode_duration"])
        if "replica" in o:
            opts.replica = o["replica"]
        if "precision" in o:
            opts.precision = Precision.parse(o["precision"])
        if "config" in o:
            opts.config = dict(o["config"])
        self.meta.create_database(
            DatabaseSchema(session.tenant, stmt.name, opts), stmt.if_not_exists)
        return ResultSet.message("ok")

    def _alter_database(self, stmt: ast.AlterDatabase, session: Session):
        kw = {}
        o = stmt.options
        if "ttl" in o:
            kw["ttl"] = Duration.parse(o["ttl"])
        if "shard_num" in o:
            kw["shard_num"] = o["shard_num"]
        if "vnode_duration" in o:
            kw["vnode_duration"] = Duration.parse(o["vnode_duration"])
        if "replica" in o:
            kw["replica"] = o["replica"]
        self.meta.alter_database(session.tenant, stmt.name, **kw)
        return ResultSet.message("ok")

    def _create_table(self, stmt: ast.CreateTable, session: Session):
        db = stmt.database or session.database
        fields = []
        for f in stmt.fields:
            vt = ValueType.parse(f.type_name)
            fields.append((f.name, vt, f.codec))
        schema = TskvTableSchema.new_measurement(
            session.tenant, db, stmt.name, stmt.tags,
            [(n, vt) for n, vt, _ in fields],
            precision=self.meta.database(session.tenant, db)
            .options.precision, sort_tags=False)
        for f in stmt.fields:
            tn = f.type_name.upper()
            if tn.startswith("GEOMETRY("):
                schema.column(f.name).geom_subtype = \
                    tn[len("GEOMETRY("):].split(",")[0].strip()
        for n, _vt, codec in fields:
            if codec:
                schema.column(n).encoding = Encoding.from_str(codec)
                schema.column(n).explicit_codec = True
        self.meta.create_table(schema, stmt.if_not_exists)
        return ResultSet.message("ok")

    def _alter_table(self, stmt: ast.AlterTable, session: Session):
        db = session.database
        name = stmt.name
        if "." in name:   # ALTER TABLE db.tbl
            db, name = name.split(".", 1)
        schema = self.meta.table(session.tenant, db, name)
        if stmt.action == "add_field":
            col = schema.add_column(stmt.column.name,
                                    ColumnType.field(ValueType.parse(stmt.column.type_name)))
            if stmt.column.codec and stmt.column.codec != "DEFAULT":
                col.encoding = Encoding.from_str(stmt.column.codec)
                col.explicit_codec = True
            else:
                col.encoding = col.default_encoding()
        elif stmt.action == "add_tag":
            schema.add_column(stmt.column.name, ColumnType.tag())
        elif stmt.action == "alter_codec":
            # ALTER <col> SET CODEC: fields only (reference alter_table.slt
            # pins tag/time as errors); CODEC(DEFAULT) restores the
            # type-default rendering
            col = schema.column(stmt.column.name)
            if not col.column_type.is_field:
                raise ExecutionError(
                    "only FIELD columns take a compression codec")
            if stmt.column.codec == "DEFAULT":
                col.encoding = col.default_encoding()
                col.explicit_codec = False
            else:
                from ..models.codec import codecs_for

                enc = Encoding.from_str(stmt.column.codec)
                if enc not in codecs_for(col.column_type.value_type.name):
                    raise ExecutionError(
                        f"codec {stmt.column.codec} does not apply to "
                        f"{col.column_type.value_type.name}")
                col.encoding = enc
                col.explicit_codec = True
            schema.schema_version += 1
        elif stmt.action == "rename":
            # RENAME COLUMN old TO new (reference rename_field/tag.slt:
            # time never renames; target must be free) — invariants live
            # in TskvTableSchema.rename_column; buffered rows re-key so
            # they follow the column like id-resolved TSM chunks do
            col = schema.rename_column(stmt.drop_name, stmt.rename_to)
            owner = f"{session.tenant}.{db}"
            if col.column_type.is_field:
                for v in self.coord.engine.local_vnodes(owner):
                    v.rename_mem_field(name, stmt.drop_name,
                                       stmt.rename_to)
            elif col.column_type.is_tag:
                # tag values live in index series keys, which carry tag
                # NAMES — rewrite them so historic series follow the
                # column (same WAL-logged machinery as tag UPDATE)
                from ..models.series import SeriesKey

                for v in self.coord.engine.local_vnodes(owner):
                    old_keys, new_keys = [], []
                    for sid in v.index.table_series_ids(name):
                        k = v.index.get_series_key(int(sid))
                        if k is None or k.tag_value(stmt.drop_name) is None:
                            continue
                        tags = {(stmt.rename_to if tk == stmt.drop_name
                                 else tk): tv
                                for tk, tv in k.tag_dict().items()}
                        old_keys.append(k)
                        new_keys.append(SeriesKey(name, tags))
                    if old_keys:
                        v.update_tags(name, old_keys, new_keys)
        elif stmt.action == "drop":
            tgt = schema.column(stmt.drop_name)
            if tgt is not None and tgt.column_type.is_field:
                n_fields = sum(1 for c in schema.columns
                               if c.column_type.is_field)
                if n_fields <= 1:
                    # a table must keep at least one field
                    # (alter_table.slt pins DROP of the only field)
                    raise ExecutionError(
                        "cannot drop the only field column")
            if tgt is not None and tgt.column_type.is_tag:
                # the reference's ALTER TABLE DROP never removes TAG
                # columns (create_table.slt pins DROP column7 on a
                # two-tag table as an error)
                raise ExecutionError("cannot drop a tag column")
            dropped = schema.drop_column(stmt.drop_name)
            if dropped.column_type.is_field:
                owner = f"{session.tenant}.{db}"
                for v in self.coord.engine.local_vnodes(owner):
                    v.drop_mem_field(name, stmt.drop_name)
        self.meta.update_table(schema)
        self._serving_invalidate(session.tenant, db, name)
        return ResultSet.message("ok")

    # ------------------------------------------------------------------ SHOW
    def _show(self, stmt: ast.ShowStmt, session: Session):
        if stmt.kind == "databases":
            names = self.meta.list_databases(session.tenant)
            return ResultSet(["database_name"], [np.array(names, dtype=object)])
        if stmt.kind == "tables":
            db = stmt.on_database or session.database
            names = self.meta.list_tables(session.tenant, db)
            return ResultSet(["table_name"], [np.array(names, dtype=object)])
        if stmt.kind == "tag_values":
            # (key, value) rows per the reference
            # (planner.rs:2819 show_tag_value_projections)
            db = stmt.on_database or session.database
            schema = self.meta.table(session.tenant, db, stmt.table)
            if stmt.where is not None:
                bad = stmt.where.columns() - set(schema.tag_names()) \
                    - {"time"}
                if bad:
                    raise PlanError(
                        f"SHOW TAG VALUES WHERE supports tag/time "
                        f"predicates only, got {sorted(bad)}")
            for name, _asc in stmt.order_by:
                if name not in ("key", "value"):
                    raise PlanError(
                        f"SHOW TAG VALUES can only ORDER BY key/value, "
                        f"got {name!r}")
            tags = schema.tag_names()
            op, names = stmt.tag_with or ("eq", [stmt.tag_key])
            keys = {"eq": [t for t in tags if t in names],
                    "ne": [t for t in tags if t not in names],
                    "in": [t for t in tags if t in names],
                    "notin": [t for t in tags if t not in names]}[op]
            pairs: set[tuple] = set()
            if stmt.where is not None:
                # derive values from the WHERE-surviving series only
                skeys = self._filtered_series(session.tenant, db,
                                              stmt.table, stmt.where)
                for k in skeys:
                    for key in keys:
                        v = k.tag_value(key)
                        if v is not None:
                            pairs.add((key, v))
            else:
                for key in keys:
                    for v in self.coord.tag_values(
                            session.tenant, db, stmt.table, key):
                        pairs.add((key, v))
            rows = sorted(pairs)
            for name, asc in reversed(stmt.order_by):
                idx = 0 if name == "key" else 1
                rows.sort(key=lambda r: r[idx], reverse=not asc)
            if stmt.offset:
                rows = rows[stmt.offset:]
            if stmt.limit is not None:
                rows = rows[:stmt.limit]
            return ResultSet(["key", "value"],
                             [np.array([r[0] for r in rows], dtype=object),
                              np.array([r[1] for r in rows], dtype=object)])
        if stmt.kind == "tag_keys":
            schema = self.meta.table(session.tenant, session.database, stmt.table)
            return ResultSet(["tag_key"],
                             [np.array(schema.tag_names(), dtype=object)])
        if stmt.kind == "series":
            db = stmt.on_database or session.database
            if stmt.where is not None:
                keys = self._filtered_series(session.tenant, db,
                                             stmt.table, stmt.where)
            else:
                keys = self.coord.series_keys(session.tenant, db,
                                              stmt.table)
            reprs = [repr(k) for k in keys]
            for name, asc in reversed(stmt.order_by):
                if name != "key":
                    raise PlanError(
                        f"SHOW SERIES can only ORDER BY key, got {name!r}")
                reprs.sort(reverse=not asc)
            if stmt.offset:
                reprs = reprs[stmt.offset:]
            if stmt.limit is not None:
                reprs = reprs[:stmt.limit]
            return ResultSet(["key"], [np.array(reprs, dtype=object)])
        if stmt.kind == "queries":
            import time as _t

            ids, texts, users, durs = [], [], [], []
            for qid, q in self.tracker.snapshot():
                ids.append(qid)
                texts.append(q["sql"][:200])
                users.append(q["user"])
                durs.append(round(_t.time() - q["start"], 3))
            return ResultSet(
                ["query_id", "query_text", "user_name", "duration"],
                [np.array(ids, dtype=np.int64),
                 np.array(texts, dtype=object),
                 np.array(users, dtype=object),
                 np.array(durs)])
        if stmt.kind == "backups":
            entries = []
            for db in self.meta.list_databases(session.tenant):
                entries.extend(
                    self.meta.list_backups(f"{session.tenant}.{db}"))
            entries.sort(key=lambda e: e["created_ts"])
            import datetime as _dt

            created = [_dt.datetime.fromtimestamp(
                e["created_ts"], _dt.timezone.utc).isoformat()
                for e in entries]
            return ResultSet(
                ["backup_id", "database", "incremental", "created_at",
                 "vnodes", "objects_uploaded", "objects_reused", "bytes"],
                [np.array([e["id"] for e in entries], dtype=object),
                 np.array([e["owner"].split(".", 1)[1] for e in entries],
                          dtype=object),
                 np.array([bool(e["incremental"]) for e in entries],
                          dtype=bool),
                 np.array(created, dtype=object),
                 np.array([e["vnodes"] for e in entries], dtype=np.int64),
                 np.array([e["objects_uploaded"] for e in entries],
                          dtype=np.int64),
                 np.array([e["objects_reused"] for e in entries],
                          dtype=np.int64),
                 np.array([e["bytes"] for e in entries], dtype=np.int64)])
        if stmt.kind == "streams":
            se = self.stream_engine()
            names = sorted(se.streams)
            return ResultSet(
                ["stream_name", "target", "interval_s", "query"],
                [np.array(names, dtype=object),
                 np.array([se.streams[n].sink[1] if isinstance(se.streams[n].sink, tuple)
                           else "<callback>" for n in names], dtype=object),
                 np.array([se.streams[n].interval_s for n in names]),
                 np.array([se.streams[n].sql[:120] for n in names], dtype=object)])
        if stmt.kind == "matviews":
            me = self.matview_engine()
            me.sync_from_meta()
            names = sorted(me.views)
            views = [me.views[n] for n in names]
            return ResultSet(
                ["view_name", "table", "delay_ns", "query"],
                [np.array(names, dtype=object),
                 np.array([v.table for v in views], dtype=object),
                 np.array([v.delay_ns for v in views], dtype=np.int64),
                 np.array([v.select_sql[:120] for v in views],
                          dtype=object)])
        if stmt.kind == "roles":
            roles = self.meta.list_roles(session.tenant)
            names = sorted(roles)
            return ResultSet(
                ["role_name", "inherit", "privileges"],
                [np.array(names, dtype=object),
                 np.array([roles[n].get("inherit", "") for n in names],
                          dtype=object),
                 np.array([", ".join(f"{db}:{lv}" for db, lv in
                                     sorted(roles[n].get("privileges", {})
                                            .items()))
                           for n in names], dtype=object)])
        if stmt.kind == "users":
            users = sorted(self.meta.users)
            return ResultSet(
                ["user_name", "is_admin"],
                [np.array(users, dtype=object),
                 np.array([bool(self.meta.users[u].get("admin"))
                           for u in users])])
        raise ExecutionError(f"unsupported SHOW {stmt.kind}")

    def _filtered_series(self, tenant: str, db: str, table: str, where):
        """Series keys surviving a SHOW SERIES / SHOW TAG VALUES WHERE:
        tag predicates evaluate against the series keys, a `time`
        conjunct against each series' data extent (reference
        ShowTagBody.selection); field predicates are rejected."""
        keys = self.coord.series_keys(tenant, db, table)
        schema = self.meta.table(tenant, db, table)
        tag_names = set(schema.tag_names())
        bad = where.columns() - tag_names - {"time"}
        if bad:
            raise PlanError(
                f"SHOW ... WHERE supports tag/time predicates only, "
                f"got {sorted(bad)}")
        n = len(keys)
        env: dict = {}
        for c in where.columns() - {"time"}:
            env[c] = np.array([k.tag_value(c) for k in keys], dtype=object)
            env[f"__valid__:{c}"] = np.array(
                [k.tag_value(c) is not None for k in keys], dtype=bool)
        if "time" in where.columns():
            from .planner import split_where

            trs, _doms, _res = split_where(where, schema)
            mask = self._series_in_time(tenant, db, table, keys, trs)
            tag_only = _strip_time_conjuncts(where)
            if tag_only is not None:
                m2 = np.asarray(tag_only.eval(env, np), dtype=bool)
                if m2.shape == ():
                    m2 = np.full(n, bool(m2))
                mask = mask & m2
        else:
            mask = np.asarray(where.eval(env, np), dtype=bool)
            if mask.shape == ():
                mask = np.full(n, bool(mask))
        return [k for k, m in zip(keys, mask) if m]

    def _series_in_time(self, tenant: str, db: str, table: str, keys,
                        trs) -> np.ndarray:
        """Mask of series with ≥1 point inside the time ranges (reference
        SHOW SERIES scans; `WHERE time < now()` keeps live series)."""
        present = set()
        for b in self.coord.scan_table(tenant, db, table, time_ranges=trs):
            for k in b.series_keys:
                if k is not None and b.n_rows:
                    present.add(repr(k))
        return np.array([repr(k) in present for k in keys], dtype=bool)

    def _describe(self, stmt: ast.DescribeStmt, session: Session):
        if stmt.kind == "database":
            d = self.meta.database(session.tenant, stmt.name)
            o = d.options
            # reference row (describe_database.slt):
            # ttl, shard, vnode_duration, replica, precision, then the
            # storage-config constants the reference surfaces per-db
            return ResultSet(
                ["ttl", "shard", "vnode_duration", "replica", "precision",
                 "max_memcache_size", "memcache_partitions",
                 "wal_max_file_size", "wal_sync", "strict_write",
                 "max_cache_readers"],
                [np.array([o.ttl.humantime()], dtype=object),
                 np.array([o.shard_num]),
                 np.array([o.vnode_duration.humantime()], dtype=object),
                 np.array([o.replica]),
                 np.array([o.precision.name], dtype=object),
                 np.array([_size_display(o.config.get(
                     "max_memcache_size", "128 MiB"))], dtype=object),
                 np.array([o.config.get("memcache_partitions", 16)]),
                 np.array([_size_display(o.config.get(
                     "wal_max_file_size", "128 MiB"))], dtype=object),
                 np.array([bool(o.config.get("wal_sync", False))]),
                 np.array([bool(o.config.get("strict_write", False))]),
                 np.array([o.config.get("max_cache_readers", 32)])])
        ext = self.meta.external_opt(
            session.tenant, stmt.database or session.database, stmt.name)
        if ext is not None:
            # external tables DESCRIBE with arrow type names and no
            # codec (create_external_table.slt: "Decimal128(10, 6)")
            names = [c[0] for c in ext.get("columns") or []]
            types = [_arrow_type_name(c[1])
                     for c in ext.get("columns") or []]
            return ResultSet(
                ["column_name", "data_type", "column_type",
                 "compression_codec"],
                [np.array(names, dtype=object),
                 np.array(types, dtype=object),
                 np.array(["FIELD"] * len(names), dtype=object),
                 np.array([None] * len(names), dtype=object)])
        schema = self.meta.table(session.tenant,
                                 stmt.database or session.database, stmt.name)
        names, types, kinds, codecs = [], [], [], []
        for c in schema.columns:
            names.append(c.name)
            ct = c.column_type
            if ct.is_time:
                types.append("TIMESTAMP("
                             + {"NS": "NANOSECOND", "US": "MICROSECOND",
                                "MS": "MILLISECOND"}[ct.precision.name]
                             + ")")
                kinds.append("TIME")
            elif ct.is_tag:
                types.append("STRING")
                kinds.append("TAG")
            else:
                types.append(ct.value_type.sql_name())
                kinds.append("FIELD")
            codecs.append(None if c.encoding.name == "NULL"
                          else (c.encoding.name if c.explicit_codec
                                else "DEFAULT"))
        return ResultSet(
            ["column_name", "data_type", "column_type", "compression_codec"],
            [np.array(x, dtype=object) for x in (names, types, kinds, codecs)])

    # ------------------------------------------------------------------ DML
    def _insert(self, stmt: ast.InsertStmt, session: Session):
        db = stmt.database or session.database
        schema = self.meta.table(session.tenant, db, stmt.table)
        cols = stmt.columns or [c.name for c in schema.columns]
        # unquoted SQL identifiers are case-insensitive: fold each column
        # to its schema-cased name (`TIME` → `time`; reference cases
        # write INSERT tbl(TIME, ...))
        by_lower = {c.name.lower(): c.name for c in schema.columns}
        cols = [by_lower.get(c.lower(), c) if not schema.contains_column(c)
                else c for c in cols]
        implicit_time = "time" not in cols
        if implicit_time:
            # reference fills now() when the time column is omitted
            # (math_function/random.slt inserts VALUES (random()), …);
            # one timestamp per statement — rows collide on identical
            # series keys exactly as upstream
            cols = list(cols) + ["time"]
        # SQL INSERT is schema-strict (the schemaless path is line
        # protocol); unknown columns are an error, not an auto-evolution
        unknown = [c for c in cols
                   if c != "time" and not schema.contains_column(c)]
        if unknown:
            raise ExecutionError(
                f"unknown column(s) {unknown} in INSERT INTO {stmt.table}")
        tag_names = [c for c in cols if schema.contains_column(c)
                     and schema.column(c).column_type.is_tag]
        field_types = {c: schema.column(c).column_type.value_type
                       for c in cols if schema.contains_column(c)
                       and schema.column(c).column_type.is_field}
        prec_factor = self.meta.database(
            session.tenant, db).options.precision.to_ns_factor()
        scale_time = (prec_factor != 1 and stmt.select is None
                      and not implicit_time)
        src_rows = stmt.rows
        if stmt.select is not None:
            # INSERT ... SELECT: run the query, map columns positionally
            # (reference: insert_select.slt — SELECT from VALUES etc.)
            rsel = self.execute_statement(stmt.select, session)
            if len(rsel.names) != len(cols):
                raise ExecutionError(
                    f"INSERT SELECT arity mismatch: {len(cols)} target "
                    f"column(s), query yields {len(rsel.names)}")
            src_rows = [
                [None if (isinstance(v, float) and v != v) else
                 (v.item() if isinstance(v, np.generic) else v)
                 for v in row]
                for row in zip(*[c.tolist() if hasattr(c, "tolist") else c
                                 for c in rsel.columns])]
        if implicit_time:
            import time as _time

            now_ns = int(_time.time() * 1e9)
            src_rows = [list(r) + [now_ns] for r in src_rows]
        if stmt.select is None and len(src_rows) > 1:
            # DataFusion types the VALUES list itself: mixing literal
            # classes in one column position is an error before any
            # schema coercion ("Inconsistent data type across values
            # list" — sqlancer/function.slt)
            for j in range(len(cols)):
                seen_cls = None
                for i, r in enumerate(src_rows):
                    v = r[j] if j < len(r) else None
                    if v is None:
                        continue
                    cls = (bool if isinstance(v, bool) else
                           int if isinstance(v, int) else
                           float if isinstance(v, float) else
                           str if isinstance(v, str) else type(v))
                    if seen_cls is None:
                        seen_cls = cls
                    elif cls is not seen_cls:
                        raise ExecutionError(
                            f"Inconsistent data type across values list "
                            f"at row {i} column {j}")
        rows = []
        for raw in src_rows:
            if len(raw) != len(cols):
                raise ExecutionError("INSERT row arity mismatch")
            row = dict(zip(cols, raw))
            t = row["time"]
            if isinstance(t, str):
                from .parser import parse_timestamp_string

                row["time"] = parse_timestamp_string(t)
            elif isinstance(t, float):
                # a fractional time literal is a type error
                # (create_table.slt pins VALUES (0.1, ...))
                raise ExecutionError(
                    f"INSERT time must be an integer timestamp, got {t!r}")
            if row["time"] is None:
                raise ExecutionError("INSERT time must not be NULL")
            if scale_time and not isinstance(t, str):
                # EXPLICIT integer time literals are interpreted in the
                # DATABASE's precision (db_precision.slt); implicit-now
                # and INSERT..SELECT times are already ns and never scale
                scaled = int(row["time"]) * prec_factor
                if abs(scaled) > 2**63 - 1:
                    raise ExecutionError(
                        "timestamp overflows the ns domain at this "
                        "database's precision")
                row["time"] = scaled
            # a point with no field value is unrepresentable (same rule as
            # line protocol; reference rejects all-NULL-field INSERT rows)
            if not any(row.get(c) is not None for c in field_types):
                raise ExecutionError(
                    "INSERT row has no non-NULL field value")
            for c, vt in field_types.items():
                v = row.get(c)
                if v is not None:
                    row[c] = _insert_coerce(vt, v, c)
            for c in field_types:
                sub = schema.column(c).geom_subtype \
                    if schema.contains_column(c) else None
                v = row.get(c)
                if sub and v is not None:
                    from .gis import parse_wkt

                    g = parse_wkt(str(v))
                    if g.kind != sub:
                        raise ExecutionError(
                            f"geometry column {c!r} expects {sub}, got "
                            f"{g.kind}")
            rows.append(row)
        wb = WriteBatch.from_rows(stmt.table, rows, tag_names, field_types)
        self.coord.write_points(session.tenant, db, wb)
        return ResultSet(["rows"], [np.array([len(rows)])])

    def _delete(self, stmt: ast.DeleteStmt, session: Session):
        schema = self.meta.table(session.tenant,
                                 stmt.database or session.database,
                                 stmt.table)
        from .planner import split_where

        trs, tag_domains, residual = split_where(stmt.where, schema)
        if residual is not None:
            # reference: non-constant expressions in a DELETE predicate
            # are unimplemented ("operator || in delete statement" —
            # cases/dml/delete.slt); only direct tag/time comparisons
            from .expr import Func as _Func
            from .expr import iter_child_exprs

            def _no_funcs(e):
                if isinstance(e, _Func):
                    raise ExecutionError(
                        f"function {e.name}() in a DELETE predicate is "
                        "not supported")
                for c in iter_child_exprs(e):
                    _no_funcs(c)
            _no_funcs(residual)
            dom_cols = set(tag_domains.domains) if not tag_domains.is_all else set()
            extra = residual.columns() - dom_cols - set(schema.tag_names())
            if extra:
                raise ExecutionError(
                    f"DELETE supports time/tag predicates only, got {sorted(extra)}")
        lo = trs.min_ts if not trs.is_all else -(2**63)
        hi = trs.max_ts if not trs.is_all else 2**63 - 1
        self.coord.delete_from_table(session.tenant,
                                     stmt.database or session.database,
                                     stmt.table, tag_domains, lo, hi)
        self._serving_invalidate(session.tenant,
                                 stmt.database or session.database,
                                 stmt.table)
        return ResultSet.message("ok")

    def _update(self, stmt: ast.UpdateStmt, session: Session):
        db = stmt.database or session.database
        schema = self.meta.table(session.tenant, db, stmt.table)
        tag_names = set(schema.tag_names())
        assigned = set(stmt.assignments)
        if "time" in assigned:
            raise ExecutionError("UPDATE cannot assign the time column")
        if stmt.where is None:
            raise ExecutionError(
                "updating the entire table is disabled; add `where true` "
                "to continue")
        if assigned <= set(schema.field_names()):
            return self._update_fields(stmt, schema, session, db)
        if not assigned <= tag_names:
            raise ExecutionError(
                "UPDATE assigns either tag columns or field columns, "
                "not a mix")
        bad = stmt.where.columns() - tag_names
        if bad:
            # tag UPDATE rewrites whole series; a time/field condition
            # would need per-row splits (reference: "Where clause cannot
            # contain field/time column")
            raise ExecutionError(
                f"tag UPDATE WHERE cannot reference field/time columns, "
                f"found: {sorted(bad)}")
        from .planner import split_where

        _, tag_domains, _ = split_where(stmt.where, schema)
        new_vals = {}
        for k, e in stmt.assignments.items():
            if not isinstance(e, Literal):
                raise ExecutionError("UPDATE tag values must be literals")
            # NULL removes the tag from the series key; the reference
            # allows it as long as ≥1 tag remains (update_tag.slt: both
            # tags → error, one of two → ok)
            v = e.value
            if isinstance(v, bool):
                v = "true" if v else "false"   # SQL bool rendering
            new_vals[k] = None if v is None else str(v)
        owner = f"{session.tenant}.{db}"
        from ..models.series import SeriesKey, Tag

        count = 0
        for v in self.coord.engine.local_vnodes(owner):
            sids = v.index.get_series_ids_by_domains(stmt.table, tag_domains)
            old_keys, new_keys = [], []
            for sid in sids:
                k = v.index.get_series_key(int(sid))
                if k is None:
                    continue
                tags = k.tag_dict()
                tags.update(new_vals)
                tags = {tk: tv for tk, tv in tags.items() if tv is not None}
                if not tags:
                    raise ExecutionError(
                        "UPDATE would leave a series with no tags")
                old_keys.append(k)
                new_keys.append(SeriesKey(stmt.table, tags))
            if old_keys:
                v.update_tags(stmt.table, old_keys, new_keys)
                count += len(old_keys)
        return ResultSet(["series_updated"], [np.array([count])])

    def _update_fields(self, stmt: ast.UpdateStmt, schema, session, db):
        """UPDATE of FIELD columns: scan the matching rows, evaluate the
        assignment expressions over them, write the assigned fields back
        at the same (series, time) — the LSM read path is last-write-wins
        per field, so unassigned fields keep their old values (reference
        dml update_field.slt semantics)."""
        tag_names = schema.tag_names()
        needed: set[str] = set()
        for e in stmt.assignments.values():
            if isinstance(e, Expr):
                needed |= e.columns()
        unknown = needed - set(schema.field_names()) - set(tag_names) \
            - {"time"}
        if unknown:
            raise ExecutionError(
                f"UPDATE expression references unknown column(s) "
                f"{sorted(unknown)}")
        items = [ast.SelectItem(Column("time"), None)]
        for t in tag_names:
            items.append(ast.SelectItem(Column(t), None))
        for c in sorted(needed - {"time"} - set(tag_names)):
            items.append(ast.SelectItem(Column(c), None))
        sel = ast.SelectStmt(items=items, table=stmt.table,
                             where=stmt.where, database=db)
        rs = self._select(sel, session)
        n = rs.n_rows
        if n == 0:
            return ResultSet(["count"], [np.array([0], dtype=np.int64)])
        env = {nm: col for nm, col in zip(rs.names, rs.columns)}
        rows: list[dict] = []
        for i in range(n):
            row: dict = {"time": int(env["time"][i])}
            for t in tag_names:
                v = env[t][i]
                if v is not None:
                    row[t] = v
            rows.append(row)
        field_types = {}
        for fname, e in stmt.assignments.items():
            field_types[fname] = schema.column(fname).column_type.value_type
            vals = e.eval(env, np) if isinstance(e, Expr) else e
            if np.isscalar(vals) or vals is None \
                    or getattr(vals, "shape", None) == ():
                vals = [vals] * n
            for i, row in enumerate(rows):
                v = vals[i]
                if isinstance(v, np.generic):
                    v = v.item()
                if isinstance(v, float) and v != v:
                    v = None
                row[fname] = v
        wb = WriteBatch.from_rows(stmt.table, rows,
                                  [t for t in tag_names], field_types)
        self.coord.write_points(session.tenant, db, wb)
        return ResultSet(["count"], [np.array([n], dtype=np.int64)])

    # ------------------------------------------------------------------ SELECT
    def _explain(self, stmt: ast.ExplainStmt, session: Session):
        if isinstance(stmt.inner, ast.CopyStmt):
            src_txt = stmt.inner.source if isinstance(stmt.inner.source,
                                                      str) else "<query>"
            return ResultSet.message(
                f"CopyExec target={stmt.inner.target} source={src_txt} "
                f"format={stmt.inner.fmt}")
        if isinstance(stmt.inner, ast.InsertStmt) \
                and stmt.inner.select is not None:
            return ResultSet.message(
                f"InsertExec table={stmt.inner.table} source=<query>")
        if not isinstance(stmt.inner, ast.SelectStmt):
            raise ExecutionError("EXPLAIN supports SELECT only")
        sel = stmt.inner
        if sel.table is None:
            return ResultSet.message("Projection (no table)")
        tbl, db = sel.table, sel.database or session.database
        st = None
        if sel.database is None and self.meta.table_opt(
                session.tenant, db, tbl) is None:
            st = self.meta.stream_table(session.tenant, db, tbl)
        if st is not None:
            tbl, db = st["table"], st["db"]
        schema = self.meta.table(session.tenant, db, tbl)
        try:
            plan = plan_select(sel, schema)
        except CnosError as e:
            # EXPLAIN (no execution) tolerates only DEFERRED-to-runtime
            # value errors, the way the reference does (DataFusion defers
            # `time >= 'xxx'` casts to execution); schema/semantic errors
            # still raise
            if stmt.analyze or "bad timestamp" not in str(e):
                raise
            return ResultSet.message(f"PlanningError (deferred): {e}")
        lines = []
        if stmt.analyze:
            import time as _t

            db = sel.database or session.database
            # the inner query runs inside its OWN profile so the rendered
            # breakdown covers exactly this execution; it then folds into
            # any ambient profile (the enclosing statement's) so the
            # stages aren't lost to the outer scope
            prof = stages.QueryProfile(
                node_id=getattr(self.coord, "node_id", None),
                sql=sel.to_sql() if hasattr(sel, "to_sql") else None)
            t0 = _t.perf_counter()
            # execute the SAME plan object that gets printed below
            with stages.profile_scope(prof):
                if isinstance(plan, AggregatePlan):
                    rs = self._exec_aggregate(plan, session.tenant, db)
                else:
                    rs = self._exec_raw(plan, session.tenant, db)
            elapsed = (_t.perf_counter() - t0) * 1e3
            prof.finish(wall_ms=elapsed)
            outer = stages.current_profile()
            if outer is not None:
                outer.merge_child(prof)
            lines.append(f"Execution: {rs.n_rows} rows in {elapsed:.2f}ms")
            # per-stage, per-node breakdown (the reference's DataFusion
            # EXPLAIN ANALYZE metrics rows, merged across the cluster)
            for node, cell in sorted(prof.node_stages().items()):
                for name, value in sorted(cell.items()):
                    lines.append(f"stage node={node} name={name} "
                                 f"value={value}")
            for k, v in sorted(prof.device.items()):
                lines.append(f"device {k}={v}")
        if isinstance(plan, AggregatePlan):
            lines.append("TpuAggregateExec")
            lines.append(f"  table={plan.table}")
            lines.append(f"  time_ranges={plan.time_ranges!r}")
            lines.append(f"  tag_domains={plan.tag_domains!r}")
            lines.append(f"  filter={plan.filter.to_sql() if plan.filter else None}")
            lines.append(f"  group_tags={plan.group_tags}"
                         + (f" group_fields={plan.group_fields}"
                            if plan.group_fields else "")
                         + f" bucket={plan.bucket}")
            lines.append(f"  partial_aggs={[(a.func, a.column) for a in plan.aggs]}")
        else:
            lines.append("TpuScanExec")
            lines.append(f"  table={plan.table}")
            lines.append(f"  time_ranges={plan.time_ranges!r}")
            lines.append(f"  filter={plan.filter.to_sql() if plan.filter else None}")
            lines.append(f"  projection={[n for n, _ in plan.output]}")
        return ResultSet(["plan"], [np.array(lines, dtype=object)])

    def _select(self, stmt: ast.SelectStmt, session: Session):
        from .analyzer import analyze

        # consume-once serving-plane handoff: non-None only for the OUTER
        # statement of a serving-instrumented request — subquery
        # resolution re-enters _select and must stay invisible to the
        # plan/result caches
        sv_state = self.serving.claim() if self.serving is not None \
            else None
        stmt = self._fold_session_scalars(stmt, session)
        stmt = analyze(self._resolve_subqueries(stmt, session))
        if stmt.from_item is not None or self._needs_relational(stmt):
            return self._select_relational(stmt, session)
        if stmt.table is not None:
            stmt = self._strip_table_qualifiers(stmt)
        if stmt.table is None:
            # constant SELECT (SELECT 1)
            from .planner import validate_scalar_sigs_env

            names, cols = [], []
            for i, it in enumerate(stmt.items):
                validate_scalar_sigs_env(it.expr, {})
                v = self._const_aggregate(it.expr) \
                    if self._is_const_agg(it.expr) else it.expr.eval({}, np)
                names.append(it.alias or it.expr.to_sql())
                if isinstance(v, (bytes, bytearray)) or v is None:
                    c = np.empty(1, dtype=object)   # numpy 'S' dtype
                    c[0] = v                        # truncates NUL bytes
                    cols.append(c)
                else:
                    cols.append(np.array([v]))
            return ResultSet(names, cols)
        table = stmt.table
        db = stmt.database or session.database
        st = None
        if stmt.database is None and self.meta.table_opt(
                session.tenant, db, table) is None:
            st = self.meta.stream_table(session.tenant, db, table)
        if st is not None:
            # a stream table reads through to its bound tskv table
            # (reference stream table provider over the base scan); the
            # plan must carry the bound name — the scan reads plan.table
            import dataclasses

            table, db = st["table"], st["db"]
            stmt = dataclasses.replace(stmt, table=table, database=db)
        from .system_tables import is_system_db_for, system_table

        if db == "usage_schema" and table in self.meta.tables.get(
                "cnosdb.usage_schema", {}):
            # usage_schema is a REAL database under the system tenant
            # (metric tables + user tables); other tenants read it as a
            # view filtered to their own rows
            # (usage_schema_privilege.slt, coord_metrics.slt)
            if session.tenant != "cnosdb":
                import dataclasses

                from .expr import BinOp

                tagf = BinOp("=", Column("tenant"),
                             Literal(session.tenant))
                stmt = dataclasses.replace(
                    stmt, where=(tagf if stmt.where is None
                                 else BinOp("and", stmt.where, tagf)))
                session = Session(tenant="cnosdb",
                                  database=session.database,
                                  user=session.user)
        elif is_system_db_for(db, session):
            names, cols = system_table(self, db, table, session)
            has_agg = stmt.group_by or any(
                rel.collect_aggs(it.expr, AGG_FUNCS)
                for it in stmt.items if isinstance(it.expr, Expr))
            if has_agg:
                scope = rel.Scope(names, cols)
                if stmt.where is not None:
                    m = np.asarray(stmt.where.eval(scope.env, np))
                    if not m.shape:
                        m = np.full(scope.n, bool(m))
                    scope = scope.filter(m)
                import dataclasses as _dc

                inner = _dc.replace(stmt, where=None)
                rs, env, order_by = self._host_group_aggregate(inner,
                                                               scope)
                rs = _order_limit(rs, order_by, stmt.limit, stmt.offset,
                                  env)
                return self._distinct(rs) if stmt.distinct else rs
            return self._select_over_env(stmt, names, cols)
        if self.meta.external_opt(session.tenant, db, table) is not None:
            # relational pipeline: aggregates/joins/windows all work over
            # the materialized file (handled in _materialize_from)
            return self._select_relational(stmt, session)
        if (len(stmt.items) == 1 and isinstance(stmt.items[0].expr, Func)
                and stmt.items[0].expr.name.lower() in _REPAIR_FUNCS):
            return self._ts_gen_func(stmt, session)
        schema = self.meta.table(session.tenant, db, table)
        try:
            plan = plan_select(stmt, schema)
            if sv_state is not None:
                self.serving.observe_plan(sv_state, stmt, plan, session,
                                          db, table, schema)
            if isinstance(plan, AggregatePlan):
                return self._exec_aggregate(plan, session.tenant, db)
            return self._exec_raw(plan, session.tenant, db)
        except PlanError as e:
            if getattr(e, "fallback_relational", False):
                # e.g. GROUP BY on a field column the segment kernels
                # can't key (non-string field, cardinality blow-up): the
                # relational pipeline groups by arbitrary expressions
                return self._select_relational(stmt, session)
            raise

    def _ts_gen_func(self, stmt: ast.SelectStmt, session: Session):
        """Row-set-valued data repair (reference ts_gen_func/data_repair/:
        timestamp_repair/value_fill/value_repair run as a dedicated exec
        node over the scanned series; here a raw time-ordered scan feeds
        the numpy implementations in sql.tsfuncs).

        Form: SELECT <fn>(time, value[, 'k=v,k=v']) FROM t [WHERE ...]"""
        from . import tsfuncs

        f = stmt.items[0].expr
        name = f.name.lower()
        if stmt.group_by or stmt.having is not None or stmt.distinct:
            raise PlanError(
                f"{name} does not support GROUP BY/HAVING/DISTINCT — "
                "restrict the series with WHERE instead")
        args = list(f.args)
        opts: dict[str, str] = {}
        if args and isinstance(args[-1], Literal) \
                and isinstance(args[-1].value, str):
            # urlencoded-style 'k=v&k=v' (the reference deserializes the
            # option string with deny_unknown_fields: unknown or repeated
            # fields are execution errors); ',' is accepted as a
            # separator alias
            allowed = {"timestamp_repair": {"method", "interval",
                                            "start_mode"},
                       "value_fill": {"method"},
                       "value_repair": {"method", "min_speed", "max_speed",
                                        "center", "sigma"}}[name]
            raw = args.pop().value
            for kv in re.split(r"[&,]", raw):
                kv = kv.strip()
                if not kv:
                    continue
                k, eq, v = kv.partition("=")
                k = k.strip()
                if not eq or k not in allowed:
                    raise PlanError(
                        f"Fail to parse argument: unknown field `{k}`, "
                        f"expected one of "
                        f"{', '.join(sorted(allowed))}")
                if k in opts:
                    raise PlanError(
                        f"Fail to parse argument: duplicate field `{k}`")
                opts[k] = v.strip()
        if len(args) != 2 or not isinstance(args[1], Column):
            raise PlanError(f"{name}(time, value[, 'options']) expected")
        value_col = args[1].name
        base = ast.SelectStmt(
            items=[ast.SelectItem(Column("time")),
                   ast.SelectItem(Column(value_col))],
            table=stmt.table, where=stmt.where, database=stmt.database,
            order_by=[(Column("time"), True)])
        rs = self._select(base, session)
        ts = rs.columns[0].astype(np.int64)
        vals = rs.columns[1].astype(np.float64)

        def _method(valid: set, default: str | None) -> str | None:
            m = opts.get("method", default)
            if m is not None and m.lower() not in valid:
                raise PlanError(f"Invalid method: {m}")
            return m.lower() if m is not None else None

        if name == "timestamp_repair":
            start_mode = opts.get("start_mode")
            if start_mode is not None \
                    and start_mode.lower() not in ("linear", "mode"):
                raise PlanError(f"Invalid start_mode: {start_mode}")
            try:
                interval = int(opts["interval"]) if "interval" in opts \
                    else None
            except ValueError as e:
                raise PlanError(f"Fail to parse argument: {e}")
            # an explicit interval takes precedence and method is then
            # never even validated (timestamp_repair.rs:70-85 checks
            # arg.interval first)
            method = None if interval is not None \
                else _method({"median", "mode", "cluster"}, None)
            new_ts, new_vals = tsfuncs.timestamp_repair(
                ts, vals, method=method, interval=interval,
                start_mode=start_mode.lower() if start_mode else None)
        elif name == "value_fill":
            new_ts = ts
            new_vals = tsfuncs.value_fill(
                ts, vals,
                method=_method({"mean", "previous", "linear", "ar", "ma"},
                               "linear"))
        else:
            new_ts = ts

            def fopt(k):
                try:
                    return float(opts[k]) if k in opts else None
                except ValueError as e:
                    raise PlanError(f"Fail to parse argument: {e}")

            new_vals = tsfuncs.value_repair(
                ts, vals,
                method=_method({"screen", "lsgreedy"}, "screen"),
                min_speed=fopt("min_speed"), max_speed=fopt("max_speed"),
                center=fopt("center"), sigma=fopt("sigma"))
        alias = stmt.items[0].alias or value_col
        out = ResultSet(["time", alias], [new_ts, new_vals])
        env = {"time": new_ts, alias: new_vals, value_col: new_vals}
        return _order_limit(out, stmt.order_by, stmt.limit, stmt.offset, env)

    def _vnode_admin(self, stmt: ast.VnodeAdmin) -> ResultSet:
        """Vnode/replica elasticity ops (reference ast.rs:56-73 +
        raft/manager.rs:323-566)."""
        if stmt.op == "move":
            self.coord.move_vnode(stmt.vnode_id, stmt.node_id)
            return ResultSet.message("ok")
        if stmt.op == "copy":
            new_id = self.coord.copy_vnode(stmt.vnode_id, stmt.node_id)
            return ResultSet(["new_vnode_id"],
                             [np.array([new_id], dtype=np.int64)])
        if stmt.op == "compact":
            self.coord.compact_vnode(stmt.vnode_id)
            return ResultSet.message("ok")
        if stmt.op == "replica_add":
            new_id = self.coord.copy_vnode_to_set(stmt.replica_set_id,
                                                  stmt.node_id)
            return ResultSet(["new_vnode_id"],
                             [np.array([new_id], dtype=np.int64)])
        if stmt.op == "replica_remove":
            self.coord.drop_replica(stmt.vnode_id)
            return ResultSet.message("ok")
        if stmt.op == "replica_promote":
            self.meta.promote_replica(stmt.vnode_id)
            return ResultSet.message("ok")
        if stmt.op == "replica_destory":
            self.coord.destroy_replica_set(stmt.replica_set_id)
            return ResultSet.message("ok")
        if stmt.op == "checksum":
            rows = self.coord.checksum_group(stmt.replica_set_id)
            return ResultSet(
                ["vnode_id", "node_id", "checksum"],
                [np.array([r[0] for r in rows], dtype=np.int64),
                 np.array([r[1] for r in rows], dtype=np.int64),
                 np.array([r[2] for r in rows], dtype=object)])
        raise ExecutionError(f"unsupported vnode admin {stmt.op}")

    def _copy(self, stmt: ast.CopyStmt, session: Session):
        """COPY INTO (reference execution/ddl/copy + object-store sinks):
        export a table to CSV/parquet, or import a file into a table.
        s3:// gcs:// azblob:// paths ride utils.objstore with the
        statement's CONNECTION options."""
        import io

        import pyarrow as pa

        if stmt.target_is_path:
            if isinstance(stmt.source, (ast.SelectStmt, ast.UnionStmt)):
                rs = self.execute_statement(stmt.source, session)
            else:
                rs = self._select(ast.SelectStmt(
                    items=[ast.SelectItem("*")], table=stmt.source),
                    session)
            arrays, fields = [], []
            for n, c in zip(rs.names, rs.columns):
                if c.dtype == object:
                    arrays.append(pa.array(
                        [None if v is None else str(v) for v in c]))
                else:
                    arrays.append(pa.array(c))
                fields.append(n)
            table = pa.table(dict(zip(fields, arrays)))
            from ..utils import objstore

            target = stmt.target
            if target.startswith("file://"):
                target = target[len("file://"):]
            remote = objstore.is_remote(target)
            if not remote:
                # a '/'-terminated target is a directory sink (reference
                # writes part files under the prefix)
                if target.endswith("/") or os.path.isdir(target):
                    os.makedirs(target, exist_ok=True)
                    # append the next part file (re-exports into the same
                    # prefix accumulate, as the reference's sink does)
                    part = 0
                    while os.path.exists(os.path.join(
                            target, f"part-{part}.{stmt.fmt}")):
                        part += 1
                    target = os.path.join(target,
                                          f"part-{part}.{stmt.fmt}")
                else:
                    os.makedirs(os.path.dirname(target) or ".",
                                exist_ok=True)
            sink = io.BytesIO() if remote else target
            if stmt.fmt == "parquet":
                import pyarrow.parquet as pq

                pq.write_table(table, sink)
            else:
                import pyarrow.csv as pc

                pc.write_csv(table, sink)
            if remote:
                objstore.write_uri(stmt.target, sink.getvalue(),
                                   stmt.options)
            return ResultSet(["rows_exported"],
                             [np.array([rs.n_rows], dtype=np.int64)])
        # import: file/object → table (schema must exist; map by name)
        from ..utils import objstore

        source = stmt.source
        if isinstance(source, str) and source.startswith("file://"):
            source = source[len("file://"):]
        if isinstance(source, str) and os.path.isdir(source):
            # directory import: concatenate every part file (reference
            # lists the prefix); parquet readers take the dir directly
            if stmt.fmt != "parquet":
                parts = sorted(
                    os.path.join(source, f) for f in os.listdir(source)
                    if not f.startswith("."))
                import pyarrow.csv as pc

                tables = [pc.read_csv(p) for p in parts]
                table = pa.concat_tables(tables)
                return self._copy_import(stmt, session, table)
        src = objstore.open_source(source, stmt.options)
        if stmt.fmt == "parquet":
            import pyarrow.parquet as pq

            table = pq.read_table(src)
        elif stmt.fmt == "json":
            import pyarrow.json as pj

            table = pj.read_json(src)
        else:
            import pyarrow.csv as pc

            table = pc.read_csv(src)
        return self._copy_import(stmt, session, table)

    def _copy_import(self, stmt: ast.CopyStmt, session: Session, table):
        schema = self.meta.table(session.tenant, session.database,
                                 stmt.target)
        auto_infer = bool((stmt.options.get("__copy_options__") or {})
                          .get("auto_infer_schema"))
        if stmt.columns:
            # COPY INTO t(col, ...): positional mapping of file columns
            if len(stmt.columns) != len(table.column_names):
                raise ExecutionError(
                    f"COPY INTO column list has {len(stmt.columns)} "
                    f"name(s), file has {len(table.column_names)}")
            cols = {stmt.columns[i]: table.column(i).to_pylist()
                    for i in range(len(stmt.columns))}
        elif stmt.fmt == "csv" or auto_infer:
            # csv (and auto_infer_schema mode): positional mapping to the
            # table's declared column order (reference parses the file
            # against the target schema — copy_into_table.slt expects a
            # parse error when the layout doesn't line up, and
            # auto_infer_schema errors on a column-count mismatch)
            order = [c.name for c in schema.columns]
            if len(table.column_names) != len(order):
                raise ExecutionError(
                    f"COPY INTO {stmt.target}: insert columns and source "
                    f"columns not match ({len(table.column_names)} vs "
                    f"{len(order)})")
            cols = {order[i]: table.column(i).to_pylist()
                    for i in range(len(order))}
        else:
            # named formats (parquet/json): map by column NAME; columns
            # absent from the file stay NULL (reference json import)
            cols = {n: table.column(n).to_pylist()
                    for n in table.column_names}
            unknown = [c for c in cols
                       if c != "time" and not schema.contains_column(c)]
            if unknown:
                raise ExecutionError(
                    f"COPY INTO {stmt.target}: file column(s) "
                    f"{sorted(unknown)} not in target schema")
        if "time" not in cols:
            raise ExecutionError("COPY INTO table requires a time column")
        n = len(cols["time"])
        tag_names = [c for c in cols if schema.contains_column(c)
                     and schema.column(c).column_type.is_tag]
        field_types = {c: schema.column(c).column_type.value_type
                       for c in cols if schema.contains_column(c)
                       and schema.column(c).column_type.is_field}
        rows = [{c: cols[c][i] for c in cols} for i in range(n)]
        wb = WriteBatch.from_rows(stmt.target, rows, tag_names, field_types)
        self.coord.write_points(session.tenant, session.database, wb)
        return ResultSet(["rows_imported"], [np.array([n], dtype=np.int64)])

    # ------------------------------------------------------- relational path
    def _needs_relational(self, stmt: ast.SelectStmt) -> bool:
        """Window functions and aggregates over computed expressions
        (sum(a*b)) route through the relational pipeline — it evaluates
        aggregate arguments as expressions; plain single-table queries
        keep the fused-kernel path."""
        exprs = [it.expr for it in stmt.items if isinstance(it.expr, Expr)]
        exprs += [e for e in (stmt.where, stmt.having) if e is not None]
        exprs += [e for e, _ in stmt.order_by if isinstance(e, Expr)]
        exprs += [g for g in stmt.group_by if isinstance(g, Expr)]
        if any(rel.contains_window(e) for e in exprs):
            return True
        if stmt.table is not None or stmt.from_item is not None:
            tw = []
            for e in exprs:
                rel.walk_exprs(e, lambda x: tw.append(x)
                               if isinstance(x, Func)
                               and x.name.lower() == "time_window" else None)
            if tw:
                # TIME_WINDOW row expansion lives in the relational
                # pipeline (_expand_time_window); the no-FROM constant
                # form evaluates via the scalar Func registration
                return True
        for e in exprs:
            for f in rel.collect_aggs(e, AGG_FUNCS):
                args = f.args
                if args and isinstance(args[0], Literal) \
                        and args[0].value == "__distinct__":
                    args = args[1:]
                if any(not isinstance(a, (Column, Literal))
                       for a in args):
                    # computed argument ANYWHERE (corr(f1, -f1)): the
                    # relational path evaluates expressions
                    return True
        return False

    def _catalog_columns(self, from_item, table: str | None,
                         session: Session) -> set | None:
        """Column-name set of a FROM clause, resolved from catalog
        metadata only (no execution) — None when any relation's columns
        can't be known statically. Lets decorrelation classify
        UNQUALIFIED outer references (tpch q2/q17/q20 correlate on bare
        column names)."""
        def of_item(item):
            if item is None:
                return set()
            if isinstance(item, ast.TableRef):
                db = item.database or session.database
                sch = self.meta.table_opt(session.tenant, db, item.name)
                if sch is not None:
                    return set(sch.field_names()) | set(sch.tag_names()) \
                        | {"time"}
                ext = self.meta.external_opt(session.tenant, db, item.name)
                if ext is not None and ext.get("columns"):
                    return {c[0] for c in ext["columns"]}
                return None
            if isinstance(item, ast.Join):
                a = of_item(item.left)
                b = of_item(item.right)
                return None if a is None or b is None else a | b
            return None   # derived tables / VALUES: undeterminable here

        if from_item is not None:
            return of_item(from_item)
        if table is not None:
            return of_item(ast.TableRef(table, None, None))
        return set()

    def _split_correlation(self, q, session: Session,
                           outer_cols: set | None = None):
        """Shared decorrelation front end: analyze the subquery body and
        split its WHERE into correlated equality pairs and a local
        residual (reference: DataFusion's subquery optimizer rules,
        query_server/query/src/sql/logical/optimizer.rs:66-108).
        → (analyzed_q, [(outer_expr, inner_expr)], residual) or None when
        the body has no extractable correlation (uncorrelated, or
        correlation in an unsupported position)."""
        if not isinstance(q, ast.SelectStmt) or q.where is None:
            return None
        # Normalize first (exact_count→count, topk→ORDER BY+LIMIT, …) so
        # the guards see the executable shape — an un-analyzed
        # exact_count would slip past the aggregate checks.
        from .analyzer import analyze

        q = analyze(q)
        local_quals = self._from_qualifiers(q)
        if not local_quals:
            return None
        # column-level resolution for UNQUALIFIED names: a bare column
        # that is NOT in the subquery's own relations but IS in the outer
        # query's is a correlated reference (catalog-only check; when the
        # inner columns can't be known statically, bare names stay local,
        # the pre-existing conservative behavior)
        local_cols = self._catalog_columns(q.from_item, q.table, session)

        def col_outer(c: str) -> bool:
            if "." in c:
                return c.split(".", 1)[0] not in local_quals
            return (local_cols is not None and outer_cols
                    and c not in local_cols and c in outer_cols)

        def is_outer(expr: Expr) -> bool:
            cols = expr.columns()
            return bool(cols) and all(col_outer(c) for c in cols)

        def is_local(expr: Expr) -> bool:
            return not any(col_outer(c) for c in expr.columns())

        pairs = []            # [(outer_expr, inner_expr)]
        residual = []         # fully-local conjuncts
        cross = []            # conjuncts mixing inner and outer columns
        from .relational import _split_conjuncts

        for c in _split_conjuncts(q.where):
            took = False
            if isinstance(c, expr_mod.BinOp) and c.op == "=":
                for outer, inner in ((c.left, c.right), (c.right, c.left)):
                    if is_outer(outer) and is_local(inner) \
                            and inner.columns():
                        pairs.append((outer, inner))
                        took = True
                        break
            if not took:
                if is_local(c) and not is_outer(c):
                    residual.append(c)
                else:
                    cross.append(c)
        if not pairs:
            return None
        return q, pairs, residual, cross, col_outer

    @staticmethod
    def _py_rows(rs):
        """ResultSet columns → per-row python tuples, normalized through
        the SAME helper the probe side uses (expr._rows_of: np-scalar
        unwrap, NaN→None) so build/probe key equality can't drift."""
        from .expr import _rows_of

        if not rs.columns:
            return []
        n = rs.n_rows
        cols = [_rows_of(c, n) for c in rs.columns]
        return list(zip(*cols))

    def _decorrelate_exists(self, e, session: Session,
                            outer_cols: set | None = None):
        """Correlated EXISTS (`EXISTS (SELECT .. FROM u WHERE u.k = t.k
        AND <local preds>)`) → semi-join: one equality conjunct becomes
        an IN over the inner key set, several become a KeyInSet over key
        tuples; NOT EXISTS → the anti-join form (outer NULL keys stay,
        unlike NOT IN's 3VL). Returns the replacement Expr or None."""
        split = self._split_correlation(e.select, session, outer_cols)
        if split is None:
            return None
        q, pairs, residual, cross, col_outer = split
        if q.group_by or q.having is not None or q.order_by or \
                q.limit is not None or q.offset:
            return None   # EXISTS bodies with those don't need them anyway
        contains_agg = any(rel.collect_aggs(it.expr, AGG_FUNCS)
                           for it in q.items if isinstance(it.expr, Expr))
        import copy as _copy
        import dataclasses

        if contains_agg:
            # An ungrouped aggregate subquery yields exactly one row no
            # matter what the WHERE matches, so EXISTS is unconditionally
            # true (and NOT EXISTS false) — never a semi-join. Execute the
            # body with the correlation conjunct dropped first so invalid
            # names (bad table/column) still raise instead of being
            # silently short-circuited away. Name resolution happens at
            # plan time, so a constant-false time bound prunes the probe's
            # scan to nothing (single-table bodies only: in a join body an
            # unqualified `time` would be ambiguous).
            probe_where = self._conjoin(residual)
            if q.from_item is None:
                never = expr_mod.BinOp("<", Column("time"),
                                       Literal(-(2 ** 62)))
                probe_where = never if probe_where is None \
                    else expr_mod.BinOp("and", probe_where, never)
            probe = dataclasses.replace(q, where=probe_where)
            self._select(probe, session)
            return Literal(not e.negated)
        if cross:
            # cross-correlation conjuncts (inner col vs outer col, tpch
            # q21): semi-join on the equality keys, then evaluate the
            # remaining conjuncts per (outer row, inner candidate)
            return self._decorrelate_exists_cross(
                e, q, pairs, residual, cross, col_outer, session)
        inner_q = dataclasses.replace(
            _copy.copy(q),
            items=[ast.SelectItem(inner, f"__ck{i}")
                   for i, (_o, inner) in enumerate(pairs)],
            where=self._conjoin(residual))
        rs = self._select(inner_q, session)
        if len(pairs) == 1:
            outer_expr = pairs[0][0]
            vals = [v.item() if hasattr(v, "item") else v
                    for v in rs.columns[0]]
            non_null = [v for v in vals if v is not None
                        and not (isinstance(v, float) and v != v)]
            keys = sorted(set(non_null), key=repr)
            if e.negated:
                # anti-join: a NULL outer key has no match → row KEPT (3VL
                # NOT IN would drop it, so spell the NULL case explicitly)
                return expr_mod.BinOp(
                    "or", expr_mod.IsNull(outer_expr),
                    InList(outer_expr, keys, negated=True))
            return InList(outer_expr, keys, False)
        # composite correlation key: tuple-membership semi/anti-join
        keys = {row for row in self._py_rows(rs)
                if not any(k is None for k in row)}
        return expr_mod.KeyInSet([o for o, _i in pairs], keys, e.negated)

    def _decorrelate_exists_cross(self, e, q, pairs, residual, cross,
                                  col_outer, session: Session):
        """EXISTS with mixed inner/outer conjuncts → CorrExists: inner
        rows bucket by the equality keys carrying the columns the cross
        conjuncts need; those conjuncts re-evaluate per candidate."""
        import copy as _copy
        import dataclasses

        inner_cols: list[str] = []
        outer_cols_used: list[str] = []
        for c in cross:
            for col in sorted(c.columns()):
                if col_outer(col):
                    if col not in outer_cols_used:
                        outer_cols_used.append(col)
                elif col not in inner_cols:
                    inner_cols.append(col)
        inner_map = {c: f"__cc{i}" for i, c in enumerate(inner_cols)}
        outer_map = {c: f"__oc{i}" for i, c in enumerate(outer_cols_used)}

        def rw(conj):
            return rel.rewrite_exprs(
                conj,
                lambda x: isinstance(x, Column)
                and (x.name in inner_map or x.name in outer_map),
                lambda x: Column(inner_map.get(x.name)
                                 or outer_map[x.name]))

        cross_rw = [rw(c) for c in cross]
        items = [ast.SelectItem(inner, f"__ck{i}")
                 for i, (_o, inner) in enumerate(pairs)]
        items += [ast.SelectItem(Column(c), inner_map[c])
                  for c in inner_cols]
        inner_q = dataclasses.replace(
            _copy.copy(q), items=items, where=self._conjoin(residual))
        rs = self._select(inner_q, session)
        n_eq = len(pairs)
        inner_rows: dict = {}
        for row in self._py_rows(rs):
            key = row[:n_eq]
            if any(k is None for k in key):
                continue
            inner_rows.setdefault(key, []).append(
                {inner_map[c]: v
                 for c, v in zip(inner_cols, row[n_eq:])})
        args = [o for o, _i in pairs] + [Column(c)
                                         for c in outer_cols_used]
        return expr_mod.CorrExists(
            args, n_eq, [outer_map[c] for c in outer_cols_used],
            inner_rows, cross_rw, e.negated)

    def _decorrelate_scalar(self, e, session: Session,
                            outer_cols: set | None = None):
        """Correlated scalar subquery → grouped-aggregate lookup
        (scalar-subquery-to-join): run the body once GROUPED BY its
        correlation columns, then map each outer row's key through the
        result. COUNT-shaped bodies default to 0 on missing keys, others
        to NULL; non-aggregate bodies enforce at-most-one-row per probed
        key. Returns a CorrLookup or None when not this pattern."""
        split = self._split_correlation(e.select, session, outer_cols)
        if split is None:
            return None
        q, pairs, residual, cross, _co = split
        if cross:
            return None   # mixed inner/outer conjuncts: EXISTS-only form
        if q.group_by or q.having is not None or q.order_by or \
                q.limit is not None or q.offset or len(q.items) != 1:
            return None
        item = q.items[0].expr
        if not isinstance(item, Expr):
            return None
        import copy as _copy
        import dataclasses

        key_items = [ast.SelectItem(inner, f"__ck{i}")
                     for i, (_o, inner) in enumerate(pairs)]
        outer_exprs = [o for o, _i in pairs]
        aggs = rel.collect_aggs(item, AGG_FUNCS)
        if aggs:
            if isinstance(item, Func) \
                    and item.name.lower() in ("count", "exact_count",
                                              "approx_distinct"):
                default = 0
            elif any(a.name.lower() in ("count", "exact_count",
                                        "approx_distinct") for a in aggs):
                # an expression AROUND count (count(*)+1) needs the
                # empty-group value of the whole expression — punt
                return None
            else:
                default = None
            inner_q = dataclasses.replace(
                _copy.copy(q),
                items=key_items + [ast.SelectItem(item, "__v")],
                where=self._conjoin(residual),
                group_by=[inner for _o, inner in pairs])
            rs = self._select(inner_q, session)
            mapping = {row[:-1]: row[-1] for row in self._py_rows(rs)
                       if not any(k is None for k in row[:-1])}
            return expr_mod.CorrLookup(outer_exprs, mapping, default)
        # non-aggregate body: at most one inner row may match any probed
        # key — group and keep a duplicate sentinel that raises only if
        # an outer row actually probes it
        inner_q = dataclasses.replace(
            _copy.copy(q),
            items=key_items + [ast.SelectItem(item, "__v")],
            where=self._conjoin(residual))
        rs = self._select(inner_q, session)
        mapping: dict = {}
        for row in self._py_rows(rs):
            key = row[:-1]
            if any(k is None for k in key):
                continue
            if key in mapping:
                mapping[key] = expr_mod._SCALAR_DUP
            else:
                mapping[key] = row[-1]
        return expr_mod.CorrLookup(outer_exprs, mapping, None)

    def _decorrelate_in(self, e, session: Session,
                        outer_cols: set | None = None):
        """Correlated IN subquery (`a [NOT] IN (SELECT v FROM u WHERE
        u.k = t.k ..)`) → per-key membership with full three-valued
        logic (CorrIn). Returns the replacement Expr or None."""
        split = self._split_correlation(e.select, session, outer_cols)
        if split is None:
            return None
        q, pairs, residual, cross, _co = split
        if cross:
            return None   # mixed inner/outer conjuncts: EXISTS-only form
        if q.group_by or q.having is not None or q.order_by or \
                q.limit is not None or q.offset or len(q.items) != 1:
            return None
        item = q.items[0].expr
        if not isinstance(item, Expr) or rel.collect_aggs(item, AGG_FUNCS):
            return None
        import copy as _copy
        import dataclasses

        inner_q = dataclasses.replace(
            _copy.copy(q),
            items=[ast.SelectItem(inner, f"__ck{i}")
                   for i, (_o, inner) in enumerate(pairs)]
            + [ast.SelectItem(item, "__v")],
            where=self._conjoin(residual))
        rs = self._select(inner_q, session)
        pairs_set: set = set()
        keyed: set = set()
        null_keys: set = set()
        for row in self._py_rows(rs):
            key, v = row[:-1], row[-1]
            if any(k is None for k in key):
                continue
            keyed.add(key)
            if v is None:
                null_keys.add(key)
            else:
                pairs_set.add(key + (v,))
        return expr_mod.CorrIn([e.expr] + [o for o, _i in pairs],
                               pairs_set, keyed, null_keys, e.negated)

    @staticmethod
    def _conjoin(cs):
        out = None
        for c in cs:
            out = c if out is None else expr_mod.BinOp("and", out, c)
        return out

    @staticmethod
    def _from_qualifiers(q: ast.SelectStmt) -> set:
        """Relation qualifiers visible inside a subquery's own FROM."""
        quals: set = set()

        def visit(item):
            if item is None:
                return
            if isinstance(item, ast.TableRef):
                quals.add(item.alias or item.name)
            elif isinstance(item, ast.SubqueryRef):
                quals.add(item.alias)
            elif isinstance(item, ast.Join):
                visit(item.left)
                visit(item.right)

        visit(q.from_item)
        if q.table:
            quals.add(q.table)
        return quals

    def _resolve_subqueries(self, stmt: ast.SelectStmt, session: Session):
        """Execute uncorrelated scalar / IN subqueries and splice their
        results in as literals; correlated EXISTS decorrelates to
        semi/anti-joins (reference: DataFusion subquery rules)."""
        # fold NOT over EXISTS into the node FIRST: anti-join NULL
        # semantics differ from 3VL NOT over the semi-join replacement
        def fold_pred(e):
            return isinstance(e, expr_mod.UnaryOp) and e.op == "not" \
                and isinstance(e.operand, expr_mod.Exists)

        def fold(e):
            return expr_mod.Exists(e.operand.select,
                                   not e.operand.negated)

        import dataclasses as _dc

        stmt = _dc.replace(
            stmt,
            items=[ast.SelectItem(
                rel.rewrite_exprs(it.expr, fold_pred, fold)
                if isinstance(it.expr, Expr) else it.expr, it.alias)
                for it in stmt.items],
            where=(rel.rewrite_exprs(stmt.where, fold_pred, fold)
                   if stmt.where is not None else None),
            having=(rel.rewrite_exprs(stmt.having, fold_pred, fold)
                    if stmt.having is not None else None))
        found = []

        def spot(e):
            if isinstance(e, (Subquery, InSubquery, expr_mod.Exists)):
                found.append(e)

        exprs = [it.expr for it in stmt.items if isinstance(it.expr, Expr)]
        exprs += [e for e in (stmt.where, stmt.having) if e is not None]
        for e in exprs:
            rel.walk_exprs(e, spot)
        if not found:
            return stmt

        outer_cols = self._catalog_columns(stmt.from_item, stmt.table,
                                           session)

        def replace(e):
            q = e.select
            if isinstance(e, expr_mod.Exists):
                corr = self._decorrelate_exists(e, session, outer_cols)
                if corr is not None:
                    return corr
            elif isinstance(e, Subquery):
                corr = self._decorrelate_scalar(e, session, outer_cols)
                if corr is not None:
                    return corr
            elif isinstance(e, InSubquery):
                corr = self._decorrelate_in(e, session, outer_cols)
                if corr is not None:
                    return corr
            rs = self._union(q, session) if isinstance(q, ast.UnionStmt) \
                else self._select(q, session)
            if isinstance(e, expr_mod.Exists):
                hit = rs.n_rows > 0
                return Literal((not hit) if e.negated else hit)
            if isinstance(e, Subquery):
                if len(rs.columns) != 1 or rs.n_rows > 1:
                    raise QueryError(
                        "scalar subquery must return a single value")
                if rs.n_rows == 0:
                    return Literal(None)
                v = rs.columns[0][0]
                return Literal(v.item() if hasattr(v, "item") else v)
            if len(rs.columns) != 1:
                raise QueryError("IN subquery must return a single column")
            vals = [v.item() if hasattr(v, "item") else v
                    for v in rs.columns[0]]
            non_null = [v for v in vals if v is not None]
            return InList(e.expr, non_null, e.negated,
                          null_present=len(non_null) != len(vals))

        import copy as _copy

        out = _copy.copy(stmt)
        pred = lambda e: isinstance(  # noqa: E731
            e, (Subquery, InSubquery, expr_mod.Exists))
        out.items = [ast.SelectItem(rel.rewrite_exprs(it.expr, pred, replace)
                                    if isinstance(it.expr, Expr) else it.expr,
                                    it.alias) for it in stmt.items]
        if stmt.where is not None:
            out.where = rel.rewrite_exprs(stmt.where, pred, replace)
        if stmt.having is not None:
            out.having = rel.rewrite_exprs(stmt.having, pred, replace)
        return out

    def _is_const_agg(self, e) -> bool:
        from .planner import AGG_FUNCS

        return (isinstance(e, Func) and e.name.lower() in AGG_FUNCS
                and all(isinstance(a, Literal) for a in e.args))

    def _const_aggregate(self, e: Func):
        """Aggregate over a literal with no FROM: one conceptual row
        (reference: `select mode(null)` is NULL, `select count(null)`
        is 0 — function/common/mode.slt, count.slt)."""
        name = e.name.lower()
        if not e.args:
            raise PlanError(f"{e.name}() requires an argument")
        if name in ("approx_percentile_cont",
                    "approx_percentile_cont_with_weight") \
                and len(e.args) < 2:
            raise PlanError(
                f"{e.name} requires a column and a constant quantile")
        v = e.args[0].value
        if name in ("count", "count_distinct", "approx_distinct"):
            return 0 if v is None else 1
        if v is None:
            return None
        if name in ("avg", "mean", "median", "sum", "stddev_pop",
                    "var_pop", "approx_median"):
            return float(v) if name != "sum" else v
        return v

    def _fold_session_scalars(self, stmt: ast.SelectStmt, session):
        """current_user()/current_tenant()/current_database()/
        current_role() fold to the SESSION's values (reference
        session.rs scalars are session-bound; current_role is NULL in
        the single-role default)."""
        from datetime import datetime, timezone

        from .expr import DateLit, TimeOfDayLit

        role = self.meta.members.get(session.tenant, {}).get(session.user)
        now = datetime.now(timezone.utc)
        vals = {"current_user": session.user,
                "current_tenant": session.tenant,
                "current_database": session.database,
                "current_role": role}
        # date/time scalars fold ONCE per statement (reference:
        # current_time() = current_time() is true within a query —
        # time_functions/current_time.slt)
        typed = {"current_date": DateLit(now.strftime("%Y-%m-%d")),
                 "current_time": TimeOfDayLit(
                     now.strftime("%H:%M:%S.%f"))}

        def hit(x):
            return isinstance(x, Func) and not x.args \
                and x.name.lower() in (*vals, *typed, "arrow_typeof")

        def sub(x):
            n = x.name.lower()
            if n in typed:
                return typed[n]
            return Literal(vals[n])

        def hit_typeof(x):
            return isinstance(x, Func) and x.name.lower() == \
                "arrow_typeof" and len(x.args) == 1

        def sub_typeof(x):
            a = x.args[0]
            if isinstance(a, DateLit):
                t = "Date32"
            elif isinstance(a, TimeOfDayLit):
                t = "Time64(Nanosecond)"
            elif isinstance(a, Literal):
                v = a.value
                t = ("Boolean" if isinstance(v, bool) else
                     "Int64" if isinstance(v, int) else
                     "Float64" if isinstance(v, float) else
                     "Utf8" if isinstance(v, str) else "Null")
            elif isinstance(a, Column) and a.name.endswith("time"):
                t = 'Timestamp(Nanosecond, None)'
            else:
                raise ExecutionError("arrow_typeof over expressions is "
                                     "not supported")
            return Literal(t)

        import dataclasses

        def fold(e):
            if not isinstance(e, Expr):
                return e
            e = rel.rewrite_exprs(e, hit, sub)
            return rel.rewrite_exprs(e, hit_typeof, sub_typeof)

        changed = dataclasses.replace(
            stmt,
            items=[ast.SelectItem(fold(it.expr), it.alias)
                   for it in stmt.items],
            where=fold(stmt.where) if stmt.where is not None else None,
            having=fold(stmt.having) if stmt.having is not None else None)
        return changed

    def _strip_table_qualifiers(self, stmt: ast.SelectStmt):
        """`SELECT m2.f0 FROM m2 WHERE m2.f1 > 0` — a single-table query
        may qualify columns with the table (or db.table) name; resolve to
        bare names before planning (joins handle qualifiers in the
        relational scope instead)."""
        import dataclasses

        quals = [stmt.table + "."]
        if stmt.database:
            quals.append(f"{stmt.database}.{stmt.table}.")

        def strip(e):
            if not isinstance(e, Expr):
                return e
            out = e
            for q in quals:
                out = rel.rewrite_exprs(
                    out, lambda x: isinstance(x, Column)
                    and x.name.startswith(q),
                    lambda x: Column(x.name[len(q):]))
            return out

        changed = dataclasses.replace(
            stmt,
            items=[ast.SelectItem(strip(it.expr), it.alias)
                   for it in stmt.items],
            where=strip(stmt.where), having=strip(stmt.having),
            order_by=[(strip(oe), asc) for oe, asc in stmt.order_by],
            group_by=[strip(g) for g in stmt.group_by])
        return changed

    def _strip_alias(self, e: Expr, alias: str | None) -> Expr:
        """alias.col → col for pushdown into the aliased base relation."""
        if alias is None or e is None:
            return e
        prefix = alias + "."
        return rel.rewrite_exprs(
            e, lambda x: isinstance(x, Column) and x.name.startswith(prefix),
            lambda x: Column(x.name[len(prefix):]))

    def _materialize_from(self, item, session: Session,
                          pushed_where: Expr | None = None) -> rel.Scope:
        """FROM item → Scope. Base tables materialize through the normal
        single-table path (predicate pushdown, fused kernels, system
        tables); joins compose host-side (reference: TskvExec leaves under
        DataFusion join operators)."""
        if isinstance(item, ast.TableRef):
            # an unaliased table is addressable by its own name
            # (`FROM o JOIN c ON o.cust = c.cust` — standard SQL); an
            # explicit alias REPLACES the table name as the qualifier
            qual = item.alias or item.name
            ext = self.meta.external_opt(
                session.tenant, item.database or session.database, item.name)
            if ext is not None:
                names, cols = _load_external(ext)
                scope = rel.Scope.from_relation(names, cols, qual)
                if pushed_where is not None:
                    w = self._strip_alias(pushed_where, qual)
                    m = np.asarray(w.eval(scope.env, np))
                    if not m.shape:
                        m = np.full(scope.n, bool(m))
                    scope = scope.filter(m)
                return scope
            sub = ast.SelectStmt(
                items=[ast.SelectItem("*")], table=item.name,
                where=self._strip_alias(pushed_where, qual),
                database=item.database)
            rs = self._select(sub, session)
            return rel.Scope.from_relation(rs.names, rs.columns, qual)
        if isinstance(item, ast.ValuesRef):
            width = len(item.rows[0]) if item.rows else 0
            names = item.columns or [f"column{i + 1}"
                                     for i in range(width)]
            cols = []
            for i in range(width):
                vals = [r[i] for r in item.rows]
                if all(isinstance(v, bool) for v in vals):
                    cols.append(np.array(vals, dtype=bool))
                elif all(isinstance(v, int) and not isinstance(v, bool)
                         for v in vals):
                    cols.append(np.array(vals, dtype=np.int64))
                elif all(isinstance(v, (int, float))
                         and not isinstance(v, bool) for v in vals):
                    cols.append(np.array(vals, dtype=np.float64))
                else:
                    c = np.empty(len(vals), dtype=object)
                    c[:] = vals
                    cols.append(c)
            scope = rel.Scope.from_relation(names, cols, item.alias)
            if pushed_where is not None:
                w = self._strip_alias(pushed_where, item.alias)
                m = np.asarray(w.eval(scope.env, np))
                if not m.shape:
                    m = np.full(scope.n, bool(m))
                scope = scope.filter(m)
            return scope
        if isinstance(item, ast.SubqueryRef):
            q = item.select
            rs = self._union(q, session) if isinstance(q, ast.UnionStmt) \
                else self._select(q, session)
            names = rs.names
            aliases = getattr(item, "col_aliases", None)
            if aliases:
                # derived-table column list renames positionally
                # (tpch.slt q13: FROM (...) AS c_orders (c_custkey, c_count))
                if len(aliases) > len(names):
                    raise PlanError(
                        f"derived table {item.alias} declares "
                        f"{len(aliases)} columns, query returns "
                        f"{len(names)}")
                names = list(aliases) + names[len(aliases):]
            # pushed_where (if any) applies post-materialization
            scope = rel.Scope.from_relation(names, rs.columns, item.alias)
            if pushed_where is not None:
                w = self._strip_alias(pushed_where, item.alias)
                m = np.asarray(w.eval(scope.env, np))
                if not m.shape:
                    m = np.full(scope.n, bool(m))
                scope = scope.filter(m)
            return scope
        if isinstance(item, ast.Join):
            scope = self._join_optimized(item, session)
            if scope is None:
                left = self._materialize_from(item.left, session)
                right = self._materialize_from(item.right, session)
                scope = rel.hash_join(left, right, item.kind, item.on)
            if pushed_where is not None:
                m = np.asarray(pushed_where.eval(scope.env, np))
                if not m.shape:
                    m = np.full(scope.n, bool(m))
                scope = scope.filter(m)
            return scope
        raise PlanError(f"unsupported FROM item {type(item).__name__}")

    def _join_optimized(self, item: ast.Join, session: Session):
        """Cost-based ordering for maximal inner-join trees (exact
        cardinalities — relations are materialized; sql/join_order.py).
        None → structure not proven safe, caller runs written order."""
        from . import join_order

        leaf_items, conjuncts = join_order.flatten_inner(item)
        if len(leaf_items) < 3:   # nothing to reorder; don't materialize twice
            return None
        leaves = [self._materialize_from(li, session) for li in leaf_items]
        if not join_order.reorderable(leaves, conjuncts):
            # structural decline AFTER materialization: replay the written
            # tree over the already-materialized leaves (no double scan)
            it = iter(leaves)
            return self._join_written(item, it)
        return join_order.order_and_join(leaves, conjuncts)

    def _join_written(self, item, leaf_iter) -> rel.Scope:
        # outer-join subtrees are LEAVES of the flattened inner region
        # (they materialized as one scope) — only inner joins recurse
        if isinstance(item, ast.Join) and item.kind == "inner":
            left = self._join_written(item.left, leaf_iter)
            right = self._join_written(item.right, leaf_iter)
            return rel.hash_join(left, right, item.kind, item.on)
        return next(leaf_iter)

    def _select_relational(self, stmt: ast.SelectStmt, session: Session):
        item = stmt.from_item or ast.TableRef(stmt.table, None, stmt.database)
        where = stmt.where
        pushed = None
        if isinstance(item, ast.TableRef) and where is not None \
                and not rel.contains_window(where):
            pushed, where = where, None   # full pushdown into the base scan
        scope = self._materialize_from(item, session, pushed)
        # schema-aware scalar signature checks over the materialized
        # scope (the single-table path validates in plan_select)
        from .planner import validate_scalar_sigs_env

        for it in stmt.items:
            if isinstance(it.expr, Expr):
                validate_scalar_sigs_env(it.expr, scope.env)
        for _e in (stmt.where, stmt.having):
            if _e is not None:
                validate_scalar_sigs_env(_e, scope.env)
        if where is not None:
            if rel.contains_window(where):
                raise PlanError("window functions are not allowed in WHERE")
            m = np.asarray(where.eval(scope.env, np))
            if not m.shape:
                m = np.full(scope.n, bool(m))
            scope = scope.filter(m)

        scope, stmt = self._expand_time_window(stmt, scope)

        has_agg = any(
            rel.collect_aggs(it.expr, AGG_FUNCS)
            for it in stmt.items if isinstance(it.expr, Expr))
        if stmt.group_by or has_agg:
            win_exprs = [it.expr for it in stmt.items
                         if isinstance(it.expr, Expr)]
            win_exprs += [e for e, _ in stmt.order_by if isinstance(e, Expr)]
            if any(rel.contains_window(e) for e in win_exprs):
                raise PlanError(
                    "window functions cannot mix with GROUP BY in one "
                    "SELECT — wrap the aggregate in a subquery")
            rs, env, order_by = self._host_group_aggregate(stmt, scope)
            rs = _order_limit(rs, order_by, stmt.limit, stmt.offset, env)
            return self._distinct(rs) if stmt.distinct else rs

        # window evaluation over the filtered scope, then projection
        win_map: dict[int, str] = {}
        wfs: list[WindowFunc] = []
        for it in stmt.items:
            if isinstance(it.expr, Expr):
                rel.walk_exprs(it.expr, lambda e: wfs.append(e)
                               if isinstance(e, WindowFunc) else None)
        for e, _ in stmt.order_by:
            if isinstance(e, Expr):
                rel.walk_exprs(e, lambda x: wfs.append(x)
                               if isinstance(x, WindowFunc) else None)
        env = dict(scope.env)
        for i, wf in enumerate(wfs):
            alias = f"__win{i}"
            env[alias] = rel.eval_window(wf, scope.env, scope.n)
            win_map[id(wf)] = alias

        def unwin(e):
            if not isinstance(e, Expr):
                return e
            return rel.rewrite_exprs(
                e, lambda x: isinstance(x, WindowFunc),
                lambda x: Column(win_map[id(x)]))

        out_names, out_cols = [], []
        for it in stmt.items:
            if it.expr == "*":
                out_names.extend(scope.names)
                out_cols.extend(scope.cols)
                continue
            v = unwin(it.expr).eval(env, np)
            if np.isscalar(v) or getattr(v, "shape", None) == ():
                v = np.full(scope.n, v)
            out_names.append(_out_name(it))
            out_cols.append(np.asarray(v))
        rs = ResultSet(out_names, out_cols)
        env_all = dict(env)
        for nm, c in zip(out_names, out_cols):
            env_all.setdefault(nm, c)
        order_by = [(unwin(e), asc) for e, asc in stmt.order_by]
        rs = _order_limit(rs, order_by, stmt.limit, stmt.offset, env_all)
        return self._distinct(rs) if stmt.distinct else rs

    def _expand_time_window(self, stmt: ast.SelectStmt, scope: rel.Scope):
        """Row-expanding TIME_WINDOW (reference transform_time_window.rs:
        TIME_WINDOW → Expand): every row joins each sliding window that
        contains its timestamp; the call sites are rewritten to a struct
        column ({start, end} dicts) and all scope columns re-index by the
        expansion. One distinct call per SELECT (upstream restriction)."""
        calls: list[Func] = []

        def spot(e):
            if isinstance(e, Func) and not isinstance(e, WindowFunc) \
                    and e.name.lower() == "time_window":
                calls.append(e)

        exprs = [it.expr for it in stmt.items if isinstance(it.expr, Expr)]
        exprs += [g for g in stmt.group_by if isinstance(g, Expr)]
        exprs += [e for e, _ in stmt.order_by if isinstance(e, Expr)]
        if stmt.having is not None:
            exprs.append(stmt.having)
        for e in exprs:
            rel.walk_exprs(e, spot)
        if not calls:
            return scope, stmt
        sigs = {c.to_sql() for c in calls}
        if len(sigs) > 1:
            raise PlanError(
                "only one TIME_WINDOW expression per SELECT is supported")
        f = calls[0]
        if not 2 <= len(f.args) <= 4:
            raise PlanError(
                "time_window(time, window[, slide[, start_time]])")
        t = np.asarray(f.args[0].eval(scope.env, np))
        if t.dtype == object:
            # struct-field access (tsbench windows over window.start of
            # an inner time_window) yields object ints; NULL rows drop
            keep0 = np.array([isinstance(x, (int, np.integer))
                              and not isinstance(x, (bool, np.bool_))
                              for x in t], dtype=bool)
            if not keep0.all():
                scope = scope.filter(keep0)
                t = t[keep0]
            t = t.astype(np.int64) if len(t) else \
                np.zeros(0, dtype=np.int64)
        if t.dtype.kind not in "iu":
            raise PlanError(
                "time_window's first argument must be a timestamp")
        t = t.astype(np.int64)
        window = self._tw_interval(f.args[1])
        slide = self._tw_interval(f.args[2]) if len(f.args) > 2 else window
        origin = 0
        if len(f.args) > 3:
            a = f.args[3]
            v = a.eval({}, np) if isinstance(a, (Literal, expr_mod.Cast)) \
                else None
            if isinstance(v, str):
                from .parser import parse_timestamp_string

                v = parse_timestamp_string(v)
            if not isinstance(v, (int, np.integer)):
                raise PlanError("time_window start_time must be a "
                                "timestamp constant")
            origin = int(v)
        if window <= 0 or slide <= 0:
            raise PlanError("time_window durations must be positive")

        # reference formula (transform_time_window.rs:248-393):
        #   st_mod = start_time MOD window          (window, not slide!)
        #   last_start = t - ((t - st_mod + slide) MOD slide)
        #   window_start_i = last_start - i·slide, i ∈ [0, ⌈window/slide⌉)
        # MOD is Rust's truncating remainder. EVERY i is emitted per row
        # (a row can land in a window not covering its timestamp — the
        # pinned 10ms/6ms rows show it); but when window % slide != 0
        # the reference filters out SOURCE ROWS with t outside their own
        # i=0 window (t ≥ last_start + window, possible when slide >
        # window) — all copies of such a row drop together.
        n_win = -(window // -slide)   # ceil
        if n_win > 100:
            raise PlanError(f"Too many overlapping windows: {n_win}")
        st_mod = expr_mod.trunc_mod(origin, window)
        last_start = t - np.fmod(t - st_mod + slide, slide)
        if window % slide != 0:
            rkeep = t < last_start + window
            if not rkeep.all():
                t = t[rkeep]
                last_start = last_start[rkeep]
                scope = scope.filter(rkeep)
        n0 = len(t)
        idx = np.repeat(np.arange(n0, dtype=np.int64), n_win)
        ks = np.tile(np.arange(n_win, dtype=np.int64), n0)
        starts_all = last_start[idx] - ks * slide
        win_col = np.empty(len(idx), dtype=object)
        for i, s in enumerate(starts_all):
            win_col[i] = {"kind": "window", "start": int(s),
                          "end": int(s) + window}
        new_scope = rel.Scope(
            scope.names, [c[idx] for c in scope.cols],
            {k2: v[idx] for k2, v in scope.env.items()})
        new_scope.quals = set(scope.quals)
        new_scope.env["__time_window__"] = win_col

        def rw(e):
            if not isinstance(e, Expr):
                return e
            return rel.rewrite_exprs(
                e, lambda x: isinstance(x, Func)
                and not isinstance(x, WindowFunc)
                and x.name.lower() == "time_window",
                lambda x: Column("__time_window__"))

        import dataclasses

        stmt = dataclasses.replace(
            stmt,
            items=[ast.SelectItem(rw(it.expr), it.alias)
                   for it in stmt.items],
            group_by=[rw(g) for g in stmt.group_by],
            order_by=[(rw(e), asc) for e, asc in stmt.order_by],
            having=rw(stmt.having) if stmt.having is not None else None)
        return new_scope, stmt

    @staticmethod
    def _tw_interval(arg) -> int:
        """Interval constant for time_window durations: INTERVAL literal
        or CAST(str AS INTERVAL)."""
        if isinstance(arg, Literal) and hasattr(arg.value, "ns"):
            return int(arg.value.ns)
        if isinstance(arg, expr_mod.Cast) \
                and arg.target.upper() == "INTERVAL" \
                and isinstance(arg.expr, Literal) \
                and isinstance(arg.expr.value, str):
            from .parser import parse_interval_string

            return int(parse_interval_string(arg.expr.value))
        raise PlanError(
            "time_window durations must be INTERVAL constants")

    def _host_group_aggregate(self, stmt: ast.SelectStmt, scope: rel.Scope):
        """GROUP BY + aggregates over a joined/derived relation — the
        host-side final-aggregate (single tables use the fused kernel)."""
        alias_map = {it.alias: it.expr for it in stmt.items
                     if it.alias and isinstance(it.expr, Expr)}
        key_exprs: list[Expr] = []
        for g in stmt.group_by:
            if isinstance(g, int):
                e = stmt.items[g - 1].expr
                if not isinstance(e, Expr):
                    raise PlanError("GROUP BY ordinal refers to *")
                key_exprs.append(e)
            elif isinstance(g, Expr):
                if isinstance(g, Column) and g.name not in scope.env \
                        and g.name in alias_map:
                    g = alias_map[g.name]   # GROUP BY a SELECT alias
                key_exprs.append(g)
            else:
                name = str(g)
                if name not in scope.env and name in alias_map:
                    key_exprs.append(alias_map[name])
                else:
                    key_exprs.append(Column(name))
        key_cols = [np.asarray(e.eval(scope.env, np)) for e in key_exprs]
        gid, first_idx = rel.group_indices(key_cols, scope.n)
        n_groups = len(first_idx)
        if n_groups == 0 and not key_exprs:
            # a GLOBAL aggregate over zero rows still yields one row
            # (count 0 / NULL sums — tpch q6 over an empty filter)
            n_groups = 1

        agg_cache: dict[str, np.ndarray] = {}
        # Gather per-group representatives only for names the
        # post-aggregate exprs (keys/items/HAVING/ORDER BY) can reach —
        # gathering every env column was O(columns × groups) object
        # traffic on wide scans. Aggregate args read scope.env directly.
        needed: set[str] = set()
        for e in key_exprs:
            needed |= e.columns()
        for it in stmt.items:
            if isinstance(it.expr, Expr):
                needed |= it.expr.columns()
        if stmt.having is not None:
            needed |= stmt.having.columns()
        for oe, _asc in stmt.order_by:
            if isinstance(oe, Expr):
                needed |= oe.columns()
            elif isinstance(oe, str):
                needed.add(oe)
        for name in list(needed):
            if "." in name:   # struct access resolves through the base col
                needed.add(name.rpartition(".")[0])
        genv = {}
        for k, v in scope.env.items():
            base = k[10:] if k.startswith("__valid__:") else k
            if base not in needed:
                continue
            gv = v[first_idx]
            if n_groups and len(gv) < n_groups:   # synthesized empty group
                gv = np.full(n_groups, None, dtype=object)
            genv[k] = gv

        def agg_col(f: Func) -> str:
            distinct = bool(f.args) and isinstance(f.args[0], Literal) \
                and f.args[0].value == "__distinct__"
            args = f.args[1:] if distinct else f.args
            star = (len(args) == 1 and isinstance(args[0], Literal)
                    and args[0].value == "*")
            key = f.to_sql() + ("D" if distinct else "")
            if key not in agg_cache:
                col = None if (star or not args) else \
                    np.asarray(args[0].eval(scope.env, np))
                col2 = param = None
                name = f.name.lower()
                if name in ("corr", "covar", "covar_pop", "covar_samp") \
                        and len(args) == 2:
                    col2 = np.asarray(args[1].eval(scope.env, np))
                elif name == "approx_percentile_cont" and len(args) == 2:
                    param = args[1].eval(scope.env, np)
                elif name == "approx_percentile_cont_with_weight" \
                        and len(args) == 3:
                    col2 = np.asarray(args[1].eval(scope.env, np))
                    param = args[2].eval(scope.env, np)
                elif name == "sample":
                    if len(args) != 2 or not isinstance(args[1], Literal):
                        raise PlanError(
                            "sample(column, k) takes a column and a "
                            "constant size")
                    param = args[1].eval(scope.env, np)
                    col = np.asarray(args[0].eval(scope.env, np))
                elif name in ("gauge_agg", "state_agg",
                              "compact_state_agg") and len(args) == 2:
                    # (time, value): the timestamp column rides in col2
                    col = np.asarray(args[1].eval(scope.env, np))
                    col2 = np.asarray(args[0].eval(scope.env, np))
                agg_cache[key] = rel.host_aggregate(
                    f.name, col, gid, n_groups, distinct,
                    col2=col2, param=param)
            return key

        def rewrite(e):
            return rel.rewrite_exprs(
                e, lambda x: isinstance(x, Func)
                and not isinstance(x, WindowFunc)
                and x.name.lower() in AGG_FUNCS,
                lambda x: Column(agg_col(x)))

        rewritten = [(it, rewrite(it.expr) if isinstance(it.expr, Expr)
                      else it.expr) for it in stmt.items]
        having = rewrite(stmt.having) if stmt.having is not None else None
        genv.update(agg_cache)

        if having is not None:
            hm = np.asarray(having.eval(genv, np))
            if not hm.shape:
                hm = np.full(n_groups, bool(hm))
            genv = {k: v[hm] for k, v in genv.items()}
            n_groups = int(hm.sum())

        out_names, out_cols = [], []
        for it, e in rewritten:
            if e == "*":
                raise PlanError("SELECT * is invalid with GROUP BY")
            v = e.eval(genv, np)
            if np.isscalar(v) or getattr(v, "shape", None) == ():
                v = np.full(n_groups, v)
            out_names.append(_out_name(it))
            out_cols.append(np.asarray(v))
        rs = ResultSet(out_names, out_cols)
        env_all = dict(genv)
        for nm, c in zip(out_names, out_cols):
            env_all.setdefault(nm, c)
        # ORDER BY count(*) etc. must see the same aggregate rewrites
        order_by = [(rewrite(e) if isinstance(e, Expr) else e, asc)
                    for e, asc in stmt.order_by]
        if not key_exprs:
            # a GLOBAL aggregate exposes only its aggregate outputs:
            # ORDER BY a raw column is a schema error (sqlancer pins
            # "No field named m0.t0" for ORDER BY under SUM(...))
            allowed = set(out_names) | set(agg_cache)
            for oe, _asc in order_by:
                cols_ref = oe.columns() if isinstance(oe, Expr) else \
                    ({oe} if isinstance(oe, str) else set())
                bad = [c for c in cols_ref if c not in allowed]
                if bad:
                    raise PlanError(
                        f"No field named {bad[0]} in the aggregate "
                        f"output")
        return rs, env_all, order_by

    def _distinct(self, rs: ResultSet) -> ResultSet:
        seen = set()
        keep = []
        for i, key in enumerate(_row_keys(rs.columns)):
            if key not in seen:
                seen.add(key)
                keep.append(i)
        idx = np.asarray(keep, dtype=np.int64)
        return ResultSet(rs.names, [c[idx] for c in rs.columns])

    def _union(self, stmt: ast.UnionStmt, session: Session) -> ResultSet:
        """Set-operation chain. INTERSECT-precedence nesting is resolved at
        parse time (a nested chain arrives as a UnionStmt branch); operators
        at one level apply left to right. NULLs are not distinct from each
        other in set-op row matching (SQL; reference via DataFusion)."""
        from .analyzer import analyze

        stmt = analyze(stmt)   # union-level ORDER BY desugaring

        def run(s):
            return self._union(s, session) if isinstance(s, ast.UnionStmt) \
                else self._select(s, session)

        results = [run(s) for s in stmt.selects]
        width = len(results[0].names)
        for r in results[1:]:
            if len(r.names) != width:
                raise QueryError(
                    "set-operation branches must have equal arity")
        names = results[0].names
        acc = [results[0].columns[i] for i in range(width)]
        ops = stmt.ops or ["union"] * len(stmt.alls)
        for r, all_, op in zip(results[1:], stmt.alls, ops):
            if op == "union":
                acc = [_concat_cols(acc[i], r.columns[i])
                       for i in range(width)]
                if not all_:
                    acc = list(self._distinct(ResultSet(names, acc)).columns)
            else:
                acc = _set_op_cols(acc, list(r.columns), op, all_)
        rs = ResultSet(names, acc)
        env = {n: c for n, c in zip(names, acc)}
        return _order_limit(rs, stmt.order_by, stmt.limit, stmt.offset, env)

    def _select_over_env(self, stmt: ast.SelectStmt, names: list[str], cols):
        """Generic SELECT over an in-memory table (system schemas)."""
        env = {n: c for n, c in zip(names, cols)}
        n = len(cols[0]) if cols else 0
        mask = np.ones(n, dtype=bool)
        if stmt.where is not None:
            m = stmt.where.eval(env, np)
            mask = np.full(n, bool(m)) if np.isscalar(m) or m.shape == () else m
        env = {k: v[mask] for k, v in env.items()}
        n = int(mask.sum())
        out_names, out_cols = [], []
        for it in stmt.items:
            if it.expr == "*":
                out_names.extend(names)
                out_cols.extend(env[x] for x in names)
                continue
            v = it.expr.eval(env, np)
            if np.isscalar(v) or getattr(v, "shape", None) == ():
                v = np.full(n, v)
            out_names.append(it.alias or (it.expr.name if isinstance(it.expr, Column)
                                          else it.expr.to_sql()))
            out_cols.append(np.asarray(v))
        rs = ResultSet(out_names, out_cols)
        env_all = dict(env)
        for nm, c in zip(out_names, out_cols):
            env_all[nm] = c
        return _order_limit(rs, stmt.order_by, stmt.limit, stmt.offset, env_all)

    # ---------------------------------------------------------- aggregates
    def _exec_aggregate(self, plan: AggregatePlan, tenant: str, db: str):
        if self.serving is not None:
            # aggregates never fuse (segment kernels own their whole
            # batch); book the decline so batch telemetry stays honest
            self.serving.batcher.decline("aggregate")
        phys_aggs, finalize = _decompose_aggs(plan.aggs)
        second_cols = set()
        for a in phys_aggs:
            # collect2 / count_multi carry companion columns in param
            if a.func == "collect2" and isinstance(a.param, str):
                second_cols.add(a.param)
            elif a.func == "count_multi":
                second_cols.update(a.param or ())
        needed_fields = sorted({a.column for a in phys_aggs if a.column}
                               | second_cols
                               | set(plan.group_fields)
                               | (plan.filter.columns()
                                  & set(plan.schema.field_names())
                                  if plan.filter else set()))
        rw = self._matview_rewrite(plan, phys_aggs, tenant, db)
        if rw is not None:
            # sealed buckets come pre-aggregated from the view; only the
            # unsealed tail / unaligned range edges hit raw storage
            batches = [] if rw.scan_ranges.is_empty else \
                self.coord.scan_table(
                    tenant, db, plan.table, time_ranges=rw.scan_ranges,
                    tag_domains=plan.tag_domains,
                    field_names=needed_fields, page_filter=plan.filter)
            nbytes = _batches_bytes(batches)
            memgov.charge_query(nbytes, "scan")
            with self.memory_pool.reservation(nbytes,
                                              f"scan of {plan.table}"):
                return self._exec_aggregate_seeded(plan, batches,
                                                   phys_aggs, finalize,
                                                   rw.acc)
        # compressed-domain lane: fully-answerable pages come back as
        # pre-aggregated partials instead of rows (storage decides
        # per-page; a None spec books why the query can't engage)
        from ..storage import compressed_domain

        cspec = compressed_domain.build_spec(plan, phys_aggs)
        batches = self.coord.scan_table(
            tenant, db, plan.table, time_ranges=plan.time_ranges,
            tag_domains=plan.tag_domains, field_names=needed_fields,
            page_filter=plan.filter, compressed_spec=cspec)
        nbytes = _batches_bytes(batches)
        memgov.charge_query(nbytes, "scan")
        with self.memory_pool.reservation(nbytes,
                                          f"scan of {plan.table}"):
            return self._exec_aggregate_batches(plan, batches, phys_aggs,
                                                finalize)

    def _group_spiller(self, plan, phys_aggs):
        """Per-aggregate group-state guard: a GroupSpiller when the
        memory plane is on, else the branch-free no-op (legacy path is
        byte-identical — the hooks do nothing)."""
        if not memgov.enabled() or memgov.GROUP_BYTES <= 0:
            return _NoSpill()
        return GroupSpiller(plan, phys_aggs, memgov.GROUP_BYTES)

    def _matview_rewrite(self, plan, phys_aggs, tenant: str, db: str):
        """Try the materialized-rollup subsumption rewrite; None keeps
        the raw-scan path. Zero-cost while the catalog has no views."""
        if not self.matview_rewrite_enabled or plan.gapfill:
            return None
        try:
            if not getattr(self.meta, "matviews", None):
                return None
        except Exception:
            return None
        from .matview import MERGEABLE_FUNCS

        if any(a.func not in MERGEABLE_FUNCS for a in phys_aggs):
            return None
        try:
            return self.matview_engine().rewrite(plan, phys_aggs,
                                                 tenant, db)
        except Exception:
            # the rewrite is an optimization: any failure inside it must
            # degrade to the (always-correct) raw scan, visibly counted
            stages.count_error("matview.rewrite")
            return None

    def _exec_aggregate_seeded(self, plan, batches, phys_aggs, finalize,
                               acc: dict):
        """Finish an aggregate whose accumulator was seeded from sealed
        view buckets: fold the residual raw batches through the same
        partial-merge path, then finalize normally (bit-identical to a
        full scan)."""
        from ..ops.tpu_exec import finish_scan_aggregate, launch_scan_aggregate

        ncpu = os.cpu_count() or 1
        q = TpuQuery(filter=plan.filter, group_tags=plan.group_tags,
                     group_fields=plan.group_fields,
                     time_bucket=plan.bucket,
                     kernel_threads=max(1, ncpu // max(1, min(8,
                                                              len(batches) or 1))),
                     aggs=phys_aggs)
        jobs = [launch_scan_aggregate(batch, q) for batch in batches]
        spiller = self._group_spiller(plan, phys_aggs)
        try:
            with stages.stage("merge_ms"):
                for job in jobs:
                    self._poll_cancel()
                    r = finish_scan_aggregate(job)
                    _merge_partial(acc, r, plan, phys_aggs)
                    spiller.observe(acc)
            acc = spiller.finish(acc)
        finally:
            spiller.close()
        if not acc and not plan.group_tags \
                and not plan.group_fields and plan.bucket is None:
            acc[()] = {}  # SQL: a global aggregate always yields one row
        return self._finalize_aggregate(plan, acc, finalize)

    def _exec_aggregate_batches(self, plan, batches, phys_aggs, finalize):
        host_funcs = ("count_distinct", "collect", "collect_ts",
                      "collect2", "count_multi")
        import os

        ncpu = os.cpu_count() or 1
        q = TpuQuery(filter=plan.filter, group_tags=plan.group_tags,
                     group_fields=plan.group_fields,
                     time_bucket=plan.bucket,
                     # batches run kernels concurrently on a pool below:
                     # give each native call its fair share of cores
                     kernel_threads=max(1, ncpu // max(1, min(8,
                                                              len(batches)))),
                     aggs=[a for a in phys_aggs if a.func not in host_funcs])
        distinct_specs = [a for a in phys_aggs if a.func in host_funcs]

        # launch every vnode's device kernel before fetching any result:
        # fetches carry fixed device→host latency, launches are async
        from ..ops.tpu_exec import finish_scan_aggregate, launch_scan_aggregate

        from ..utils import stages

        if any(getattr(b, "compressed_partials", None) for b in batches):
            # compressed-domain partials join the generic accumulator
            # path: kernels run only over batches that still have rows,
            # page partials fold in with _merge_partial-identical
            # semantics (order-independent, so bit-identical)
            kernel_batches = [b for b in batches if b.n_rows]
            with stages.stage("kernel_ms"):
                self._poll_cancel()
                results = [finish_scan_aggregate(
                    launch_scan_aggregate(b, q)) for b in kernel_batches]
            acc: dict[tuple, dict] = {}
            spiller = self._group_spiller(plan, phys_aggs)
            try:
                with stages.stage("merge_ms"):
                    for r in results:
                        _merge_partial(acc, r, plan, phys_aggs)
                        spiller.observe(acc)
                    for b in batches:
                        _merge_compressed_partials(acc, b, plan, phys_aggs)
                        spiller.observe(acc)
                acc = spiller.finish(acc)
            finally:
                spiller.close()
            if not acc and not plan.group_tags \
                    and not plan.group_fields and plan.bucket is None:
                acc[()] = {}  # SQL: a global aggregate always yields one row
            return self._finalize_aggregate(plan, acc, finalize)
        if len(batches) == 1 and not distinct_specs:
            # single-vnode fast path: finalize vectorized straight from
            # the kernel's arrays, no per-group python merge
            with stages.stage("kernel_ms"):
                r = finish_scan_aggregate(
                    launch_scan_aggregate(batches[0], q))
            with stages.stage("finalize_ms"):
                return self._finalize_single(plan, r, phys_aggs, finalize)
        if not distinct_specs:
            if len(batches) > 1:
                # mesh-native lane: all batches upload sharded over the
                # execution mesh and partials merge through XLA
                # collectives in ONE program — no per-batch host partial,
                # no host merge. Bit-identical to the fan-out + vec merge
                # below; any decline (off-mesh replica, unsupported
                # shape, device loss mid-collective) books its reason in
                # cnosdb_mesh_total and falls through unchanged.
                from ..ops import mesh_exec

                self._poll_cancel()
                mres = mesh_exec.try_mesh_aggregate(batches, q)
                if mres is not None:
                    with stages.stage("finalize_ms"):
                        return self._finalize_single(plan, mres, phys_aggs,
                                                     finalize)
            with stages.stage("kernel_ms"):
                self._poll_cancel()
                if len(batches) > 1:
                    # per-vnode kernel prep (bucket/segment derivation +
                    # reductions) is independent: run on a pool, like the
                    # scan fan-out
                    from concurrent.futures import ThreadPoolExecutor

                    with ThreadPoolExecutor(
                            max_workers=min(8, len(batches))) as tp:
                        results = list(tp.map(
                            lambda b: finish_scan_aggregate(
                                launch_scan_aggregate(b, q)), batches))
                else:
                    results = [finish_scan_aggregate(
                        launch_scan_aggregate(b, q)) for b in batches]
            with stages.stage("merge_ms"):
                merged = _merge_results_vec(results, plan, phys_aggs)
            if merged is not None:
                with stages.stage("finalize_ms"):
                    return self._finalize_single(plan, merged, phys_aggs,
                                                 finalize)
            acc: dict[tuple, dict] = {}
            spiller = self._group_spiller(plan, phys_aggs)
            try:
                for r in results:
                    _merge_partial(acc, r, plan, phys_aggs)
                    spiller.observe(acc)
                acc = spiller.finish(acc)
            finally:
                spiller.close()
            if not acc and not plan.group_tags \
                    and not plan.group_fields and plan.bucket is None:
                acc[()] = {}
            return self._finalize_aggregate(plan, acc, finalize)
        # host-aggregate (distinct/collect) path: launch all kernels
        # first, then merge per batch
        jobs = [launch_scan_aggregate(batch, q) for batch in batches]
        acc: dict[tuple, dict] = {}
        spiller = self._group_spiller(plan, phys_aggs)
        try:
            for batch, job in zip(batches, jobs):
                self._poll_cancel()  # KILL QUERY lands between vnode fetches
                r = finish_scan_aggregate(job)
                _merge_partial(acc, r, plan, phys_aggs)
                for spec in distinct_specs:
                    _merge_distinct(acc, batch, plan, spec)
                spiller.observe(acc)
            acc = spiller.finish(acc)
        finally:
            spiller.close()
        if not acc and not plan.group_tags \
                and not plan.group_fields and plan.bucket is None:
            acc[()] = {}  # SQL: a global aggregate always yields one row

        return self._finalize_aggregate(plan, acc, finalize)

    def _finalize_single(self, plan: AggregatePlan, r, phys_aggs, finalize):
        n = r.n_rows
        stages.count("group_count", n)
        if n == 0 and not plan.group_tags and not plan.group_fields \
                and plan.bucket is None:
            # SQL: a global aggregate always yields one row
            return self._finalize_aggregate(plan, {(): {}}, finalize)
        env: dict[str, np.ndarray] = {}
        for t in plan.group_tags + plan.group_fields:
            env[t] = r.columns[t]
        if plan.bucket is not None:
            env["time"] = r.columns["time"]
        # vectorized finalizers over whole partial columns
        parts_env = {}
        for a in phys_aggs:
            if a.alias in r.columns:
                col = r.columns[a.alias]
                valid = r.valid.get(a.alias)
                parts_env[a.alias] = (col, valid)
        for alias, spec in finalize.items():
            vals, valids = _vector_finalize(spec, parts_env, n)
            env[alias] = vals
            env[f"__valid__:{alias}"] = valids

        if plan.having is not None and n:
            mask = np.asarray(plan.having.eval(env, np), dtype=bool)
            env = {k: v[mask] if isinstance(v, np.ndarray) and len(v) == n else v
                   for k, v in env.items()}
            n = int(mask.sum())

        rs = ResultSet(*_render_output(plan, env, n))
        if plan.gapfill and rs.n_rows:
            rs = _apply_gapfill(plan, rs)
        env_out = dict(env)
        for nm, c in zip(rs.names, rs.columns):
            env_out[nm] = c
        return _order_limit(rs, plan.order_by, plan.limit, plan.offset, env_out)

    def _finalize_aggregate(self, plan: AggregatePlan, acc: dict, finalize):
        keys = list(acc.keys())
        n = len(keys)
        stages.count("group_count", n)
        env: dict[str, np.ndarray] = {}
        for i, t in enumerate(plan.group_tags + plan.group_fields):
            env[t] = np.array([k[i] for k in keys], dtype=object)
        if plan.bucket is not None:
            env["time"] = np.array([k[-1] for k in keys], dtype=np.int64) \
                if n else np.empty(0, dtype=np.int64)
        for alias, spec in finalize.items():
            vals, valids = [], []
            for k in keys:
                v = _apply_finalizer(spec, acc[k])
                vals.append(v)
                valids.append(v is not None)
            if any(isinstance(v, (dict, list, str)) for v in vals):
                # composite results (gauge/state data, samples): object col
                arr = np.empty(len(vals), dtype=object)
                arr[:] = vals
            else:
                arr = np.array([v if v is not None else np.nan for v in vals])
            env[alias] = arr
            env[f"__valid__:{alias}"] = np.array(valids, dtype=bool)

        if plan.having is not None and n:
            mask = np.asarray(plan.having.eval(env, np), dtype=bool)
            env = {k: v[mask] if isinstance(v, np.ndarray) and len(v) == n else v
                   for k, v in env.items()}
            n = int(mask.sum())

        rs = ResultSet(*_render_output(plan, env, n))
        if plan.gapfill and rs.n_rows:
            rs = _apply_gapfill(plan, rs)
        # ORDER BY may reference output aliases (e.g. the bucket alias)
        env_out = dict(env)
        for nm, c in zip(rs.names, rs.columns):
            env_out[nm] = c
        rs = _order_limit(rs, plan.order_by, plan.limit, plan.offset, env_out)
        return rs

    # ---------------------------------------------------------- raw scans
    def _exec_raw(self, plan: RawScanPlan, tenant: str, db: str):
        needed = set()
        for _n, e in plan.output:
            needed |= e.columns()
        if plan.filter is not None:
            needed |= plan.filter.columns()
        field_names = sorted(needed & set(plan.schema.field_names()))
        if not field_names:
            field_names = plan.schema.field_names()
        sv = self.serving
        if sv is not None:
            # fused micro-batching rendezvous: compatible concurrent
            # point queries share one scan; None = run the solo path
            rs = sv.batcher.submit(self, plan, tenant, db, field_names)
            if rs is not None:
                return rs
        batches = self.coord.scan_table(
            tenant, db, plan.table, time_ranges=plan.time_ranges,
            tag_domains=plan.tag_domains, field_names=field_names,
            fingerprint=sv.current_fp() if sv is not None else None)
        nbytes = _batches_bytes(batches)
        memgov.charge_query(nbytes, "scan")
        with self.memory_pool.reservation(nbytes,
                                          f"scan of {plan.table}"):
            return self._exec_raw_batches(plan, batches)

    def _raw_batch_env(self, schema, b) -> dict:
        """Filter/projection eval environment for one ScanBatch: time +
        field columns with their `__valid__:` masks + per-row tag values
        gathered through the series ordinals."""
        env = {"time": b.ts}
        for fname, (vt, vals, valid) in b.fields.items():
            env[fname] = vals
            env[f"__valid__:{fname}"] = valid
        for t in schema.tag_names():
            per_series = np.array(
                [(k.tag_value(t) if k is not None else None)
                 for k in b.series_keys], dtype=object)
            env[t] = per_series[b.sid_ordinal] if b.n_series else \
                np.empty(0, dtype=object)
        return env

    def _exec_raw_batches(self, plan: RawScanPlan, batches, prepared=None):
        """`prepared` (serving-plane fused batches) short-circuits the
        scan→env→mask stage with precomputed ``(env, mask, n_rows)``
        triples — the member's own filter mask over a SHARED env; the
        projection half below is identical either way."""
        frames = []
        if prepared is not None:
            for env, mask, total in prepared:
                if not bool(mask.all()):
                    env = {k: (v[mask]
                               if isinstance(v, (np.ndarray, DictArray))
                               and len(v) == total else v)
                           for k, v in env.items()}
                frames.append((env, int(mask.sum())))
            batches = []
        for b in batches:
            env = self._raw_batch_env(plan.schema, b)
            mask = np.ones(b.n_rows, dtype=bool)
            if plan.filter is not None:
                missing = [c for c in plan.filter.columns() if c not in env]
                for c in missing:
                    env[c] = _schema_padding(plan.schema, c, b.n_rows)
                    env[f"__valid__:{c}"] = np.zeros(b.n_rows, dtype=bool)
                mask = np.asarray(plan.filter.eval(env, np), dtype=bool)
                if mask.shape == ():
                    mask = np.full(b.n_rows, bool(mask))
                # 3VL: comparison leaves are masked in sql.expr; this
                # post-hoc pass covers bare/NOT-wrapped predicates and is
                # only sound for conjunctive (OR-free) filters —
                # per-column, skipping columns under an explicit IS NULL
                from ..ops.tpu_exec import is_conjunctive, is_null_columns

                if is_conjunctive(plan.filter):
                    skip = is_null_columns(plan.filter)
                    for c in plan.filter.columns() - skip:
                        vk = f"__valid__:{c}"
                        if c in b.fields:
                            mask &= env[vk]
            # filter BEFORE projection (DataFusion order): expressions must
            # only see surviving rows — CAST over a filtered-out Inf row
            # must not abort, and selective scans shrink the eval cost
            if not bool(mask.all()):
                env = {k: (v[mask] if isinstance(v, (np.ndarray, DictArray))
                           and len(v) == b.n_rows else v)
                       for k, v in env.items()}
            frames.append((env, int(mask.sum())))

        # ORDER BY keys may reference non-projected columns: evaluate them
        # per frame as hidden columns
        ord_items = [(f"__ord{i}", oe, asc)
                     for i, (oe, asc) in enumerate(plan.order_by)]
        names = [n for n, _ in plan.output]
        out_cols: list[list[np.ndarray]] = [[] for _ in names]
        valid_cols: list[list[np.ndarray]] = [[] for _ in names]
        ord_cols: list[list[np.ndarray]] = [[] for _ in ord_items]
        for env, n_rows in frames:
            for j, (_hn, oe, _asc) in enumerate(ord_items):
                missing = [c for c in oe.columns() if c not in env]
                for c in missing:
                    env[c] = _schema_padding(plan.schema, c, n_rows)
                    env[f"__valid__:{c}"] = np.zeros(n_rows, dtype=bool)
                ov = oe.eval(env, np)
                if isinstance(ov, DictArray):
                    ov = ov.materialize()
                if ov is None:
                    ov = np.full(n_rows, None, dtype=object)
                elif np.isscalar(ov) or getattr(ov, "shape", None) == ():
                    ov = np.full(n_rows, ov)
                ov = np.asarray(ov)
                # NULL slots in typed columns carry garbage values — sort
                # keys must see the NULLs (rendered as None/nan) or NULLs
                # order by their slot garbage
                ovv = np.ones(n_rows, dtype=bool)
                for c in expr_mod.propagating_columns(oe):
                    vk = f"__valid__:{c}"
                    if vk in env:
                        ovv &= env[vk]
                if not ovv.all():
                    if np.issubdtype(ov.dtype, np.floating):
                        ov = ov.copy()
                        ov[~ovv] = np.nan
                    else:
                        ov = ov.astype(object)
                        ov[~ovv] = None
                ord_cols[j].append(ov)
            for i, (name, expr) in enumerate(plan.output):
                missing = [c for c in expr.columns() if c not in env]
                for c in missing:
                    env[c] = _schema_padding(plan.schema, c, n_rows)
                    env[f"__valid__:{c}"] = np.zeros(n_rows, dtype=bool)
                v = expr.eval(env, np)
                if isinstance(v, DictArray):
                    v = v.materialize()
                if v is None:   # e.g. TRY_CAST failure: an all-NULL column
                    v = np.full(n_rows, None, dtype=object)
                elif np.isscalar(v) or getattr(v, "shape", None) == ():
                    v = np.full(n_rows, v)
                out_cols[i].append(np.asarray(v))
                vv = np.ones(n_rows, dtype=bool)
                for c in expr_mod.propagating_columns(expr):
                    vk = f"__valid__:{c}"
                    if vk in env:
                        vv &= env[vk]
                valid_cols[i].append(vv)

        cols = [np.concatenate(c) if c else np.empty(0) for c in out_cols]
        valids = [np.concatenate(c) if c else np.empty(0, dtype=bool)
                  for c in valid_cols]
        # render NULLs: object columns get None, floats get nan
        rendered = []
        for col, valid in zip(cols, valids):
            if valid.all():
                rendered.append(col)
            elif col.dtype == object:
                c2 = col.copy()
                c2[~valid] = None
                rendered.append(c2)
            else:
                # NULL slots become None; valid NaN values STAY NaN —
                # the reference distinguishes them (acos(2) renders NaN,
                # a NULL renders empty)
                c2 = col.astype(object)
                c2[~valid] = None
                rendered.append(c2)
        hid = [np.concatenate(c) if c else np.empty(0) for c in ord_cols]
        rs = ResultSet(names, rendered)
        if plan.distinct and rs.n_rows:
            seen = {}
            for i, row in enumerate(zip(*[c.tolist() for c in rendered])):
                seen.setdefault(row, i)
            idx = np.array(sorted(seen.values()), dtype=np.int64)
            rs = ResultSet(names, [c[idx] for c in rendered])
            hid = [c[idx] for c in hid]
        env_all = {n: c for n, c in zip(names, rs.columns)}
        for (hn, _oe, _asc), c in zip(ord_items, hid):
            env_all[hn] = c
        order_by = [(Column(hn), asc) for (hn, _oe, asc) in ord_items]
        rs = _order_limit(rs, order_by, plan.limit, plan.offset, env_all)
        return rs


# ---------------------------------------------------------------------------
# partial-aggregate decomposition + merging
# ---------------------------------------------------------------------------
def _decompose_aggs(aggs: list[AggSpec]):
    """mean → sum+count partials; → (physical specs, finalizers)."""
    phys: list[AggSpec] = []
    finalize: dict = {}
    seen: dict[tuple, str] = {}

    def want(func, col, param=None):
        key = (func, col, repr(param))
        if key not in seen:
            alias = f"__p{len(phys)}"
            phys.append(AggSpec(func, col, alias, param))
            seen[key] = alias
        return seen[key]

    for a in aggs:
        if a.func in ("mean", "avg"):
            s = want("sum", a.column)
            c = want("count", a.column)
            finalize[a.alias] = ("mean", s, c)
        elif a.func == "count":
            c = want("count", a.column)
            finalize[a.alias] = ("int", c)
        elif a.func == "count_null_const":
            # count(NULL): zero per group, but groups still materialize
            c = want("count", a.column)
            finalize[a.alias] = ("zero", c)
        elif a.func == "count_multi":
            # count(a, b, ...): rows where every column is non-NULL
            finalize[a.alias] = ("int", want("count_multi", a.column,
                                             a.param))
        elif a.func.startswith("const_agg:"):
            # aggregate over a constant literal (avg(3) → 3.0)
            c = want("count", None)
            finalize[a.alias] = ("const_agg", a.func.split(":", 1)[1],
                                 c, a.param)
        elif a.func == "sum":
            finalize[a.alias] = ("pass", want("sum", a.column))
        elif a.func in ("min", "max", "first", "last"):
            finalize[a.alias] = ("pass", want(a.func, a.column))
        elif a.func in ("count_distinct", "approx_distinct"):
            finalize[a.alias] = ("distinct", want("count_distinct", a.column))
        elif a.func == "array_agg" and isinstance(a.param, tuple) \
                and a.param and a.param[0] == "const_array":
            finalize[a.alias] = ("array_const", want("collect_ts", a.column),
                                 a.param[1])
        elif a.func == "array_agg" and isinstance(a.param, tuple) \
                and a.param and a.param[0] == "order_time":
            finalize[a.alias] = ("array_ts", want("collect_ts", a.column),
                                 a.param[1], a.column == "time")
        elif a.func in ("median", "approx_median", "stddev",
                        "stddev_samp", "stddev_pop", "var", "var_samp",
                        "var_pop", "mode", "array_agg",
                        "bit_and", "bit_or", "bit_xor"):
            kind = {"approx_median": "median", "stddev_samp": "stddev",
                    "var": "var_samp"}.get(a.func, a.func)
            finalize[a.alias] = (kind, want("collect", a.column))
        elif a.func == "approx_percentile_cont":
            finalize[a.alias] = ("percentile", want("collect", a.column),
                                 a.param)
        elif a.func == "approx_percentile_cont_with_weight":
            wcol, q = a.param
            if isinstance(wcol, tuple) and wcol[0] == "__const_w__":
                finalize[a.alias] = ("percentile_w_const",
                                     want("collect", a.column),
                                     wcol[1], q)
            else:
                finalize[a.alias] = ("percentile_w",
                                     want("collect2", a.column, wcol), q)
        elif a.func in ("corr", "covar", "covar_pop", "covar_samp"):
            kind = "covar_samp" if a.func == "covar" else a.func
            finalize[a.alias] = (kind,
                                 want("collect2", a.column, a.param))
        elif a.func in _SERIES_AGGS:
            # whole-series aggregates: need the group's full time-ordered
            # (ts, value) sequence (reference runs these as DataFusion
            # accumulators, not decomposable partials)
            finalize[a.alias] = ("series", a.func,
                                 want("collect_ts", a.column), a.param)
        else:
            raise PlanError(f"aggregate {a.func!r} not supported yet")
    return phys, finalize


# aggregates finalized from the full (ts, value) sequence via sql.tsfuncs
_SERIES_AGGS = {"increase", "sample", "gauge_agg", "state_agg",
                "compact_state_agg", "completeness", "consistency",
                "timeliness", "validity"}

# row-set-valued repair transforms (reference ts_gen_func)
_REPAIR_FUNCS = {"timestamp_repair", "value_fill", "value_repair"}


def _load_external(ext: dict) -> tuple[list[str], list[np.ndarray]]:
    """Materialize an external table (reference create_external_table.rs
    reads through object_store + DataFusion listing providers; here a
    local path reads directly and s3://, gcs://, azblob:// locations go
    through utils.objstore with the table's stored connection options)."""
    from ..utils import objstore

    path = ext["path"]
    # relative locations resolve against CNOSDB_EXTERNAL_DATA_ROOT when
    # absent from the cwd (test corpora reference fixture trees by
    # repo-relative path)
    root = os.environ.get("CNOSDB_EXTERNAL_DATA_ROOT")
    if root and "://" not in path and not os.path.isabs(path) \
            and not os.path.exists(path) \
            and os.path.exists(os.path.join(root, path)):
        path = os.path.join(root, path)
    src = objstore.open_source(path, ext.get("options"))
    if ext["fmt"] == "parquet":
        import pyarrow.parquet as pq

        table = pq.read_table(src)   # accepts files and directories
    elif ext["fmt"] in ("ndjson", "json"):
        import pyarrow.json as pj

        table = pj.read_json(src)
    else:
        import pyarrow as pa
        import pyarrow.csv as pc

        ropts = pc.ReadOptions(autogenerate_column_names=not ext.get(
            "header", True))
        if isinstance(src, str) and os.path.isdir(src):
            parts = sorted(os.path.join(src, f) for f in os.listdir(src)
                           if not f.startswith("."))
            table = pa.concat_tables(
                [pc.read_csv(p, read_options=ropts) for p in parts])
        else:
            table = pc.read_csv(src, read_options=ropts)
    names, cols = [], []
    for name in table.column_names:
        col = table.column(name)
        arr = col.to_numpy(zero_copy_only=False)
        if col.null_count and arr.dtype.kind == "f":
            # arrow NULLs land as NaN in to_numpy; NULL ≠ NaN — carry
            # them as object None so they render as empty cells
            nulls = np.asarray(col.is_null())
            arr = arr.astype(object)
            arr[nulls] = None
            names.append(name)
            cols.append(arr)
            continue
        if arr.dtype.kind == "M":
            # arrow timestamp columns (CSV type inference) → this
            # engine's i64 ns representation
            arr = arr.astype("datetime64[ns]").astype(np.int64)
        elif arr.dtype == object and len(arr) \
                and type(arr[0]).__name__ == "Timestamp":
            arr = np.array([int(v.value) for v in arr], dtype=np.int64)
        elif arr.dtype == object or arr.dtype.kind in ("U", "S"):
            arr = np.array([None if v is None else str(v)
                            for v in col.to_pylist()], dtype=object)
        names.append(name)
        cols.append(arr)
    declared = ext.get("columns") or []
    if declared:
        # declared column list (tpch.slt): positional rename + coercion
        names = [c[0] for c in declared[:len(cols)]] + names[len(declared):]
        for i, (_cn, sql_type) in enumerate(declared[:len(cols)]):
            t = sql_type.upper()
            a = cols[i]
            try:
                if t in ("NUMERIC", "DOUBLE", "FLOAT", "DECIMAL", "REAL"):
                    if a.dtype != object:
                        cols[i] = a.astype(np.float64)
                elif t in ("INTEGER", "INT", "BIGINT"):
                    if a.dtype != object and a.dtype.kind != "f":
                        cols[i] = a.astype(np.int64)
                elif t in ("VARCHAR", "STRING", "TEXT", "CHAR"):
                    if a.dtype != object:
                        cols[i] = np.array([str(v) for v in a],
                                           dtype=object)
            except (TypeError, ValueError):
                pass   # keep the inferred dtype on impossible coercions
    return names, cols


def _strip_time_conjuncts(e):
    """Drop top-level AND conjuncts that reference only `time`, returning
    the tag-only remainder (None when nothing remains). SHOW SERIES
    evaluates time separately against each series' data extent."""
    from .expr import BinOp

    if "time" not in e.columns():
        return e
    if isinstance(e, BinOp) and e.op == "and":
        left = _strip_time_conjuncts(e.left)
        right = _strip_time_conjuncts(e.right)
        if left is None:
            return right
        if right is None:
            return left
        return BinOp("and", left, right)
    if e.columns() <= {"time"}:
        return None
    raise PlanError(
        "SHOW SERIES: time predicates must be top-level AND conjuncts")


def _schema_padding(schema, col: str, n: int) -> np.ndarray:
    """All-invalid padding for a field absent from a vnode's batch, typed
    from the schema so cross-batch concatenation keeps the declared dtype
    (a BIGINT column must not decay to float64 because one vnode never
    saw it; reference returns typed arrow arrays with null validity)."""
    try:
        dt = schema.column(col).column_type.value_type.numpy_dtype()
    except Exception:
        dt = np.float64
    if dt is object:
        return np.full(n, None, dtype=object)
    return np.zeros(n, dtype=dt)


def _batches_bytes(batches) -> int:
    """Rough working-set estimate of scan batches for memory-pool gating."""
    total = 0
    for b in batches:
        total += b.ts.nbytes + b.sid_ordinal.nbytes
        for _vt, vals, valid in b.fields.values():
            total += getattr(vals, "nbytes", 0) + getattr(valid, "nbytes", 0)
    return total


# ------------------------------------------------- group-state spilling
def _acc_group_bytes(acc: dict) -> int:
    """Rough live bytes of a group accumulator (keys + partial values;
    sets/collect chunks dominate wide states)."""
    total = 0
    for key, parts in acc.items():
        total += 64 + 16 * len(key)
        for v in parts.values():
            if isinstance(v, set):
                total += 64 + 64 * len(v)
            elif isinstance(v, list):
                total += 64
                for ch in v:
                    if isinstance(ch, tuple):
                        total += sum(int(getattr(c, "nbytes", 16) or 16)
                                     for c in ch)
                    else:
                        total += int(getattr(ch, "nbytes", 16) or 16)
            else:
                total += 16 + int(getattr(v, "nbytes", 0) or 0)
    return total


def _merge_spill_entry(dst: dict, src: dict, phys_aggs):
    """Fold a LATER spill fragment's parts into an EARLIER one for the
    same group key. Semantics mirror _merge_partial per func exactly
    (count add, sum left-fold, min/max combine, first/last by strict
    timestamp so the earlier epoch wins ties, distinct-set union,
    collect-chunk extend in arrival order) — spilled and in-memory
    execution finalize bit-identically."""
    for a in phys_aggs:
        al = a.alias
        if a.func in ("first", "last"):
            if al not in src:
                continue
            v = src[al]
            ts = src.get(al + "__ts")
            cur = dst.get(al)
            cur_ts = dst.get(al + "__ts")
            better = (cur is None or cur_ts is None
                      or (a.func == "first" and ts < cur_ts)
                      or (a.func == "last" and ts > cur_ts))
            if better:
                dst[al] = v
                dst[al + "__ts"] = ts
            continue
        if al not in src:
            continue
        v = src[al]
        cur = dst.get(al)
        if a.func in ("count", "count_multi"):
            dst[al] = (cur or 0) + int(v)
        elif a.func == "sum":
            dst[al] = v if cur is None else cur + v
        elif a.func == "min":
            dst[al] = v if cur is None else min(cur, v)
        elif a.func == "max":
            dst[al] = v if cur is None else max(cur, v)
        elif a.func == "count_distinct":
            if cur is None:
                dst[al] = v
            else:
                cur.update(v)
        elif a.func in ("collect", "collect_ts", "collect2"):
            if cur is None:
                dst[al] = v
            else:
                cur.extend(v)


class _NoSpill:
    """Disabled-plane spiller: the aggregate paths call the same three
    hooks unconditionally, so the legacy path stays branch-free."""

    spill_count = 0
    spilled_bytes = 0

    def observe(self, acc) -> None:
        pass

    def finish(self, acc) -> dict:
        return acc

    def close(self) -> None:
        pass


class GroupSpiller:
    """Bounds group-by accumulator memory by spilling partial state to
    disk, bit-identically to the in-memory fold.

    Epoch discipline: the first time the live accumulator crosses the
    budget, its whole contents spill as epoch 0 and EVERY subsequent
    observe() spills unconditionally — each later epoch therefore holds
    at most one batch's contribution per key, so replaying epochs in
    order reproduces the exact left-fold association the in-memory path
    would have used (float sums stay bit-identical, first/last ties
    resolve to the same arrival). Entries carry their (epoch, position)
    of first appearance; the finished accumulator is rebuilt in global
    (epoch, pos) order, which is first-appearance insertion order —
    _finalize_aggregate's row order is unchanged.

    Files publish atomically (tmp + fsync + rename) behind the
    ``memory.spill`` fault point; key space is partitioned by stable
    hash so finish() holds one partition in memory at a time."""

    PARTITIONS = 8

    def __init__(self, plan, phys_aggs, budget_bytes: int):
        self.plan = plan
        self.phys_aggs = phys_aggs
        self.budget = int(budget_bytes)
        self._dir: str | None = None
        self._epoch = 0
        self._engaged = False
        self._booked = 0
        self._closed = False
        self.spill_count = 0
        self.spilled_bytes = 0

    # ------------------------------------------------------------ hooks
    def observe(self, acc: dict) -> None:
        est = _acc_group_bytes(acc)
        if self._engaged or (self.budget and est > self.budget):
            self._spill(acc, est)
            return
        delta = est - self._booked
        if delta > 0:
            memgov.book("query_groups", delta, action="grow")
            self._booked = est
            # charge BEFORE growing further: an over-budget query dies
            # here with MemoryExceeded while in-budget neighbors run on
            memgov.charge_query(delta, "group_state")

    def finish(self, acc: dict) -> dict:
        if not self._engaged:
            return acc
        self._spill(acc, _acc_group_bytes(acc))   # live tail → last epoch
        merged: list[tuple[int, int, tuple, dict]] = []
        for p in range(self.PARTITIONS):
            merged.extend(self._merge_partition(p))
        merged.sort(key=lambda e: (e[0], e[1]))
        out = {key: parts for _e, _pos, key, parts in merged}
        memgov.count("query_groups", "unspill")
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._booked:
            memgov.unbook("query_groups", self._booked)
            memgov.release_query(self._booked)
            self._booked = 0
        if self._dir is not None:
            import shutil

            shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None

    # --------------------------------------------------------- internals
    def _spill(self, acc: dict, est: int) -> None:
        if not acc:
            return
        self._engaged = True
        if self._dir is None:
            import tempfile

            self._dir = tempfile.mkdtemp(prefix="cnosdb-spill-")
        by_part: dict[int, list] = {}
        for pos, (key, parts) in enumerate(acc.items()):
            by_part.setdefault(hash(key) % self.PARTITIONS, []) \
                .append((pos, key, parts))
        import pickle

        for p, entries in by_part.items():
            path = os.path.join(self._dir,
                                f"p{p:02d}_e{self._epoch:06d}.spill")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(entries, f, protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            if faults.ENABLED:
                faults.fire("memory.spill", path=path, epoch=self._epoch)
            os.rename(tmp, path)
            self.spilled_bytes += os.path.getsize(path)
        self._epoch += 1
        self.spill_count += 1
        memgov.count("query_groups", "spill")
        stages.count("group_spill", 1)
        acc.clear()
        if self._booked:
            memgov.unbook("query_groups", self._booked)
            memgov.release_query(self._booked)
            self._booked = 0

    def _merge_partition(self, p: int) -> list[tuple[int, int, tuple, dict]]:
        import pickle

        assert self._dir is not None
        names = sorted(n for n in os.listdir(self._dir)
                       if n.startswith(f"p{p:02d}_e")
                       and n.endswith(".spill"))
        part: dict[tuple, list] = {}   # key → [epoch, pos, parts]
        for name in names:
            epoch = int(name[len(f"p{p:02d}_e"):-len(".spill")])
            with open(os.path.join(self._dir, name), "rb") as f:
                entries = pickle.load(f)
            for pos, key, parts in entries:
                cur = part.get(key)
                if cur is None:
                    part[key] = [epoch, pos, parts]
                else:
                    _merge_spill_entry(cur[2], parts, self.phys_aggs)
        return [(e, pos, key, parts)
                for key, (e, pos, parts) in part.items()]


def _out_name(it: ast.SelectItem) -> str:
    """Display name for a select item: SQL strips the relation qualifier
    from a plain column reference (SELECT c.host → column \"host\")."""
    if it.alias:
        return it.alias
    if isinstance(it.expr, Column):
        return it.expr.name.rsplit(".", 1)[-1]
    return it.expr.to_sql()


def _series_finalize(func: str, ts: np.ndarray, vals: np.ndarray, param):
    from . import tsfuncs

    order = np.argsort(ts, kind="stable")
    ts, vals = ts[order], np.asarray(vals)[order]
    if isinstance(param, tuple) and len(param) == 2 \
            and param[0] == "const_state":
        vals = np.full(len(ts), param[1], dtype=object)
        param = None
    if func == "increase":
        return tsfuncs.increase(ts, vals)
    if func == "sample":
        return tsfuncs.sample(vals, int(param) if param is not None else 1)
    if func == "gauge_agg":
        return tsfuncs.gauge_data(ts, vals)
    if func == "state_agg":
        return tsfuncs.state_data(ts, vals, compact=False)
    if func == "compact_state_agg":
        return tsfuncs.state_data(ts, vals, compact=True)
    # a degenerate group (<2 finite values) FAILS the query, matching the
    # reference's "At least two non-NaN values are needed" execution error
    # (function/data_quality.slt pins statement error for 1-row input)
    return tsfuncs.data_quality(func, ts, vals)


def _iso_ns(ns: int) -> str:
    """arrow timestamp rendering: ISO, fraction trimmed of trailing
    zeros, omitted when zero."""
    from datetime import datetime, timezone

    secs, frac = divmod(int(ns), 1_000_000_000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if frac:
        digits = f"{frac:09d}"
        while digits.endswith("000"):   # trim ns→us→ms like arrow
            digits = digits[:-3]
        base += "." + digits
    return base


def _insert_coerce(vt, v, col: str):
    """INSERT value → column type, with DataFusion's CAST semantics
    (type_conversion/between.slt pins 23.456 into BIGINT as 23;
    boolean.slt pins 1/0 into BOOLEAN as true/false)."""
    from ..models.schema import ValueType as VT

    is_bool = isinstance(v, (bool, np.bool_))
    try:
        if vt == VT.FLOAT:
            if is_bool:
                raise ValueError("BOOLEAN into DOUBLE")
            return float(v)
        if vt in (VT.INTEGER, VT.UNSIGNED):
            if is_bool:
                raise ValueError("BOOLEAN into BIGINT")
            if isinstance(v, float):
                if v != v or v in (float("inf"), float("-inf")):
                    raise ValueError("NaN/Inf into BIGINT")
                v = int(v)   # truncation toward zero (CAST semantics)
            elif isinstance(v, str):
                v = int(v.strip())
            v = int(v)
            if vt == VT.UNSIGNED and v < 0:
                raise ValueError("negative into UNSIGNED")
            return v
        if vt == VT.BOOLEAN:
            if is_bool:
                return bool(v)
            if isinstance(v, (int, float)):
                return v != 0
            if isinstance(v, str):
                from .expr import _parse_bool_str

                return _parse_bool_str(v)
            raise ValueError(f"{type(v).__name__} into BOOLEAN")
        if vt in (VT.STRING, VT.GEOMETRY):
            return v if isinstance(v, str) else str(v)
    except (ValueError, OverflowError) as e:
        raise ExecutionError(
            f"INSERT value {v!r} cannot be cast to the {vt.name} "
            f"column {col!r}: {e}")
    return v


def _arrow_type_name(sql_type: str) -> str:
    """Declared external-column SQL type → the arrow type name the
    reference's DESCRIBE prints (create_external_table.slt)."""
    t = sql_type.strip().upper()
    m = re.match(r"^DECIMAL\((\d+),\s*(\d+)\)$", t)
    if m:
        return f"Decimal128({m.group(1)}, {m.group(2)})"
    return {
        "BIGINT": "Int64", "BIGINT UNSIGNED": "UInt64",
        "INT": "Int32", "INTEGER": "Int32", "SMALLINT": "Int16",
        "TINYINT": "Int8", "DOUBLE": "Float64", "FLOAT": "Float32",
        "BOOLEAN": "Boolean", "STRING": "Utf8", "VARCHAR": "Utf8",
        "TEXT": "Utf8", "TIMESTAMP": "Timestamp(Nanosecond, None)",
        "DATE": "Date32",
    }.get(t, t.capitalize())


def _size_display(v) -> str:
    """'128MiB'/'300M' → the reference's byte-size rendering: parse to
    bytes (decimal K/M/G vs binary Ki/Mi/Gi suffixes), then humanize in
    BINARY units with full float precision — describe_database.slt pins
    wal_max_file_size '300M' as '286.102294921875 MiB'."""
    s = str(v).strip()
    m = re.match(r"^(\d+(?:\.\d+)?)\s*([KMGTP]?)(I?B?)$", s, re.I)
    if not m:
        return s
    num = float(m.group(1))
    unit, tail = m.group(2).upper(), m.group(3).upper()
    power = " KMGTP".index(unit) if unit else 0
    base = 1024 if (unit and tail.startswith("I")) else 1000
    nbytes = num * base ** power
    for p, uname in ((5, "PiB"), (4, "TiB"), (3, "GiB"), (2, "MiB"),
                     (1, "KiB")):
        if nbytes >= 1024 ** p:
            val = nbytes / 1024 ** p
            txt = str(int(val)) if val == int(val) else repr(val)
            return f"{txt} {uname}"
    txt = str(int(nbytes)) if nbytes == int(nbytes) else repr(nbytes)
    return f"{txt} B"


def _median_value(vals: np.ndarray):
    """Median with DataFusion's type semantics: integer inputs compute
    the even-count middle as (a + b) / 2 in INTEGER arithmetic
    (truncating division — approx_median.slt pins median([1,4,5,6]) = 4),
    floats interpolate."""
    def all_int(a):
        if np.issubdtype(a.dtype, np.integer):
            return True
        return a.dtype == object and len(a) and all(
            isinstance(x, (int, np.integer))
            and not isinstance(x, (bool, np.bool_)) for x in a)

    if all_int(vals):
        s = sorted(int(x) for x in vals)
        m = len(s)
        if m % 2:
            return s[m // 2]
        t = s[m // 2 - 1] + s[m // 2]
        return t // 2 if t >= 0 else -((-t) // 2)   # truncate toward 0
    return float(np.median(vals.astype(np.float64)))


def _cell_repr(v) -> str:
    """array_agg element rendering (bare values, arrow list style)."""
    if v is None:
        return "NULL"
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    if isinstance(v, (bool, np.bool_)):
        return "true" if v else "false"
    if isinstance(v, np.integer):
        return str(int(v))
    return str(v)


def _apply_finalizer(spec, parts: dict):
    """Scalar (per-group-dict) interpretation of a finalizer spec."""
    kind = spec[0]
    if kind == "mean":
        cnt = parts.get(spec[2], 0)
        if not cnt:
            return None
        return parts.get(spec[1], 0.0) / cnt
    if kind == "int":
        return int(parts.get(spec[1], 0))
    if kind == "zero":
        return 0
    if kind == "pass":
        return parts.get(spec[1])
    if kind == "distinct":
        vals = parts.get(spec[1])
        return len(vals) if vals is not None else 0
    if kind in ("median", "stddev", "stddev_pop", "var_samp", "var_pop",
                "mode", "array_agg", "bit_and", "bit_or", "bit_xor"):
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        vals = np.concatenate(chunks)
        if kind in ("bit_and", "bit_or", "bit_xor"):
            return rel.bit_reduce(kind, vals)
        if kind == "median":
            return _median_value(vals)
        if kind == "stddev":
            return float(np.std(vals.astype(np.float64), ddof=1)) \
                if len(vals) > 1 else None
        if kind == "stddev_pop":
            return float(np.std(vals.astype(np.float64), ddof=0))
        if kind == "var_samp":
            return float(np.var(vals.astype(np.float64), ddof=1)) \
                if len(vals) > 1 else None
        if kind == "var_pop":
            return float(np.var(vals.astype(np.float64), ddof=0))
        if kind == "array_agg":
            # rendered like arrow's list repr (reference array_agg.slt)
            return "[" + ", ".join(_cell_repr(v) for v in vals) + "]"
        uniq, counts = np.unique(vals, return_counts=True)
        return uniq[np.argmax(counts)]
    if kind == "array_ts":
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        ts = np.concatenate([c[0] for c in chunks])
        vals = np.concatenate([np.asarray(c[1], dtype=object)
                               for c in chunks])
        order = np.argsort(ts, kind="stable")
        if not spec[2]:
            order = order[::-1]
        vals = vals[order]
        if spec[3]:   # array_agg(time ...): elements render as arrow ts
            return "[" + ", ".join(_iso_ns(int(t)) for t in ts[order]) \
                + "]"
        return "[" + ", ".join(_cell_repr(v) for v in vals) + "]"
    if kind == "array_const":
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        n_rows = sum(len(c[0]) for c in chunks)
        return "[" + ", ".join([_cell_repr(spec[2])] * n_rows) + "]"
    if kind == "percentile":
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        vals = np.concatenate(chunks).astype(np.float64)
        return float(np.quantile(vals, spec[2]))
    if kind == "percentile_w_const":
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        vals = np.concatenate(chunks).astype(np.float64)
        w = np.full(len(vals), float(spec[2]))
        order = np.argsort(vals)
        vals, w = vals[order], w[order]
        cum = np.cumsum(w)
        if cum[-1] <= 0:
            return None
        target = spec[3] * cum[-1]
        return float(vals[np.searchsorted(cum, target, side="left")
                          .clip(0, len(vals) - 1)])
    if kind == "percentile_w":
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        vals = np.concatenate([c[0] for c in chunks]).astype(np.float64)
        w = np.concatenate([c[1] for c in chunks]).astype(np.float64)
        order = np.argsort(vals)
        vals, w = vals[order], w[order]
        cum = np.cumsum(w)
        if cum[-1] <= 0:
            return None
        target = spec[2] * cum[-1]
        return float(vals[np.searchsorted(cum, target, side="left")
                          .clip(0, len(vals) - 1)])
    if kind in ("corr", "covar_samp", "covar_pop"):
        chunks = parts.get(spec[1])
        if not chunks:
            return None
        x = np.concatenate([c[0] for c in chunks]).astype(np.float64)
        y = np.concatenate([c[1] for c in chunks]).astype(np.float64)
        if kind == "corr":
            if len(x) < 2 or np.std(x) == 0 or np.std(y) == 0:
                return None
            return float(np.corrcoef(x, y)[0, 1])
        ddof = 1 if kind == "covar_samp" else 0
        if len(x) <= ddof:
            return None
        return float(np.cov(x, y, ddof=ddof)[0, 1])
    if kind == "const_agg":
        rows = int(parts.get(spec[2], 0))
        func, value = spec[1], spec[3]
        if value is None:
            return None
        if func == "sum":
            return value * rows if rows else None
        if rows == 0:
            return None
        if func in ("avg", "mean", "median"):
            return float(value)
        if func in ("min", "max", "first", "last",
                    "bit_and", "bit_or", "bit_xor"):
            return value
        if func in ("stddev", "stddev_samp", "var", "var_samp"):
            return 0.0 if rows > 1 else None
        if func in ("stddev_pop", "var_pop"):
            return 0.0
        if func == "zero":
            return 0.0
        return None   # const_agg:null and unknown constants → NULL
    if kind == "series":
        chunks = parts.get(spec[2])
        if not chunks:
            return None
        ts = np.concatenate([c[0] for c in chunks])
        vals = np.concatenate([np.asarray(c[1]) for c in chunks])
        return _series_finalize(spec[1], ts, vals, spec[3])
    raise ExecutionError(f"bad finalizer {spec!r}")


def _render_output(plan, env: dict, n: int):
    """Evaluate output expressions and RENDER NULLs: a slot whose source
    aggregate is invalid (e.g. sum over an all-NULL group) must surface
    as NULL/NaN, not its 0 accumulator."""
    names, cols = [], []
    for name, expr in plan.output:
        if n == 0:
            names.append(name)
            cols.append(np.empty(0))
            continue
        v = expr.eval(env, np)
        if isinstance(v, DictArray):
            v = v.materialize()
        if np.isscalar(v) or getattr(v, "shape", None) == ():
            v = np.full(n, v)
        arr = np.asarray(v)
        vv = np.ones(n, dtype=bool)
        for c in expr_mod.propagating_columns(expr):
            vk = f"__valid__:{c}"
            if vk in env and len(env[vk]) == n:
                vv &= env[vk]
        if not vv.all():
            arr = arr.astype(object)
            arr[~vv] = None
        names.append(name)
        cols.append(arr)
    return names, cols


def _vector_finalize(spec, parts_env: dict, n: int):
    """Vectorized interpretation over whole partial columns.
    parts_env: alias → (values array, valid array|None)."""
    kind = spec[0]

    def col(alias, default=0.0):
        entry = parts_env.get(alias)
        if entry is None:
            return np.full(n, default), np.zeros(n, dtype=bool)
        v, valid = entry
        return v, (valid if valid is not None else np.ones(n, dtype=bool))

    if kind == "mean":
        s, sv = col(spec[1])
        c, _cv = col(spec[2], 0)
        c = c.astype(np.int64)
        with np.errstate(invalid="ignore", divide="ignore"):
            out = np.where(c > 0, s.astype(np.float64) / np.maximum(c, 1), np.nan)
        return out, c > 0
    if kind == "int":
        c, _ = col(spec[1], 0)
        return c.astype(np.int64), np.ones(n, dtype=bool)
    if kind == "zero":
        return np.zeros(n, dtype=np.int64), np.ones(n, dtype=bool)
    if kind == "const_agg":
        rows, _ = col(spec[2], 0)
        rows = rows.astype(np.int64)
        func, value = spec[1], spec[3]
        ok = rows > 0
        if value is None:
            return np.full(n, None, dtype=object), np.zeros(n, dtype=bool)
        if func == "sum":
            return np.where(ok, value * rows, 0), ok
        if func in ("avg", "mean", "median"):
            return np.where(ok, float(value), np.nan), ok
        if func in ("min", "max", "first", "last",
                    "bit_and", "bit_or", "bit_xor"):
            return np.where(ok, value, 0), ok
        if func in ("stddev", "stddev_samp", "var", "var_samp"):
            return np.zeros(n), rows > 1
        if func in ("stddev_pop", "var_pop"):
            return np.zeros(n), ok
        if func == "zero":
            return np.zeros(n), ok
        if func == "null":
            return np.full(n, None, dtype=object), np.zeros(n, dtype=bool)
        raise ExecutionError(f"bad const_agg {func!r}")
    if kind == "pass":
        return col(spec[1])
    if kind == "distinct":
        c, v = col(spec[1], 0)
        return c, v
    raise ExecutionError(f"bad finalizer {spec!r}")


# one shared NaN so cross-vnode NaN group keys collapse to a single dict
# entry (NaN != NaN defeats tuple keys; dict identity matches this object)
_NAN_KEY = float("nan")


def _canon_group_key(v):
    if isinstance(v, float) and v != v:
        return _NAN_KEY
    if isinstance(v, np.floating) and v != v:
        return _NAN_KEY
    return v


_VEC_MERGE_FUNCS = {"count", "sum", "min", "max", "first", "last"}


def _merge_results_vec(results, plan: AggregatePlan,
                       phys_aggs: list[AggSpec]):
    """Vectorized cross-vnode partial merge → one synthetic AggResult, or
    None when ineligible (string-field group axes, host aggregates,
    object-valued agg columns). This is the multi-vnode half of the 5×
    headline: the per-row python dict merge costs more than the kernels
    themselves at 100M-row scale (reference merges partials inside
    DataFusion's final AggregateExec, also columnar)."""
    from ..ops.tpu_exec import AggResult

    if plan.group_fields:
        return None
    if any(a.func not in _VEC_MERGE_FUNCS for a in phys_aggs):
        return None
    results = [r for r in results if r.n_rows]
    if not results:
        cols = {t: np.empty(0, dtype=object) for t in plan.group_tags}
        if plan.bucket is not None:
            cols["time"] = np.empty(0, dtype=np.int64)
        for a in phys_aggs:
            cols[a.alias] = np.empty(0)
        return AggResult(cols, 0)
    if any(r.gid is None for r in results):
        return None
    for r in results:
        for a in phys_aggs:
            col = r.columns.get(a.alias)
            if col is not None and col.dtype == object:
                return None   # string min/max etc: generic path
    # ---- global tag-group ids (label tables are tiny: one entry per
    # distinct tag combination per vnode)
    glab: dict[tuple, int] = {}
    gid_parts = []
    for r in results:
        lut = np.empty(len(r.labels), dtype=np.int64)
        for i, lab in enumerate(r.labels):
            lut[i] = glab.setdefault(lab, len(glab))
        gid_parts.append(lut[r.gid])
    gids = np.concatenate(gid_parts)
    n_lab = max(len(glab), 1)
    # ---- bucket-time codes
    if plan.bucket is not None:
        times = np.concatenate([r.columns["time"] for r in results])
        utimes, tcode = np.unique(times, return_inverse=True)
        n_t = len(utimes)
    else:
        utimes, tcode, n_t = None, np.zeros(len(gids), dtype=np.int64), 1
    code = gids * n_t + tcode
    k = n_lab * n_t
    occupied = np.zeros(k, dtype=bool)
    occupied[code] = True
    sel = np.nonzero(occupied)[0]
    pos = np.empty(k, dtype=np.int64)
    pos[sel] = np.arange(len(sel))
    out_cols: dict[str, np.ndarray] = {}
    out_valid: dict[str, np.ndarray] = {}
    # group label columns
    if plan.group_tags:
        lab_table = [None] * len(glab)
        for lab, g in glab.items():
            lab_table[g] = lab
        for i, t in enumerate(plan.group_tags):
            col = np.empty(len(glab), dtype=object)
            col[:] = [lab[i] for lab in lab_table]
            out_cols[t] = col[sel // n_t]
    if plan.bucket is not None:
        out_cols["time"] = utimes[sel % n_t]
    n_out = len(sel)
    for a in phys_aggs:
        vals = np.concatenate([
            np.asarray(r.columns[a.alias]) if a.alias in r.columns
            else np.zeros(r.n_rows) for r in results])
        valid = np.concatenate([
            r.valid[a.alias] if a.alias in r.valid
            else (np.ones(r.n_rows, dtype=bool) if a.alias in r.columns
                  else np.zeros(r.n_rows, dtype=bool))
            for r in results])
        vcode = pos[code[valid]]
        vv = vals[valid]
        if a.func == "count":
            acc = np.zeros(n_out, dtype=np.int64)
            np.add.at(acc, vcode, vv.astype(np.int64))
            out_cols[a.alias] = acc
        elif a.func == "sum":
            acc = np.zeros(n_out, dtype=vv.dtype if vv.dtype.kind in "iuf"
                           else np.float64)
            np.add.at(acc, vcode, vv)
            has = np.zeros(n_out, dtype=bool)
            has[vcode] = True
            out_cols[a.alias] = acc
            out_valid[a.alias] = has
        elif a.func in ("min", "max"):
            if vv.dtype.kind == "f":
                init = np.inf if a.func == "min" else -np.inf
            elif vv.dtype.kind == "u":
                init = np.iinfo(vv.dtype).max if a.func == "min" else 0
            else:
                ii = np.iinfo(np.int64)
                init = ii.max if a.func == "min" else ii.min
            acc = np.full(n_out, init, dtype=vv.dtype)
            red = np.minimum if a.func == "min" else np.maximum
            red.at(acc, vcode, vv)
            has = np.zeros(n_out, dtype=bool)
            has[vcode] = True
            out_cols[a.alias] = acc
            out_valid[a.alias] = has
        else:   # first / last by actual timestamp
            ts_key = a.alias + "__ts"
            ts = np.concatenate([
                np.asarray(r.columns[ts_key]) if ts_key in r.columns
                else np.zeros(r.n_rows, dtype=np.int64)
                for r in results])[valid]
            order = np.lexsort((ts, vcode))
            if a.func == "last":
                order = order[::-1]
            codes_sorted = vcode[order]
            _, firsts = np.unique(codes_sorted, return_index=True)
            rows = order[firsts]
            acc = np.zeros(n_out, dtype=vv.dtype)
            acc[vcode[rows]] = vv[rows]
            tacc = np.zeros(n_out, dtype=np.int64)
            tacc[vcode[rows]] = ts[rows]
            has = np.zeros(n_out, dtype=bool)
            has[vcode] = True
            out_cols[a.alias] = acc
            out_cols[ts_key] = tacc
            out_valid[a.alias] = has
    return AggResult(out_cols, n_out, out_valid)


def _merge_partial(acc: dict, result, plan: AggregatePlan,
                   phys_aggs: list[AggSpec]):
    n = result.n_rows
    if n == 0:
        return
    cols = result.columns
    gt = plan.group_tags + plan.group_fields
    for i in range(n):
        key = tuple(_canon_group_key(cols[t][i]) for t in gt)
        if plan.bucket is not None:
            key = key + (int(cols["time"][i]),)
        parts = acc.setdefault(key, {})
        for a in phys_aggs:
            if a.func == "count_distinct":
                continue
            if a.alias not in cols:
                continue
            valid = result.valid.get(a.alias)
            if valid is not None and not valid[i]:
                continue
            v = cols[a.alias][i]
            cur = parts.get(a.alias)
            if a.func == "count":
                parts[a.alias] = (cur or 0) + int(v)
            elif a.func == "sum":
                parts[a.alias] = v if cur is None else cur + v
            elif a.func == "min":
                parts[a.alias] = v if cur is None else min(cur, v)
            elif a.func == "max":
                parts[a.alias] = v if cur is None else max(cur, v)
            elif a.func in ("first", "last"):
                ts_col = cols.get(a.alias + "__ts")
                ts = int(ts_col[i]) if ts_col is not None else 0
                cur_ts = parts.get(a.alias + "__ts")
                better = (cur is None or cur_ts is None
                          or (a.func == "first" and ts < cur_ts)
                          or (a.func == "last" and ts > cur_ts))
                if better:
                    parts[a.alias] = v
                    parts[a.alias + "__ts"] = ts


def _merge_compressed_partials(acc: dict, batch, plan: AggregatePlan,
                               phys_aggs: list[AggSpec]):
    """Fold a batch's compressed-domain page partials into the generic
    accumulator. Key layout and merge semantics are _merge_partial's
    exactly — group tags from the partial's series key (same values
    _tag_group_layout labels carry), bucket time appended — so lane
    partials and kernel partials interleave bit-identically regardless
    of which pages the lane answered."""
    cp = getattr(batch, "compressed_partials", None)
    if not cp:
        return
    skeys = cp["series_keys"]
    for (sid, bts), parts in cp["rows"].items():
        sk = skeys.get(sid)
        tags = sk.tag_dict() if sk is not None else {}
        key = tuple(_canon_group_key(tags.get(t))
                    for t in plan.group_tags)
        if plan.bucket is not None:
            key = key + (int(bts),)
        dst = acc.setdefault(key, {})
        for a in phys_aggs:
            if a.alias not in parts:
                continue
            v = parts[a.alias]
            cur = dst.get(a.alias)
            if a.func == "count":
                dst[a.alias] = (cur or 0) + int(v)
            elif a.func == "sum":
                dst[a.alias] = v if cur is None else cur + v
            elif a.func == "min":
                dst[a.alias] = v if cur is None else min(cur, v)
            elif a.func == "max":
                dst[a.alias] = v if cur is None else max(cur, v)
            elif a.func in ("first", "last"):
                ts = int(parts.get(a.alias + "__ts", 0))
                cur_ts = dst.get(a.alias + "__ts")
                better = (cur is None or cur_ts is None
                          or (a.func == "first" and ts < cur_ts)
                          or (a.func == "last" and ts > cur_ts))
                if better:
                    dst[a.alias] = v
                    dst[a.alias + "__ts"] = ts


def _batch_column(batch, plan, col, native: bool = False):
    """(values, valid) for a field / tag / time column of a scan batch,
    or (None, None) when absent from this vnode. native=True skips the
    object-array conversion (the vectorized DISTINCT path factorizes
    native dtypes — and DictArray codes — directly)."""
    if col in batch.fields:
        vt, vals, valid = batch.fields[col]
        if native:
            return vals, valid
        return as_object_array(vals), valid
    if col in plan.schema.tag_names():
        per_series = np.array(
            [(k.tag_value(col) if k is not None else None)
             for k in batch.series_keys], dtype=object)
        vals = per_series[batch.sid_ordinal]
        return vals, np.array([v is not None for v in vals], dtype=bool)
    if col == "time":
        return batch.ts, np.ones(batch.n_rows, dtype=bool)
    return None, None


def _merge_distinct(acc: dict, batch, plan: AggregatePlan, spec: AggSpec):
    """Host-side COUNT(DISTINCT col) + collect/count_multi partials per
    group.

    Vectorized: rows map to combined (tag × field × bucket) segment ids
    through ops.tpu_exec.host_group_layout — the same per-batch cached
    factorization the segment kernels use, so warm rescans pay nothing —
    and every per-group update happens in bulk: count_multi via bincount,
    collect via one stable argsort + run slicing, DISTINCT via sorted
    unique (group, value) code pairs (ops.group_agg). Python work is
    O(occupied groups), not O(rows). The per-row fold survives only as
    the fallback for unfactorizable payloads."""
    native = spec.func == "count_distinct"
    vals, valid = _batch_column(batch, plan, spec.column, native=native)
    if vals is None:
        return
    vals2 = None
    if spec.func == "collect2":
        vals2, valid2 = _batch_column(batch, plan, spec.param)
        if vals2 is None:
            return
        valid = valid & valid2
    if spec.func == "count_multi":
        for extra in spec.param or []:
            _ev, evalid = _batch_column(batch, plan, extra)
            if _ev is None:
                return
            valid = valid & evalid
    # reuse the group/bucket mapping by building keys per row
    from ..ops.tpu_exec import _filter_env

    mask = np.ones(batch.n_rows, dtype=bool)
    if plan.filter is not None:
        env = _filter_env(batch, needed=plan.filter.columns())
        missing = [c for c in plan.filter.columns() if c not in env]
        for c in missing:
            env[c] = np.zeros(batch.n_rows)
            env[f"__valid__:{c}"] = np.zeros(batch.n_rows, dtype=bool)
        mask = np.asarray(plan.filter.eval(env, np), dtype=bool)
        if mask.shape == ():
            mask = np.full(batch.n_rows, bool(mask))
    mask = mask & valid
    buckets = None
    if plan.bucket is not None:
        origin, interval = plan.bucket
        buckets = origin + ((batch.ts - origin) // interval) * interval
    if _merge_distinct_vec(acc, batch, plan, spec, vals, vals2, mask):
        return
    # ------------------------------------------- scalar fallback
    if isinstance(vals, DictArray):
        vals = as_object_array(vals)
    tagmaps = []
    for k in batch.series_keys:
        tags = k.tag_dict() if k is not None else {}
        tagmaps.append(tuple(tags.get(t) for t in plan.group_tags))
    gf_cols = []
    for fc in plan.group_fields:
        gv, gok = _batch_column(batch, plan, fc)
        if gv is None:
            gv = np.empty(batch.n_rows, dtype=object)
            gok = np.zeros(batch.n_rows, dtype=bool)
        gf_cols.append((gv, gok))

    def row_key(i):
        key = tagmaps[batch.sid_ordinal[i]]
        for gv, gok in gf_cols:
            key = key + ((_canon_group_key(gv[i]) if gok[i] else None),)
        if plan.bucket is not None:
            key = key + (int(buckets[i]),)
        return key

    collect = spec.func in ("collect", "collect_ts", "collect2")
    idxs = np.nonzero(mask)[0]
    if spec.func == "count_multi":
        if plan.bucket is not None or plan.group_tags or plan.group_fields:
            for i in idxs:
                parts = acc.setdefault(row_key(i), {})
                parts[spec.alias] = parts.get(spec.alias, 0) + 1
        else:
            parts = acc.setdefault((), {})
            parts[spec.alias] = parts.get(spec.alias, 0) + len(idxs)
        return
    if collect:
        # group indices first, slice values in bulk per group
        group_rows: dict[tuple, list[int]] = {}
        for i in idxs:
            group_rows.setdefault(row_key(i), []).append(i)
        arr = np.asarray(vals)
        with_ts = spec.func == "collect_ts"
        arr2 = np.asarray(vals2) if vals2 is not None else None
        for key, rows in group_rows.items():
            parts = acc.setdefault(key, {})
            if spec.func == "collect2":
                chunk = (arr[rows], arr2[rows])
            elif with_ts:
                chunk = (batch.ts[rows], arr[rows])
            else:
                chunk = arr[rows]
            parts.setdefault(spec.alias, []).append(chunk)
        return
    for i in idxs:
        parts = acc.setdefault(row_key(i), {})
        s = parts.setdefault(spec.alias, set())
        s.add(vals[i])


def _merge_distinct_vec(acc: dict, batch, plan: AggregatePlan,
                        spec: AggSpec, vals, vals2,
                        mask: np.ndarray) -> bool:
    """Bulk per-group merge of one host aggregate over one batch.
    Returns False when the payload defeats factorization (caller keeps
    the scalar fold). Segment layout (and its decode tables) comes from
    the ScanToken-persistent caches shared with the kernel path."""
    from ..ops import group_agg as _ga
    from ..ops.tpu_exec import host_group_layout

    try:
        layout = host_group_layout(batch, plan.group_tags,
                                   plan.group_fields, plan.bucket)
    except Exception:
        stages.count_error("executor.group_layout")
        return False
    if layout is None:
        return False        # empty batch: scalar path keeps global-key rows
    idx = np.nonzero(mask)[0]
    globl = not (plan.bucket is not None or plan.group_tags
                 or plan.group_fields)
    if spec.func == "count_multi" and globl:
        # global count_multi creates its row even when no rows match
        parts = acc.setdefault((), {})
        parts[spec.alias] = parts.get(spec.alias, 0) + len(idx)
        return True
    # occupied segments only — never allocate num_segments-sized arrays
    # (tag × bucket cardinality is unbounded on this host path)
    useg, inv = np.unique(layout.seg_ids[idx].astype(np.int64),
                          return_inverse=True)
    inv = inv.astype(np.int64).ravel()

    def seg_keys(segs: np.ndarray) -> list[tuple]:
        """Decode combined segment ids → group key tuples (tag values,
        field values, bucket start) — the exact key layout
        _merge_partial builds from the kernel's label columns."""
        nb = max(layout.n_buckets, 1)
        bkt = segs % nb
        rem = segs // nb
        peeled = []
        for dim, dic in zip(reversed(layout.gf_dims),
                            reversed(layout.gf_dicts)):
            peeled.append((rem % dim, dic))
            rem = rem // dim
        peeled.reverse()
        keys = []
        bs = layout.bucket_starts
        for i in range(len(segs)):
            key = layout.group_labels[int(rem[i])]
            for codes_arr, dic in peeled:
                c = int(codes_arr[i])
                key = key + ((_canon_group_key(dic[c]) if c < len(dic)
                              else None),)
            if plan.bucket is not None:
                key = key + (int(bs[int(bkt[i])]),)
            keys.append(key)
        return keys

    if spec.func == "count_multi":
        cnt = np.bincount(inv, minlength=len(useg))
        for key, c in zip(seg_keys(useg), cnt):
            parts = acc.setdefault(key, {})
            parts[spec.alias] = parts.get(spec.alias, 0) + int(c)
        return True
    if spec.func in ("collect", "collect_ts", "collect2"):
        order, bounds, run_codes = _ga.grouped_order(inv)
        arr = np.asarray(vals)
        arr2 = np.asarray(vals2) if vals2 is not None else None
        with_ts = spec.func == "collect_ts"
        keys = seg_keys(useg[run_codes.astype(np.int64)])
        for k, key in enumerate(keys):
            rows = idx[order[bounds[k]:bounds[k + 1]]]
            if spec.func == "collect2":
                chunk = (arr[rows], arr2[rows])
            elif with_ts:
                chunk = (batch.ts[rows], arr[rows])
            else:
                chunk = arr[rows]
            acc.setdefault(key, {}).setdefault(spec.alias, []).append(chunk)
        return True
    # ---- count(DISTINCT): sorted unique (group, value) code pairs
    if isinstance(vals, DictArray):
        # dictionary codes ARE the factorization (values unique by
        # construction — the gf group axis makes the same assumption)
        codes = vals.codes.astype(np.int64)[idx]
        dic = vals.values
        nv = len(dic)
    else:
        f = _ga.factorize(np.asarray(vals)[idx])
        if f is None:
            return False
        codes, dic, nv = f.codes, f.values, f.n_values
    pairs = _ga.distinct_pairs(inv, codes, nv)
    _ga._count("distinct_sort")
    stages.count("distinct_path.sort")
    nvm = max(nv, 1)
    pseg = pairs // nvm
    pval = pairs % nvm
    if not len(pairs):
        return True
    starts = np.nonzero(np.concatenate(
        ([True], pseg[1:] != pseg[:-1])))[0]
    ends = np.append(starts[1:], len(pairs))
    for k, key in enumerate(seg_keys(useg[pseg[starts]])):
        s = acc.setdefault(key, {}).setdefault(spec.alias, set())
        s.update(dic[pval[starts[k]:ends[k]]].tolist())
    return True


def _apply_gapfill(plan: AggregatePlan, rs: ResultSet) -> ResultSet:
    """Expand to a dense (group × bucket) grid; fill per locf/interpolate
    (reference extension/expr scalar_function gapfill/locf/interpolate).

    Vectorized over the grid: rows scatter into a (n_groups, n_buckets)
    matrix in one fancy-indexed assignment, locf is a row-wise
    maximum.accumulate of last-known indices (object columns included —
    locf's semantics there are positional, not arithmetic), and
    interpolate stays np.interp per group. Python work is O(result rows
    + groups), never O(groups × grid)."""
    origin, interval = plan.bucket
    cols = {n: c for n, c in zip(rs.names, rs.columns)}
    # outputs may alias the bucket ("t") and tags: resolve via plan.output
    time_name = None
    tag_name_of: dict[str, str] = {}
    for name, expr in plan.output:
        if isinstance(expr, Column):
            if expr.name == "time":
                time_name = name
            elif expr.name in plan.group_tags:
                tag_name_of[expr.name] = name
    if time_name is None or time_name not in cols or rs.n_rows == 0:
        return rs
    times = cols[time_name].astype(np.int64)
    # grid bounds: the query's time range when bounded, else observed range
    lo = times.min()
    hi = times.max()
    if not plan.time_ranges.is_all:
        qlo, qhi = plan.time_ranges.min_ts, plan.time_ranges.max_ts
        if qlo > -(2**62):
            lo = origin + ((qlo - origin) // interval) * interval
        if qhi < 2**62:
            hi = origin + ((qhi - origin) // interval) * interval
    grid = np.arange(lo, hi + 1, interval, dtype=np.int64)
    G = len(grid)
    gt = [tag_name_of.get(t, t) for t in plan.group_tags if
          tag_name_of.get(t, t) in cols]
    group_keys = list(zip(*[cols[t] for t in gt])) if gt else [()] * rs.n_rows
    # group ids per row (tag keys are arbitrary objects: dict factorize),
    # renumbered into the output order (sorted by stringified key)
    gmap: dict[tuple, int] = {}
    gids = np.empty(rs.n_rows, dtype=np.int64)
    for i, k in enumerate(group_keys):
        gids[i] = gmap.setdefault(tuple(k), len(gmap))
    sorted_keys = sorted(gmap, key=lambda k: tuple(str(x) for x in k))
    rank = np.empty(len(gmap), dtype=np.int64)
    for pos, key in enumerate(sorted_keys):
        rank[gmap[key]] = pos
    ng = len(sorted_keys)
    bi = (times - lo) // interval
    ok = (bi >= 0) & (bi < G)
    # later rows win duplicate (group, bucket) cells — same as the old
    # dict-of-rows construction
    flat = rank[gids[ok]] * G + bi[ok]

    def _locf2d(vals: np.ndarray, known: np.ndarray) -> np.ndarray:
        """Row-wise forward fill: carry the last known column index."""
        src_col = np.where(known, np.arange(G)[None, :], -1)
        src_col = np.maximum.accumulate(src_col, axis=1)
        filled = src_col >= 0
        rows = np.broadcast_to(np.arange(ng)[:, None], (ng, G))
        out = vals.copy()
        out[filled] = vals[rows[filled], src_col[filled]]
        return out

    agg_names = [n for n in rs.names if n not in gt and n != time_name]
    out_cols_by_name: dict[str, np.ndarray] = {}
    for name in agg_names:
        src = cols[name]
        method = plan.fill_methods.get(name)
        if src.dtype == object:
            # string-valued aggregates: grid holes stay None; only locf
            # makes sense for them
            vals = np.full(ng * G, None, dtype=object)
            vals[flat] = src[ok]
            vals = vals.reshape(ng, G)
            if method == "locf":
                known = np.frompyfunc(
                    lambda v: v is not None, 1, 1)(vals).astype(bool)
                vals = _locf2d(vals, known)
            out_cols_by_name[name] = vals.ravel()
            continue
        vals = np.full(ng * G, np.nan)
        vals[flat] = src[ok].astype(np.float64)
        vals = vals.reshape(ng, G)
        if method == "locf":
            vals = _locf2d(vals, ~np.isnan(vals))
        elif method == "interpolate":
            gridf = grid.astype(np.float64)
            for r in range(ng):
                row = vals[r]
                known = ~np.isnan(row)
                if known.sum() < 2:
                    continue
                missing = ~known
                interp = np.interp(gridf[missing], gridf[known], row[known])
                # strict interpolation: no extrapolation beyond endpoints
                mlo, mhi = grid[known][0], grid[known][-1]
                inside = (grid[missing] >= mlo) & (grid[missing] <= mhi)
                fill = np.full(int(missing.sum()), np.nan)
                fill[inside] = interp[inside]
                row[missing] = fill
        out_cols_by_name[name] = vals.ravel()
    new_cols = []
    for n in rs.names:
        if n == time_name:
            new_cols.append(np.tile(grid, ng))
        elif n in gt:
            i = gt.index(n)
            col = np.empty(ng * G, dtype=object)
            for pos, key in enumerate(sorted_keys):
                col[pos * G:(pos + 1) * G] = key[i]
            new_cols.append(col)
        else:
            new_cols.append(out_cols_by_name[n])
    return ResultSet(rs.names, new_cols)


# NULLS LAST ascending, FIRST descending — DataFusion's defaults, which
# the reference inherits; shared with the window-function order keys
_null_safe_key = rel.null_safe_key


def _positional_order(order_by, rs: ResultSet):
    """ORDER BY n (a bare integer literal) is positional over the output
    columns in every SQL dialect; resolve it to the column array itself so
    each _order_limit caller (set-op chain, relational join path, scan
    path) gets it without needing the name in its env."""
    out = []
    for oe, asc in order_by:
        pos = oe.value if isinstance(oe, Literal) else oe
        if isinstance(pos, int) and not isinstance(pos, bool):
            if not 1 <= pos <= len(rs.names):
                raise QueryError(f"ORDER BY position {pos} is out of range")
            oe = np.asarray(rs.columns[pos - 1])
        out.append((oe, asc))
    return out


def _order_limit(rs: ResultSet, order_by, limit, offset, env) -> ResultSet:
    n = rs.n_rows
    if n and order_by:
        order_by = _positional_order(order_by, rs)
        keys = []
        for oe, asc in reversed(order_by):
            v = oe if isinstance(oe, np.ndarray) \
                else oe.eval(env, np) if isinstance(oe, Expr) else env[oe]
            vals, nulls = _null_safe_key(np.asarray(v))
            keys.append(vals)
            if nulls is not None:
                keys.append(nulls)  # later key = higher priority in lexsort
        idx = None
        if limit is not None and len(order_by) == 1 and len(keys) == 1:
            # ORDER BY key LIMIT k: select-then-gather top-k
            # (ops/strkernels; device threshold on TPU) instead of a full
            # sort — bit-identical tie order, or None → full sort below
            from ..ops import strkernels

            idx = strkernels.topk_order_indices(
                keys[0], None, order_by[0][1], (offset or 0) + limit)
        if idx is None:
            idx = np.lexsort(keys)
            # lexsort is ascending on all; apply desc by flipping per-key
            # is complex — handle single-key desc and uniform direction
            # fast paths
            if all(not asc for _, asc in order_by):
                idx = idx[::-1]
            elif not all(asc for _, asc in order_by):
                idx = _mixed_order(order_by, env, n)
        rs = ResultSet(rs.names, [c[idx] for c in rs.columns])
    if offset:
        rs = ResultSet(rs.names, [c[offset:] for c in rs.columns])
    if limit is not None:
        rs = ResultSet(rs.names, [c[:limit] for c in rs.columns])
    return rs


def _concat_cols(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Concatenate union branches; mixed dtypes fall back to object."""
    if a.dtype == b.dtype:
        return np.concatenate([a, b])
    if a.dtype != object and b.dtype != object:
        try:
            return np.concatenate([a.astype(np.float64),
                                   b.astype(np.float64)])
        except (TypeError, ValueError):
            pass
    return np.concatenate([a.astype(object), b.astype(object)])


_NAN_KEY = object()  # NULL/NaN rows compare equal in DISTINCT and set ops


def _row_keys(columns) -> list:
    """Hashable per-row keys over a column set. Float NaN (the NULL /
    outer-join padding value) maps to a shared token so NULLs are not
    distinct from each other — SQL DISTINCT / set-operation semantics."""
    if not columns:
        return []
    keys = []
    for i in range(len(columns[0])):
        key = []
        for c in columns:
            v = c[i] if c.dtype == object else c[i].item()
            if v is None or (isinstance(v, float) and v != v):
                v = _NAN_KEY  # None (object col) and NaN (float col) are
                # both NULL; they must match across branch dtypes
            key.append(v)
        keys.append(tuple(key))
    return keys


def _set_op_cols(left: list, right: list, op: str, all_: bool) -> list:
    """INTERSECT/EXCEPT over column sets, preserving left-operand row
    order. Bag semantics for ALL (INTERSECT ALL keeps min(l,r) copies of
    a row, EXCEPT ALL keeps l−r); the distinct forms dedupe the output.
    The reference lowers these to DataFusion semi/anti joins + distinct
    (query_server inherits them from its forked sqlparser/DataFusion)."""
    from collections import Counter

    budget = Counter(_row_keys(right))
    keep: list[int] = []
    if all_:
        for i, k in enumerate(_row_keys(left)):
            if budget[k] > 0:
                budget[k] -= 1
                if op == "intersect":
                    keep.append(i)
            elif op == "except":
                keep.append(i)
    else:
        seen = set()
        for i, k in enumerate(_row_keys(left)):
            if k in seen:
                continue
            seen.add(k)
            if (budget[k] > 0) == (op == "intersect"):
                keep.append(i)
    idx = np.array(keep, dtype=np.int64)
    return [c[idx] for c in left]


def _mixed_order(order_by, env, n):
    """Mixed asc/desc via one lexsort over rank-inverted keys.

    Reversing a stable ascending argsort would reverse ties and break
    lower-priority keys; instead descending keys become negated dense
    ranks (np.unique inverse), which lexsort ascends over correctly."""
    keys = []
    for oe, asc in reversed(order_by):
        v = oe if isinstance(oe, np.ndarray) \
            else oe.eval(env, np) if isinstance(oe, Expr) else env[oe]
        vals, nulls = _null_safe_key(np.asarray(v))
        if not asc:
            _, inv = np.unique(vals, return_inverse=True)
            vals = -inv.astype(np.int64)
        keys.append(vals)
        if nulls is not None:
            keys.append(nulls if asc else -nulls)
    return np.lexsort(keys)
