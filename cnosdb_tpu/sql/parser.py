"""SQL lexer + recursive-descent parser.

Role-parity with the reference's parser (query_server/query/src/sql/
parser.rs, 3 255 LoC wrapping sqlparser-rs): standard SELECT plus the
CnosDB statement set. Built from scratch (no sqlparser dependency exists
in this environment): a regex lexer and precedence-climbing expression
parser producing sql.ast nodes over the sql.expr IR.
"""
from __future__ import annotations

import re
from datetime import datetime, timezone

from ..errors import ParserError
from . import ast
from .expr import (
    Between, BinOp, Column, Expr, Func, InList, InSubquery, IsNull, Like,
    Literal, Subquery, UnaryOp, WindowFunc,
)

# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------
_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+(?:[eE][+-]?\d+)?)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<sysvar>@@[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|\|\||@@?|<|>|=|\+|-|\*|/|%|\^|\(|\)|\[|\]|,|\.|;)
    """,
    re.VERBOSE | re.DOTALL,
)


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.value}"


def tokenize(sql: str) -> list[Token]:
    out = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise ParserError(f"unexpected character {sql[pos]!r}", at=pos)
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "string":
            out.append(Token("string", text[1:-1].replace("''", "'"), m.start()))
        elif kind == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        elif kind == "ident":
            # unquoted identifiers fold to lowercase (DataFusion/standard
            # SQL: `Order by Time` resolves the `time` column; quoted
            # identifiers above preserve case)
            out.append(Token("ident", text.lower(), m.start()))
        elif kind == "sysvar":
            out.append(Token("sysvar", text[2:].lower(), m.start()))
        elif kind == "number":
            out.append(Token("number", text, m.start()))
        else:
            out.append(Token("op", text, m.start()))
    out.append(Token("eof", "", n))
    return out


_INTERVAL_UNITS = {
    "nanosecond": 1, "nanoseconds": 1,
    "microsecond": 1_000, "microseconds": 1_000,
    "millisecond": 1_000_000, "milliseconds": 1_000_000,
    "second": 10**9, "seconds": 10**9,
    "minute": 60 * 10**9, "minutes": 60 * 10**9,
    "hour": 3600 * 10**9, "hours": 3600 * 10**9,
    "day": 86400 * 10**9, "days": 86400 * 10**9,
    "week": 7 * 86400 * 10**9, "weeks": 7 * 86400 * 10**9,
    "month": 30 * 86400 * 10**9, "months": 30 * 86400 * 10**9,
    "year": 365 * 86400 * 10**9, "years": 365 * 86400 * 10**9,
}

_SHORT_UNITS = {
    "ns": 1, "us": 1_000, "ms": 1_000_000, "s": 10**9,
    "m": 60 * 10**9, "h": 3600 * 10**9, "d": 86400 * 10**9,
    "w": 7 * 86400 * 10**9, "y": 365 * 86400 * 10**9,
}


def parse_interval_string(s: str) -> int:
    """'1 minute', '10m', '1 hour 30 minutes' → ns (months/years at
    their fixed 30d/365d equivalents — unchanged legacy behavior for
    bucketing; date arithmetic uses parse_interval_parts for
    calendar-true months)."""
    return parse_interval_parts(s)[0]


_MONTH_UNITS = {"month": 1, "months": 1, "mon": 1, "mons": 1,
                "year": 12, "years": 12, "y": 12}


def parse_interval_parts(s: str) -> tuple[int, int, int]:
    """'1 year 2 months 3 days' → (legacy total ns with months/years at
    30d/365d, symbolic months, sub-month ns). The symbolic months let
    date + INTERVAL apply calendar arithmetic (arrow IntervalMonthDayNano
    — tpch date '1993-07-01' + 3 months is 1993-10-01, not +90 days)."""
    s = s.strip().lower()
    legacy = 0
    sub_ns = 0
    months = 0
    m_all = re.findall(r"(\d+(?:\.\d+)?)\s*([a-z]+)", s)
    if not m_all:
        raise ParserError(f"bad interval {s!r}")
    for num, unit in m_all:
        factor = _INTERVAL_UNITS.get(unit) or _SHORT_UNITS.get(unit)
        if factor is None:
            raise ParserError(f"bad interval unit {unit!r}")
        legacy += int(float(num) * factor)
        if unit in _MONTH_UNITS and float(num) == int(float(num)):
            months += int(float(num)) * _MONTH_UNITS[unit]
        else:
            sub_ns += int(float(num) * factor)
    return legacy, months, sub_ns


def parse_timestamp_string(s: str) -> int:
    """RFC3339-ish → ns since epoch (UTC assumed when naive)."""
    t = s.strip()
    try:
        if t.endswith("Z"):
            t = t[:-1] + "+00:00"
        frac_ns = 0
        m = re.search(r"\.(\d+)", t)
        if m and len(m.group(1)) > 6:
            digits = m.group(1)
            frac_ns = int(digits[6:].ljust(3, "0")[:3])
            t = t.replace("." + digits, "." + digits[:6])
        dt = datetime.fromisoformat(t)
        if dt.tzinfo is None:
            dt = dt.replace(tzinfo=timezone.utc)
        # exact integer arithmetic: float seconds lose ns precision at
        # ~1e18 (dt.timestamp()*1e9 rounds .005 s to 4999936 ns)
        delta = dt - datetime(1970, 1, 1, tzinfo=timezone.utc)
        secs = delta.days * 86400 + delta.seconds
        return secs * 1_000_000_000 + delta.microseconds * 1_000 + frac_ns
    except ParserError:
        raise
    except Exception:
        raise ParserError(f"bad timestamp {s!r}")


# system variables (reference extension/variable/: @@cluster_name etc.)
_SYSTEM_VARS = {
    "cluster_name": "cluster_xxx",
    "server_version": "2.4.3",
    "deployment_mode": "singleton",
    "node_id": "1001",
}


# tenant limiter option groups (reference limiter_config: ALTER TENANT
# SET object_config ... , coord_data_in remote_max = N ...)
_LIMITER_GROUPS = {
    "OBJECT_CONFIG", "COORD_DATA_IN", "COORD_DATA_OUT", "COORD_QUERIES",
    "COORD_WRITES", "HTTP_DATA_IN", "HTTP_DATA_OUT", "HTTP_QUERIES",
    "HTTP_WRITES",
}


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------
class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def kw(self) -> str | None:
        t = self.peek()
        return t.value.upper() if t.kind == "ident" else None

    def _peek_op_at(self, offset: int) -> str | None:
        j = self.i + offset
        if j < len(self.tokens) and self.tokens[j].kind == "op":
            return self.tokens[j].value
        return None

    def _peek_kw_at(self, offset: int) -> str | None:
        j = self.i + offset
        if j < len(self.tokens) and self.tokens[j].kind == "ident":
            return self.tokens[j].value.upper()
        return None

    def accept_kw(self, *kws: str) -> bool:
        if self.kw() in kws:
            self.next()
            return True
        return False

    def expect_kw(self, *kws: str) -> str:
        k = self.kw()
        if k not in kws:
            raise ParserError(f"expected {'/'.join(kws)}, got {self.peek().value!r}")
        self.next()
        return k

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise ParserError(f"expected {op!r}, got {self.peek().value!r}")

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind != "ident":
            raise ParserError(f"expected identifier, got {t.value!r}")
        return self.next().value

    def _ident_parens(self) -> list[str]:
        self.expect_op("(")
        out = [self.expect_ident()]
        while self.accept_op(","):
            out.append(self.expect_ident())
        self.expect_op(")")
        return out

    def _parse_limiter_pairs(self) -> dict:
        """`key = <int> key = <int> ...` after a limiter group name;
        stops when the next token is not an `ident =` pair (the next
        group name or a comma follows)."""
        out: dict = {}
        while (self.peek().kind == "ident"
               and self.i + 1 < len(self.tokens)
               and self.tokens[self.i + 1].kind == "op"
               and self.tokens[self.i + 1].value == "="):
            key = self.next().value.lower()
            self.next()   # '='
            out[key] = int(self.expect_number())
        if not out:
            raise ParserError("limiter option group expects key = value")
        return out

    def _parse_kv_parens(self) -> dict:
        """(key = 'value', flag = true, n = 3) → dict — the option-list
        form of CONNECTION/OPTIONS clauses (reference parser.rs:1716-1790
        parse_connection_options / sql option lists)."""
        out: dict = {}
        self.expect_op("(")
        if not self.accept_op(")"):
            while True:
                key = self.expect_ident().lower()
                self.expect_op("=")
                t = self.peek()
                if t.kind == "string":
                    out[key] = self.expect_string()
                elif t.kind == "number":
                    out[key] = self.expect_number()
                elif self.accept_kw("TRUE"):
                    out[key] = True
                elif self.accept_kw("FALSE"):
                    out[key] = False
                else:
                    out[key] = self.expect_ident()
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return out

    def expect_string(self) -> str:
        t = self.peek()
        if t.kind != "string":
            raise ParserError(f"expected string literal, got {t.value!r}")
        return self.next().value

    def expect_number(self) -> float | int:
        t = self.peek()
        if t.kind != "number":
            raise ParserError(f"expected number, got {t.value!r}")
        self.next()
        return _num(t.value)

    # -- entry -----------------------------------------------------------
    def parse_statements(self) -> list:
        stmts = []
        while self.peek().kind != "eof":
            if self.accept_op(";"):
                continue
            stmts.append(self.parse_statement())
            if self.peek().kind != "eof":
                self.expect_op(";")
        return stmts

    def parse_statement(self):
        k = self.kw()
        if k in ("SELECT", "WITH"):
            return self.parse_query()
        if k == "EXPLAIN":
            self.next()
            analyze = self.accept_kw("ANALYZE")
            verbose = self.accept_kw("VERBOSE")
            return ast.ExplainStmt(self.parse_statement(), analyze, verbose)
        if k == "CREATE":
            return self.parse_create()
        if k == "DROP":
            return self.parse_drop()
        if k == "ALTER":
            return self.parse_alter()
        if k == "SHOW":
            return self.parse_show()
        if k in ("DESCRIBE", "DESC"):
            return self.parse_describe()
        if k == "INSERT":
            return self.parse_insert()
        if k == "DELETE":
            return self.parse_delete()
        if k == "UPDATE":
            return self.parse_update()
        if k == "RECOVER":
            # RECOVER TENANT <n> | DATABASE <n> | TABLE [db.]<n>
            # (reference spi ast.rs:65-77, parser.rs:1859)
            self.next()
            kind = self.expect_kw("TENANT", "DATABASE", "TABLE")
            if kind == "TABLE":
                database, name = self.parse_qualified_ident()
            else:
                database, name = None, self.expect_ident()
            return ast.RecoverStmt(kind.lower(), name, database)
        if k == "BACKUP":
            # BACKUP DATABASE <n> [INCREMENTAL]
            self.next()
            self.expect_kw("DATABASE")
            name = self.expect_ident()
            return ast.BackupStmt(name,
                                  incremental=self.accept_kw("INCREMENTAL"))
        if k == "RESTORE":
            # RESTORE DATABASE <n> [FROM '<backup_id>']
            #   [TO TIMESTAMP <ns>|'<RFC3339>'] [AS <new_name>]
            self.next()
            self.expect_kw("DATABASE")
            stmt = ast.RestoreStmt(self.expect_ident())
            if self.accept_kw("FROM"):
                stmt.backup_id = self.expect_string()
            if self.accept_kw("TO"):
                self.expect_kw("TIMESTAMP")
                if self.peek().kind == "string":
                    stmt.to_ts = parse_timestamp_string(self.expect_string())
                else:
                    stmt.to_ts = int(self.expect_number())
            if self.accept_kw("AS"):
                stmt.new_name = self.expect_ident()
            return stmt
        if k == "COMPACT":
            self.next()
            if self.accept_kw("VNODE"):
                return ast.VnodeAdmin("compact",
                                      vnode_id=int(self.expect_number()))
            self.expect_kw("DATABASE")
            return ast.CompactStmt(self.expect_ident())
        if k == "CHECKSUM":
            # CHECKSUM GROUP <rs_id> (reference check.rs ChecksumGroup)
            self.next()
            self.expect_kw("GROUP")
            return ast.VnodeAdmin("checksum",
                                  replica_set_id=int(self.expect_number()))
        if k == "FLUSH":
            self.next()
            db = None
            if self.accept_kw("DATABASE"):
                db = self.expect_ident()
            return ast.FlushStmt(db)
        if k == "KILL":
            self.next()
            self.accept_kw("QUERY")
            return ast.KillQuery(int(self.expect_number()))
        if k in ("MOVE", "COPY") and self._peek_kw_at(1) == "VNODE":
            op = k.lower()
            self.next()
            self.expect_kw("VNODE")
            vid = int(self.expect_number())
            self.expect_kw("TO")
            self.expect_kw("NODE")
            return ast.VnodeAdmin(op, vnode_id=vid,
                                  node_id=int(self.expect_number()))
        if k == "REPLICA":
            # REPLICA ADD ON <rs_id> NODE <node> | REMOVE VNODE <id> |
            # PROMOTE VNODE <id> (reference ast.rs:56-73 replica admin)
            self.next()
            sub = self.expect_kw("ADD", "REMOVE", "PROMOTE", "DESTORY",
                                 "DESTROY")
            if sub == "ADD":
                self.expect_kw("ON")
                rs_id = int(self.expect_number())
                self.expect_kw("NODE")
                return ast.VnodeAdmin("replica_add", replica_set_id=rs_id,
                                      node_id=int(self.expect_number()))
            if sub in ("DESTORY", "DESTROY"):
                # the reference spells it DESTORY (parser.rs:2046); accept
                # the correct spelling too
                return ast.VnodeAdmin(
                    "replica_destory",
                    replica_set_id=int(self.expect_number()))
            self.accept_kw("VNODE")
            return ast.VnodeAdmin(f"replica_{sub.lower()}",
                                  vnode_id=int(self.expect_number()))
        if k == "COPY":
            self.next()
            self.expect_kw("INTO")
            t = self.peek()
            copy_cols = None
            if t.kind == "string":
                target, target_is_path = self.expect_string(), True
            else:
                target, target_is_path = self.expect_ident(), False
                if self.accept_op("("):
                    copy_cols = [self.expect_ident()]
                    while self.accept_op(","):
                        copy_cols.append(self.expect_ident())
                    self.expect_op(")")
            self.expect_kw("FROM")
            t = self.peek()
            if t.kind == "op" and t.value == "(":
                # COPY INTO '<path>' FROM (SELECT ...) — query export
                self.next()
                source = self.parse_query()
                self.expect_op(")")
            elif t.kind == "string":
                source = self.expect_string()
            else:
                source = self.expect_ident()
            path = target if target_is_path else source
            fmt = "parquet" if isinstance(path, str) \
                and path.endswith(".parquet") else "csv"
            options: dict = {}
            while True:
                if self.accept_kw("CONNECTION"):
                    self.expect_op("=")
                    options.update(self._parse_kv_parens())
                elif self.accept_kw("FILE_FORMAT"):
                    self.expect_op("=")
                    self.expect_op("(")
                    self.expect_kw("TYPE")
                    self.accept_op("=")   # `(type 'csv')` form is legal
                    fmt = self.expect_string().lower()
                    self.expect_op(")")
                elif self.accept_kw("COPY_OPTIONS"):
                    self.expect_op("=")
                    options["__copy_options__"] = self._parse_kv_parens()
                else:
                    break
            return ast.CopyStmt(target, source, target_is_path, fmt,
                                options, copy_cols)
        if k in ("GRANT", "REVOKE"):
            grant = k == "GRANT"
            self.next()
            level = self.expect_kw("READ", "WRITE", "ALL").lower()
            self.expect_kw("ON")
            self.expect_kw("DATABASE")
            db = self.expect_ident()
            self.expect_kw("TO" if grant else "FROM")
            self.accept_kw("ROLE")   # keyword optional upstream
            return ast.GrantRevoke(grant, level, db,
                                   self._ident_or_string())
        raise ParserError(f"unsupported statement start {self.peek().value!r}")

    # -- SELECT ----------------------------------------------------------
    def parse_query(self):
        """[WITH ctes] set-expression. Set-op grammar with standard
        precedence (INTERSECT binds tighter than UNION/EXCEPT, both
        left-associative); a trailing ORDER BY/LIMIT belongs to the whole
        chain. CTEs are expanded inline at parse time — each reference
        becomes a derived relation (SubqueryRef), the same planning shape
        the reference gets from DataFusion's CTE inlining."""
        if self.accept_kw("WITH"):
            ctes: dict[str, object] = {}
            while True:
                name = self.expect_ident()
                cols = None
                if self.accept_op("("):
                    cols = [self.expect_ident()]
                    while self.accept_op(","):
                        cols.append(self.expect_ident())
                    self.expect_op(")")
                self.expect_kw("AS")
                self.expect_op("(")
                body = self.parse_query()
                self.expect_op(")")
                # earlier CTEs are visible in later bodies (standard
                # non-recursive WITH scoping); self-reference is not
                body = _expand_ctes(body, ctes)
                if cols is not None:
                    body = _apply_cte_columns(body, cols, name)
                if name in ctes:
                    raise ParserError(f"duplicate CTE name {name!r}")
                ctes[name] = body
                if not self.accept_op(","):
                    break
            return _expand_ctes(self.parse_set_query(), ctes)
        return self.parse_set_query()

    def parse_set_query(self):
        """intersect-chain ((UNION|EXCEPT) [ALL] intersect-chain)*"""
        first = self.parse_intersect_chain()
        if self.kw() not in ("UNION", "EXCEPT"):
            return first
        selects, alls, ops = [first], [], []
        while self.kw() in ("UNION", "EXCEPT"):
            ops.append(self.next().value.lower())
            alls.append(self.accept_kw("ALL"))
            selects.append(self.parse_intersect_chain())
        return self._make_setop(selects, alls, ops)

    def parse_intersect_chain(self):
        first = self.parse_select()
        if self.kw() != "INTERSECT":
            return first
        selects, alls, ops = [first], [], []
        while self.accept_kw("INTERSECT"):
            ops.append("intersect")
            alls.append(self.accept_kw("ALL"))
            selects.append(self.parse_select())
        return self._make_setop(selects, alls, ops)

    @staticmethod
    def _make_setop(selects, alls, ops):
        """Hoist the LAST branch's ORDER BY/LIMIT to the whole chain
        (standard SQL set-op scoping); earlier branches may not have one."""
        for s in selects[:-1]:
            if s.order_by or s.limit is not None:
                raise ParserError("ORDER BY/LIMIT must follow the last "
                                  "set-operation branch")
        last = selects[-1]
        u = ast.UnionStmt(selects, alls, last.order_by, last.limit,
                          last.offset, ops)
        last.order_by, last.limit, last.offset = [], None, None
        return u

    def parse_select(self) -> ast.SelectStmt:
        self.expect_kw("SELECT")
        distinct = self.accept_kw("DISTINCT")
        self.accept_kw("ALL")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        table = None
        database = None
        from_item = None
        if self.accept_kw("FROM"):
            from_item = self.parse_from_item()
            if isinstance(from_item, ast.TableRef) and from_item.alias is None:
                # plain single table: keep the fast-path fields populated
                table = from_item.name
                database = from_item.database
                from_item = None
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        group_by = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_group_item())
            while self.accept_op(","):
                group_by.append(self.parse_group_item())
        having = self.parse_expr() if self.accept_kw("HAVING") else None
        order_by = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        limit = offset = None
        if self.accept_kw("LIMIT"):
            limit = int(self.expect_number())
        if self.accept_kw("OFFSET"):
            offset = int(self.expect_number())
        return ast.SelectStmt(items, table, where, group_by, having,
                              order_by, limit, offset, distinct, database,
                              from_item)

    def parse_from_item(self):
        base = self.parse_table_factor()
        while True:
            k = self.kw()
            if k == "CROSS":
                self.next()
                self.expect_kw("JOIN")
                base = ast.Join(base, self.parse_table_factor(), "cross")
            elif k in ("JOIN", "INNER", "LEFT", "RIGHT", "FULL"):
                kind = "inner"
                if k == "INNER":
                    self.next()
                elif k in ("LEFT", "RIGHT", "FULL"):
                    kind = k.lower()
                    self.next()
                    self.accept_kw("OUTER")
                self.expect_kw("JOIN")
                right = self.parse_table_factor()
                self.expect_kw("ON")
                base = ast.Join(base, right, kind, self.parse_expr())
            elif self.accept_op(","):
                # comma join = CROSS JOIN (filters in WHERE)
                base = ast.Join(base, self.parse_table_factor(), "cross")
            else:
                return base

    def parse_table_factor(self):
        if self.accept_op("("):
            if self.kw() == "VALUES":
                return self._parse_values_rel()
            sub = self.parse_query()
            self.expect_op(")")
            had_as = self.accept_kw("AS")
            # alias is optional (reference allows a bare derived table);
            # synthesize a scope name when absent
            if had_as or (self.peek().kind == "ident"
                          and self.kw() not in _RESERVED
                          and self.kw() not in ("GROUP", "HAVING", "ORDER",
                                                "LIMIT", "OFFSET")):
                alias = self.expect_ident()
                col_aliases: list = []
                if self.accept_op("("):   # AS name (c1, c2, ...)
                    while True:
                        col_aliases.append(self.expect_ident())
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                return ast.SubqueryRef(sub, alias, col_aliases)
            return ast.SubqueryRef(sub, f"__subquery_{self.i}")
        if self.peek().kind == "string":
            # FROM 'name': DataFusion accepts a single-quoted table
            # reference (create_external_table.slt SELECT * FROM 'ba sic')
            name = self.expect_string()
            database = None
        else:
            name = self.expect_ident()
            database = None
            if self.accept_op("."):
                database, name = name, self.expect_ident()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect_ident()
        elif (self.peek().kind == "ident"
              and self.kw() not in _RESERVED
              and self.kw() not in ("GROUP", "HAVING", "ORDER", "LIMIT",
                                    "OFFSET", "UNION", "INTERSECT",
                                    "EXCEPT")):
            alias = self.next().value
        return ast.TableRef(name, alias, database)

    def parse_select_item(self) -> ast.SelectItem:
        if self.accept_op("*"):
            return ast.SelectItem("*")
        e = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect_ident()
        elif (self.peek().kind == "ident"
              and self.kw() not in ("FROM", "WHERE", "GROUP", "HAVING",
                                    "ORDER", "LIMIT", "OFFSET", "UNION",
                                    "INTERSECT", "EXCEPT")):
            alias = self.next().value
        return ast.SelectItem(e, alias)

    def parse_group_item(self):
        t = self.peek()
        if t.kind == "number":
            return int(self.expect_number())
        return self.parse_expr()

    def parse_order_item(self):
        e = self.parse_expr()
        asc = True
        if self.accept_kw("DESC"):
            asc = False
        else:
            self.accept_kw("ASC")
        return (e, asc)

    # -- DDL -------------------------------------------------------------
    def parse_create(self):
        self.expect_kw("CREATE")
        k = self.kw()
        if k == "EXTERNAL":
            self.next()
            self.expect_kw("TABLE")
            ine = self._if_not_exists()
            # quoted, string-literal, and db-qualified names are all
            # accepted; blank or '/'-bearing names are not
            # (create_external_table.slt)
            if self.peek().kind == "string":
                name = self.expect_string()
            else:
                tdb, name = self.parse_qualified_ident()
                if tdb is not None:
                    name = f"{tdb}.{name}"
            leaf = name.rsplit(".", 1)[-1]
            if not leaf.strip() or "/" in leaf:
                raise ParserError(f"invalid table name {name!r}")
            columns: list = []
            if self.accept_op("("):
                while True:
                    if self.peek().kind == "op" and self.peek().value == ")":
                        break   # trailing comma before the close paren
                    cname = self.expect_ident()
                    parts = [self.expect_ident()]
                    if self.accept_op("("):   # DECIMAL(10,6) etc.
                        args = [self.expect_number()]
                        while self.accept_op(","):
                            args.append(self.expect_number())
                        self.expect_op(")")
                        parts[-1] += "(" + ",".join(str(a) for a in args) \
                            + ")"
                    # multi-word types (BIGINT UNSIGNED); NOT NULL noise
                    while not (self.peek().kind == "op"
                               and self.peek().value in (",", ")")):
                        w = self.expect_ident().upper()
                        if w == "NOT":
                            self.expect_kw("NULL")
                            continue
                        parts.append(w)
                    columns.append((cname, " ".join(
                        x.upper() for x in parts)))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            fmt, header = "csv", False
            path = None
            options: dict = {}
            while True:
                if self.accept_kw("STORED"):
                    self.expect_kw("AS")
                    fmt = self.expect_ident().lower()
                elif self.accept_kw("WITH"):
                    self.expect_kw("HEADER")
                    self.accept_kw("ROW")
                    header = True
                elif self.accept_kw("LOCATION"):
                    path = self.expect_string()
                elif self.accept_kw("OPTIONS"):
                    self.accept_op("=")
                    options.update(self._parse_kv_parens())
                else:
                    break
            if path is None:
                raise ParserError("CREATE EXTERNAL TABLE needs LOCATION")
            return ast.CreateExternalTable(name, path, fmt, header, ine,
                                           options, columns)
        if k == "DATABASE":
            self.next()
            ine = self._if_not_exists()
            name = self._ident_or_string()
            opts = {}
            if self.accept_kw("WITH"):
                while True:
                    o = self.kw()
                    if o == "TTL":
                        self.next()
                        self.accept_op("=")
                        opts["ttl"] = self.expect_string()
                    elif o == "PRECISION":
                        self.next()
                        self.accept_op("=")
                        opts["precision"] = self.expect_string()
                    elif o == "SHARD":
                        self.next()
                        self.accept_op("=")
                        opts["shard_num"] = int(self.expect_number())
                    elif o == "VNODE_DURATION":
                        self.next()
                        self.accept_op("=")
                        opts["vnode_duration"] = self.expect_string()
                    elif o == "REPLICA":
                        self.next()
                        self.accept_op("=")
                        opts["replica"] = int(self.expect_number())
                    elif o in ("MAX_MEMCACHE_SIZE", "WAL_MAX_FILE_SIZE"):
                        self.next()
                        self.accept_op("=")
                        opts.setdefault("config", {})[o.lower()] = \
                            self.expect_string()
                    elif o in ("MEMCACHE_PARTITIONS",
                               "MAX_CACHE_READERS"):
                        self.next()
                        self.accept_op("=")
                        opts.setdefault("config", {})[o.lower()] = \
                            int(self.expect_number())
                    elif o in ("WAL_SYNC", "STRICT_WRITE"):
                        self.next()
                        self.accept_op("=")
                        opts.setdefault("config", {})[o.lower()] = \
                            self.expect_string().lower() == "true"
                    else:
                        break
            return ast.CreateDatabase(name, ine, opts)
        if k == "TABLE":
            self.next()
            ine = self._if_not_exists()
            name = self.expect_ident()
            database = None
            if self.accept_op("."):
                database, name = name, self.expect_ident()
            fields, tags = [], []
            self.expect_op("(")
            while True:
                if self.accept_kw("TAGS"):
                    self.expect_op("(")
                    tags.append(self._tag_name())
                    while self.accept_op(","):
                        tags.append(self._tag_name())
                    self.expect_op(")")
                else:
                    cname = self.expect_ident()
                    tname = self.expect_ident()
                    if tname.upper() == "BIGINT" and self.kw() == "UNSIGNED":
                        self.next()
                        tname = "BIGINT UNSIGNED"
                    elif tname.upper() == "GEOMETRY" and self.accept_op("("):
                        # GEOMETRY(subtype, srid) — stored as WKT strings
                        # (reference models/src/schema/tskv_table_schema.rs
                        # GeometryType); subtype recorded for DESCRIBE
                        sub = self.expect_ident().upper()
                        if sub not in ("POINT", "LINESTRING", "POLYGON",
                                       "MULTIPOINT", "MULTILINESTRING",
                                       "MULTIPOLYGON",
                                       "GEOMETRYCOLLECTION"):
                            raise ParserError(
                                f"unknown geometry subtype {sub!r}")
                        self.expect_op(",")
                        srid = int(self.expect_number())
                        if srid != 0:
                            raise ParserError(
                                f"unsupported geometry SRID {srid} "
                                f"(only 0)")
                        self.expect_op(")")
                        tname = f"GEOMETRY({sub}, {srid})"
                    codec = None
                    if self.accept_kw("CODEC"):
                        self.expect_op("(")
                        codec = self.expect_ident().upper()
                        self.expect_op(")")
                    fields.append(ast.ColumnDef(cname, tname, codec))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return ast.CreateTable(name, fields, tags, ine, database)
        if k == "STREAM" and self._peek_kw_at(1) == "TABLE":
            # CREATE STREAM TABLE [IF NOT EXISTS] name (cols) WITH (db=,
            # table=, event_time_column=) engine = tskv — the reference's
            # stream-source DDL (query_server stream providers)
            self.next()
            self.expect_kw("TABLE")
            ine = self._if_not_exists()
            name = self.expect_ident()
            columns = []
            if self.accept_op("("):
                while True:
                    cname = self.expect_ident()
                    tname = self.expect_ident().upper()
                    columns.append((cname, tname))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            self.expect_kw("WITH")
            opts = self._parse_kv_parens()
            engine = "tskv"
            if self.accept_kw("ENGINE"):
                self.accept_op("=")
                engine = self.expect_ident().lower()
            return ast.CreateStreamTable(name, columns, opts, engine, ine)
        if k == "MATERIALIZED":
            # CREATE MATERIALIZED VIEW [IF NOT EXISTS] name
            #   [WATERMARK DELAY '<interval>'] AS SELECT ...
            self.next()
            self.expect_kw("VIEW")
            ine = self._if_not_exists()
            name = self.expect_ident()
            delay_ns = 0
            if self.accept_kw("WATERMARK"):
                self.expect_kw("DELAY")
                delay_ns = parse_interval_string(self.expect_string())
            self.expect_kw("AS")
            start_pos = self.peek().pos
            select = self.parse_select()
            end_pos = self.peek().pos
            return ast.CreateMatView(name, select,
                                     self.sql[start_pos:end_pos].strip(),
                                     delay_ns, ine)
        if k == "STREAM":
            self.next()
            ine = self._if_not_exists()
            name = self.expect_ident()
            interval_s = 10.0
            delay_ns = 0
            if self.accept_kw("TRIGGER"):
                self.expect_kw("INTERVAL")
                interval_s = parse_interval_string(self.expect_string()) / 1e9
            if self.accept_kw("WATERMARK"):
                self.expect_kw("DELAY")
                delay_ns = parse_interval_string(self.expect_string())
            self.expect_kw("INTO")
            target = self.expect_ident()
            self.expect_kw("AS")
            start_pos = self.peek().pos
            select = self.parse_select()
            end_pos = self.peek().pos
            return ast.CreateStream(name, target, select,
                                    self.sql[start_pos:end_pos].strip(),
                                    interval_s, delay_ns, ine)
        if k == "TENANT":
            self.next()
            ine = self._if_not_exists()
            name = self._ident_or_string()
            comment = ""
            drop_after = None
            limiter: dict | None = None
            if self.accept_kw("WITH"):
                while True:
                    o = self.kw()
                    if o == "COMMENT":
                        self.next()
                        self.accept_op("=")
                        comment = self.expect_string()
                    elif o == "DROP_AFTER":
                        self.next()
                        self.accept_op("=")
                        drop_after = self.expect_string()
                    elif o in _LIMITER_GROUPS:
                        self.next()
                        limiter = limiter or {}
                        limiter[o.lower()] = self._parse_limiter_pairs()
                    else:
                        break
                    self.accept_op(",")
            return ast.CreateTenant(name, ine, comment, drop_after, limiter)
        if k == "USER":
            self.next()
            ine = self._if_not_exists()
            name = self._ident_or_string()
            password = ""
            comment = ""
            granted_admin = False
            must_change = None
            if self.accept_kw("WITH"):
                while True:
                    if self.accept_kw("PASSWORD"):
                        self.accept_op("=")
                        password = self.expect_string()
                    elif self.accept_kw("COMMENT"):
                        self.accept_op("=")
                        comment = self.expect_string()
                    elif self.accept_kw("GRANTED_ADMIN"):
                        self.accept_op("=")
                        granted_admin = \
                            self.expect_kw("TRUE", "FALSE") == "TRUE"
                    elif self.accept_kw("MUST_CHANGE_PASSWORD"):
                        self.accept_op("=")
                        must_change = \
                            self.expect_kw("TRUE", "FALSE") == "TRUE"
                    else:
                        break
                    self.accept_op(",")
            return ast.CreateUser(name, password, ine, comment,
                                  granted_admin, must_change)
        if k == "ROLE":
            self.next()
            ine = self._if_not_exists()
            name = self._ident_or_string()
            inherit = "member"
            if self.accept_kw("INHERIT"):
                inherit = self.expect_ident().lower()
            return ast.CreateRole(name, inherit, ine)
        raise ParserError(f"unsupported CREATE {k}")

    def _ident_or_string(self) -> str:
        """Role names may be quoted STRINGS ('d d' — dcl_role.slt)."""
        if self.peek().kind == "string":
            return self.next().value
        return self.expect_ident()

    def _if_not_exists(self) -> bool:
        if self.kw() == "IF":
            self.next()
            self.expect_kw("NOT")
            self.expect_kw("EXISTS")
            return True
        return False

    def _if_exists(self) -> bool:
        if self.kw() == "IF":
            self.next()
            self.expect_kw("EXISTS")
            return True
        return False

    def parse_drop(self):
        self.expect_kw("DROP")
        k = self.kw()
        if k == "DATABASE":
            self.next()
            ie = self._if_exists()
            name = self._ident_or_string()
            if self.accept_kw("AFTER"):
                self.expect_string()   # delayed drop window (trash holds)
            return ast.DropDatabase(name, ie)
        if k == "TABLE":
            self.next()
            ie = self._if_exists()
            database, name = self.parse_qualified_ident()
            return ast.DropTable(name, ie, database)
        if k == "STREAM":
            self.next()
            ie = self._if_exists()
            return ast.DropStream(self.expect_ident(), ie)
        if k == "MATERIALIZED":
            self.next()
            self.expect_kw("VIEW")
            ie = self._if_exists()
            return ast.DropMatView(self.expect_ident(), ie)
        if k == "TENANT":
            self.next()
            ie = self._if_exists()
            name = self._ident_or_string()
            after = None
            if self.accept_kw("AFTER"):
                after = self.expect_string()
            return ast.DropTenant(name, ie, after)
        if k == "USER":
            self.next()
            ie = self._if_exists()
            return ast.DropUser(self._ident_or_string(), ie)
        if k == "ROLE":
            self.next()
            ie = self._if_exists()
            return ast.DropRole(self._ident_or_string(), ie)
        raise ParserError(f"unsupported DROP {k}")

    def parse_alter(self):
        self.expect_kw("ALTER")
        k = self.kw()
        if k == "DATABASE":
            self.next()
            name = self._ident_or_string()
            self.expect_kw("SET")
            opts = {}
            while True:
                o = self.kw()
                if o == "TTL":
                    self.next()
                    self.accept_op("=")
                    opts["ttl"] = self.expect_string()
                elif o == "SHARD":
                    self.next()
                    self.accept_op("=")
                    opts["shard_num"] = int(self.expect_number())
                elif o == "VNODE_DURATION":
                    self.next()
                    self.accept_op("=")
                    opts["vnode_duration"] = self.expect_string()
                elif o == "REPLICA":
                    self.next()
                    self.accept_op("=")
                    opts["replica"] = int(self.expect_number())
                elif o == "PRECISION":
                    self.next()
                    self.accept_op("=")
                    self.expect_string()
                    raise ParserError(
                        "database precision cannot be altered")
                elif o in ("MAX_MEMCACHE_SIZE", "WAL_MAX_FILE_SIZE",
                           "MEMCACHE_PARTITIONS", "MAX_CACHE_READERS",
                           "WAL_SYNC", "STRICT_WRITE"):
                    raise ParserError(
                        f"database option {o} cannot be altered")
                else:
                    break
                if len(opts) > 1:
                    # the reference's ALTER DATABASE takes EXACTLY one
                    # option per statement (alter_database.slt)
                    raise ParserError(
                        "ALTER DATABASE takes one option per statement")
            return ast.AlterDatabase(name, opts)
        if k == "TABLE":
            self.next()
            tdb, name = self.parse_qualified_ident()
            if tdb is not None:
                name = f"{tdb}.{name}"   # executor splits db-qualified
            if self.accept_kw("RENAME"):
                self.expect_kw("COLUMN")
                old = self.expect_ident()
                self.expect_kw("TO")
                new = self.expect_ident()
                return ast.AlterTable(name, "rename", drop_name=old,
                                      rename_to=new)
            if self.accept_kw("ADD"):
                if self.accept_kw("TAG"):
                    return ast.AlterTable(name, "add_tag",
                                          ast.ColumnDef(self.expect_ident(), "STRING"))
                self.accept_kw("FIELD")
                cname = self.expect_ident()
                tname = self.expect_ident()
                codec = None
                if self.accept_kw("CODEC"):
                    self.expect_op("(")
                    codec = self.expect_ident().upper()
                    self.expect_op(")")
                return ast.AlterTable(name, "add_field",
                                      ast.ColumnDef(cname, tname, codec))
            if self.accept_kw("DROP"):
                self.accept_kw("COLUMN")
                return ast.AlterTable(name, "drop", drop_name=self.expect_ident())
            if self.accept_kw("ALTER"):
                # ALTER TABLE t ALTER <col> SET CODEC(<name>)
                # (reference alter_table.slt)
                cname = self.expect_ident()
                self.expect_kw("SET")
                self.expect_kw("CODEC")
                self.expect_op("(")
                codec = self.expect_ident().upper()
                self.expect_op(")")
                return ast.AlterTable(name, "alter_codec",
                                      ast.ColumnDef(cname, "", codec))
            raise ParserError("unsupported ALTER TABLE action")
        if k == "USER":
            self.next()
            name = self.expect_ident()
            self.expect_kw("SET")
            changes = {}
            while True:
                o = self.kw()
                if o == "PASSWORD":
                    self.next()
                    self.accept_op("=")
                    changes["password"] = self.expect_string()
                elif o == "COMMENT":
                    self.next()
                    self.accept_op("=")
                    changes["comment"] = self.expect_string()
                elif o == "GRANTED_ADMIN":
                    self.next()
                    self.accept_op("=")
                    changes["granted_admin"] = \
                        self.expect_kw("TRUE", "FALSE") == "TRUE"
                elif o == "MUST_CHANGE_PASSWORD":
                    self.next()
                    self.accept_op("=")
                    changes["must_change_password"] = \
                        self.expect_kw("TRUE", "FALSE") == "TRUE"
                else:
                    break
                self.accept_op(",")
            if not changes:
                raise ParserError("ALTER USER SET expects an option")
            return ast.AlterUser(name, changes)
        if k == "TENANT":
            self.next()
            tenant = self.expect_ident()
            if self.accept_kw("ADD"):
                self.expect_kw("USER")
                user = self.expect_ident()
                role = "member"
                if self.accept_kw("AS"):
                    role = self.expect_ident()
                return ast.AlterTenantMember(tenant, user, role, add=True)
            if self.accept_kw("REMOVE"):
                self.expect_kw("USER")
                return ast.AlterTenantMember(tenant, self.expect_ident(),
                                             add=False)
            if self.accept_kw("SET"):
                if self.accept_kw("USER"):
                    # ALTER TENANT t SET USER u AS role: re-role an
                    # existing member (dcl_tenant.slt)
                    user = self.expect_ident()
                    role = "member"
                    if self.accept_kw("AS"):
                        role = self.expect_ident()
                    return ast.AlterTenantMember(tenant, user, role,
                                                 add=True)
                changes = {}
                while True:
                    o = self.kw()
                    if o == "COMMENT":
                        self.next()
                        self.accept_op("=")
                        changes["comment"] = self.expect_string()
                    elif o == "DROP_AFTER":
                        self.next()
                        self.accept_op("=")
                        changes["drop_after"] = self.expect_string()
                    elif o in _LIMITER_GROUPS:
                        self.next()
                        changes.setdefault("_limiter_groups", {})[
                            o.lower()] = self._parse_limiter_pairs()
                    else:
                        break
                    self.accept_op(",")
                if not changes:
                    raise ParserError("ALTER TENANT SET expects an option")
                return ast.AlterTenantOpts(tenant, changes)
            if self.accept_kw("UNSET"):
                o = self.expect_kw("DROP_AFTER", "COMMENT", "_LIMITER")
                return ast.AlterTenantOpts(tenant, {o.lower(): None})
            raise ParserError(
                "ALTER TENANT expects ADD/REMOVE USER or SET/UNSET")
        raise ParserError(f"unsupported ALTER {k}")

    def _parse_values_rel(self):
        """After '(' with VALUES next: inline constant relation."""
        self.expect_kw("VALUES")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_literal_value()]
            while self.accept_op(","):
                row.append(self.parse_literal_value())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        self.expect_op(")")
        alias = f"__values_{self.i}"
        cols = None
        if self.accept_kw("AS") or (self.peek().kind == "ident"
                                    and self.kw() not in _RESERVED):
            alias = self.expect_ident()
            if self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
        width = len(rows[0])
        for r in rows:
            if len(r) != width:
                raise ParserError("VALUES rows must have equal arity")
        if cols is not None and len(cols) != width:
            raise ParserError("VALUES column list arity mismatch")
        return ast.ValuesRef(rows, alias, cols)

    def _tag_name(self) -> str:
        """Tag names in TAGS(...) may be bare identifiers or string
        literals (reference: `TAGS('foo')` in copy_into_wide_table)."""
        if self.peek().kind == "string":
            return self.expect_string()
        return self.expect_ident()

    def _parse_show_order_by(self) -> list:
        """ORDER BY over a SHOW statement's OUTPUT columns only (the
        reference accepts `SHOW SERIES ... ORDER BY key` but rejects
        data columns — validated in the executor against the output)."""
        if not self.accept_kw("ORDER"):
            return []
        self.expect_kw("BY")
        items = []
        while True:
            name = self.expect_ident()
            asc = True
            if self.accept_kw("DESC"):
                asc = False
            else:
                self.accept_kw("ASC")
            items.append((name, asc))
            if not self.accept_op(","):
                break
        return items

    def parse_show(self):
        self.expect_kw("SHOW")
        k = self.kw()
        if k == "DATABASES":
            self.next()
            return ast.ShowStmt("databases")
        if k == "TABLES":
            self.next()
            db = None
            if self.accept_kw("ON"):
                db = self.expect_ident()
            return ast.ShowStmt("tables", on_database=db)
        if k == "SERIES":
            self.next()
            stmt = ast.ShowStmt("series")
            if self.accept_kw("ON"):
                stmt.on_database = self.expect_ident()
            # FROM is mandatory (reference ast.rs ShowSeries: a bare
            # `SHOW SERIES` is a parse error)
            self.expect_kw("FROM")
            stmt.table = self.expect_ident()
            if self.accept_kw("WHERE"):
                stmt.where = self.parse_expr()
            stmt.order_by = self._parse_show_order_by()
            if self.accept_kw("LIMIT"):
                stmt.limit = int(self.expect_number())
            if self.accept_kw("OFFSET"):
                stmt.offset = int(self.expect_number())
            return stmt
        if k == "TAG":
            self.next()
            if self.accept_kw("VALUES"):
                stmt = ast.ShowStmt("tag_values")
                if self.accept_kw("ON"):
                    stmt.on_database = self.expect_ident()
                self.expect_kw("FROM")
                stmt.table = self.expect_ident()
                self.expect_kw("WITH")
                self.expect_kw("KEY")
                # = k | != k | IN (a, b) | NOT IN (a, b)
                if self.accept_op("="):
                    stmt.tag_with = ("eq", [self.expect_ident()])
                elif self.accept_op("!=") or self.accept_op("<>"):
                    stmt.tag_with = ("ne", [self.expect_ident()])
                elif self.accept_kw("NOT"):
                    self.expect_kw("IN")
                    stmt.tag_with = ("notin", self._ident_parens())
                elif self.accept_kw("IN"):
                    stmt.tag_with = ("in", self._ident_parens())
                else:
                    stmt.tag_with = ("eq", [self.expect_ident()])
                stmt.tag_key = stmt.tag_with[1][0]
                if self.accept_kw("WHERE"):
                    stmt.where = self.parse_expr()
                stmt.order_by = self._parse_show_order_by()
                if self.accept_kw("LIMIT"):
                    stmt.limit = int(self.expect_number())
                if self.accept_kw("OFFSET"):
                    stmt.offset = int(self.expect_number())
                return stmt
            self.expect_kw("KEYS")
            stmt = ast.ShowStmt("tag_keys")
            if self.accept_kw("FROM"):
                stmt.table = self.expect_ident()
            return stmt
        if k == "QUERIES":
            self.next()
            return ast.ShowStmt("queries")
        if k == "BACKUPS":
            self.next()
            return ast.ShowStmt("backups")
        if k == "STREAMS":
            self.next()
            return ast.ShowStmt("streams")
        if k == "MATERIALIZED":
            self.next()
            self.expect_kw("VIEWS")
            return ast.ShowStmt("matviews")
        if k == "ROLES":
            self.next()
            return ast.ShowStmt("roles")
        if k == "USERS":
            self.next()
            return ast.ShowStmt("users")
        raise ParserError(f"unsupported SHOW {k}")

    def parse_describe(self):
        self.next()
        k = self.kw()
        kind = "table"
        if k in ("TABLE", "DATABASE"):
            self.next()
            kind = k.lower()
        name = self.expect_ident()
        database = None
        if kind == "table" and self.accept_op("."):
            database, name = name, self.expect_ident()
        stmt = ast.DescribeStmt(kind, name)
        stmt.database = database
        return stmt

    def parse_insert(self):
        self.expect_kw("INSERT")
        self.accept_kw("INTO")   # INTO is optional (reference dialect:
        # `INSERT tbl(...) VALUES ...` — sqllogicaltests cases use both)
        self.accept_kw("TABLE")  # `INSERT INTO TABLE t` variant
        table = self.expect_ident()
        database = None
        if self.accept_op("."):
            database, table = table, self.expect_ident()
        columns = []
        if self.accept_op("("):
            columns.append(self.expect_ident())
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        if self.kw() in ("SELECT", "WITH"):
            return ast.InsertStmt(table, columns, [], self.parse_query(),
                                  database)
        self.expect_kw("VALUES")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_literal_value()]
            while self.accept_op(","):
                row.append(self.parse_literal_value())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return ast.InsertStmt(table, columns, rows, None, database)

    def parse_literal_value(self):
        e = self.parse_expr()
        return _const_eval(e)

    def parse_qualified_ident(self) -> tuple:
        """[db .] name → (database | None, name)."""
        database, name = None, self.expect_ident()
        if self.accept_op("."):
            database, name = name, self.expect_ident()
        return database, name

    def parse_delete(self):
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        database, table = self.parse_qualified_ident()
        # optional table alias (reference sqlparser accepts
        # `DELETE FROM t a WHERE ...`); WHERE refers to bare columns
        if self.peek().kind == "ident" and self.kw() not in _RESERVED:
            self.next()
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.DeleteStmt(table, where, database)

    def parse_update(self):
        self.expect_kw("UPDATE")
        database, table = self.parse_qualified_ident()
        self.expect_kw("SET")
        assigns = {}
        while True:
            col = self.expect_ident()
            self.expect_op("=")
            assigns[col] = self.parse_expr()
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("WHERE") else None
        return ast.UpdateStmt(table, assigns, where, database)

    # -- expressions (precedence climbing) -------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        e = self.parse_and()
        while self.accept_kw("OR"):
            e = BinOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> Expr:
        e = self.parse_not()
        while self.accept_kw("AND"):
            e = BinOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> Expr:
        if self.accept_kw("NOT"):
            return UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        e = self.parse_additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
                self.next()
                op = "!=" if t.value == "<>" else t.value
                e = BinOp(op, e, self.parse_additive())
            elif self.kw() == "IS":
                self.next()
                negated = self.accept_kw("NOT")
                # IS [NOT] UNKNOWN ≡ IS [NOT] NULL over booleans;
                # IS [NOT] TRUE/FALSE is the boolean value test;
                # IS [NOT] DISTINCT FROM is NULL-safe inequality
                k = self.expect_kw("NULL", "UNKNOWN", "TRUE", "FALSE",
                                   "DISTINCT")
                if k == "DISTINCT":
                    self.expect_kw("FROM")
                    from .expr import IsDistinct

                    e = IsDistinct(e, self.parse_additive(), negated)
                elif k in ("TRUE", "FALSE"):
                    from .expr import IsBool

                    e = IsBool(e, k == "TRUE", negated)
                else:
                    e = IsNull(e, negated)
            elif self.kw() == "LIKE":
                self.next()
                pat = self.parse_additive()
                e = Like(e, pat.value if isinstance(pat, Literal)
                         and isinstance(pat.value, str) else pat)
            elif self.kw() in ("IN", "NOT"):
                negated = False
                if self.kw() == "NOT":
                    save = self.i
                    self.next()
                    if self.kw() == "IN":
                        negated = True
                    elif self.kw() == "BETWEEN":
                        self.next()
                        lo = self.parse_additive()
                        self.expect_kw("AND")
                        hi = self.parse_additive()
                        e = Between(e, lo, hi, negated=True)
                        continue
                    elif self.kw() == "LIKE":
                        self.next()
                        pat = self.parse_additive()
                        e = Like(e, pat.value
                                 if isinstance(pat, Literal)
                                 and isinstance(pat.value, str) else pat,
                                 negated=True)
                        continue
                    else:
                        self.i = save
                        break
                if self.kw() == "IN":
                    self.next()
                    self.expect_op("(")
                    if self.kw() == "SELECT":
                        sub = self.parse_query()
                        self.expect_op(")")
                        e = InSubquery(e, sub, negated)
                        continue
                    vals = [_const_eval(self.parse_expr())]
                    while self.accept_op(","):
                        vals.append(_const_eval(self.parse_expr()))
                    self.expect_op(")")
                    # a literal NULL among the values: three-valued logic —
                    # it can never satisfy IN and makes NOT IN unknown
                    # (false as a filter) for every row
                    null_present = any(v is None for v in vals)
                    e = InList(e, [v for v in vals if v is not None],
                               negated, null_present)
                else:
                    break
            elif self.kw() == "BETWEEN":
                self.next()
                lo = self.parse_additive()
                self.expect_kw("AND")
                hi = self.parse_additive()
                e = Between(e, lo, hi)
            else:
                break
        return e

    def parse_additive(self) -> Expr:
        # caret (bitwise XOR) binds LOOSER than +/- (sqlparser-rs gives
        # it precedence below additive): 1 ^ 2 + 3 is 1 ^ (2 + 3)
        e = self._parse_additive_nocaret()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value == "^":
                self.next()
                e = BinOp("^", e, self._parse_additive_nocaret())
            else:
                return e

    def _parse_additive_nocaret(self) -> Expr:
        e = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                e = BinOp(t.value, e, self.parse_multiplicative())
            elif t.kind == "op" and t.value == "||":
                # string concatenation OPERATOR: NULL-propagating
                # (concat() the function skips NULLs)
                self.next()
                e = Func("__concat_op", [e, self.parse_multiplicative()])
            else:
                break
        return e

    def parse_multiplicative(self) -> Expr:
        e = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                e = BinOp(t.value, e, self.parse_unary())
            else:
                break
        return e

    def parse_unary(self) -> Expr:
        if self.accept_op("-"):
            operand = self.parse_unary()
            if isinstance(operand, Literal) and isinstance(operand.value, (int, float)):
                return Literal(-operand.value)
            return UnaryOp("-", operand)
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "number":
            self.next()
            return Literal(_num(t.value))
        if t.kind == "sysvar":
            self.next()
            val = _SYSTEM_VARS.get(t.value)
            if val is None:
                raise ParserError(f"unknown system variable @@{t.value}")
            return Literal(val() if callable(val) else val)
        if t.kind == "string":
            self.next()
            return Literal(t.value)
        if self.accept_op("("):
            if self.kw() == "SELECT":
                sub = self.parse_query()
                self.expect_op(")")
                return Subquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident":
            k = t.value.upper()
            if k == "TRUE":
                self.next()
                return Literal(True)
            if k == "FALSE":
                self.next()
                return Literal(False)
            if k == "NULL":
                self.next()
                return Literal(None)
            if k == "INTERVAL":
                self.next()
                s = self.expect_string()
                if self.peek().kind == "ident" and self.kw() in (
                        u.upper() for u in _INTERVAL_UNITS):
                    unit = self.next().value.lower()
                    s = s + " " + unit
                return Literal(ast.IntervalValue(*parse_interval_parts(s)))
            if k == "TIMESTAMP":
                self.next()
                return Literal(parse_timestamp_string(self.expect_string()))
            if k == "DATE" and self.tokens[self.i + 1].kind == "string":
                # DATE '2024-08-08' keeps its date STRING identity (the
                # reference renders Date32 as ISO); comparisons against
                # time normalize the string to ns in the planner
                self.next()
                s = self.expect_string()
                parse_timestamp_string(s)   # validate eagerly
                from .expr import DateLit

                return DateLit(s)

            if k in ("CAST", "TRY_CAST"):
                self.next()
                self.expect_op("(")
                e = self.parse_expr()
                if self.kw() != "AS":
                    raise ParserError(f"expected AS in {k}")
                self.next()
                tname = self.expect_ident().upper()
                if tname == "BIGINT" and self.kw() == "UNSIGNED":
                    self.next()
                    tname = "BIGINT UNSIGNED"
                elif self.accept_op("("):
                    # parameterized types: CHAR(6), VARCHAR(n), ...
                    self.expect_number()
                    while self.accept_op(","):
                        self.expect_number()
                    self.expect_op(")")
                self.expect_op(")")
                from .expr import Cast

                return Cast(e, tname, safe=(k == "TRY_CAST"))
            if k == "ARRAY" and self._peek_op_at(1) == "[":
                # ARRAY[1, 2, 3] → rendered list literal (reference via
                # DataFusion list arrays; displays as [1, 2, 3])
                self.next()
                self.expect_op("[")
                vals = []
                if not self.accept_op("]"):
                    vals.append(self.parse_literal_value())
                    while self.accept_op(","):
                        vals.append(self.parse_literal_value())
                    self.expect_op("]")
                def _el(v):
                    if isinstance(v, bool):
                        return "true" if v else "false"
                    if isinstance(v, float):
                        return repr(v)
                    return str(v)
                return Literal("[" + ", ".join(_el(v) for v in vals) + "]")
            if k == "EXISTS":
                self.next()
                self.expect_op("(")
                sub = self.parse_query()
                self.expect_op(")")
                from .expr import Exists

                return Exists(sub)
            if k == "TRIM" and self._peek_op_at(1) == "(" \
                    and self._peek_kw_at(2) in ("BOTH", "LEADING",
                                                "TRAILING"):
                # TRIM([BOTH|LEADING|TRAILING] chars FROM s) — standard
                # form (reference via sqlparser)
                self.next()
                self.expect_op("(")
                side = self.expect_kw("BOTH", "LEADING", "TRAILING")
                chars = self.parse_expr()
                self.expect_kw("FROM")
                s = self.parse_expr()
                self.expect_op(")")
                fname = {"BOTH": "btrim", "LEADING": "ltrim_chars",
                         "TRAILING": "rtrim_chars"}[side]
                return Func(fname, [s, chars])
            if k == "SUBSTRING" and self._peek_op_at(1) == "(":
                # SUBSTRING(s FROM start [FOR len]) — standard form
                # (tpch.slt q22; the comma form parses as a plain call)
                save = self.i
                self.next()
                self.expect_op("(")
                s = self.parse_expr()
                if self.kw() == "FROM":
                    self.next()
                    start = self.parse_expr()
                    args = [s, start]
                    if self.kw() == "FOR":
                        self.next()
                        args.append(self.parse_expr())
                    self.expect_op(")")
                    return Func("substring", args)
                self.i = save   # comma form: reparse as a normal call
            if k == "EXTRACT" and self._peek_op_at(1) == "(":
                # EXTRACT(field FROM expr) → date_part('field', expr)
                self.next()
                self.expect_op("(")
                field = self.expect_ident()
                self.expect_kw("FROM")
                e = self.parse_expr()
                self.expect_op(")")
                return Func("date_part", [Literal(field.lower()), e])
            if k == "CASE":
                # CASE [operand] WHEN v THEN r ... [ELSE d] END — searched
                # and simple forms (reference: DataFusion Expr::Case)
                self.next()
                operand = None
                if self.kw() != "WHEN":
                    operand = self.parse_expr()
                whens = []
                while self.kw() == "WHEN":
                    self.next()
                    cond = self.parse_expr()
                    self.expect_kw("THEN")
                    whens.append((cond, self.parse_expr()))
                if not whens:
                    raise ParserError("CASE requires at least one WHEN")
                else_ = None
                if self.kw() == "ELSE":
                    self.next()
                    else_ = self.parse_expr()
                self.expect_kw("END")
                from .expr import Case

                return Case(operand, whens, else_)
            if k in _RESERVED:
                # LEFT/RIGHT/EXTRACT are function names when a '(' follows
                # (DataFusion accepts the same); elsewhere they stay
                # reserved (JOIN kinds)
                nxt = self.tokens[self.i + 1] if self.i + 1 < len(
                    self.tokens) else None
                callable_kw = (k in ("LEFT", "RIGHT")
                               and nxt is not None and nxt.kind == "op"
                               and nxt.value == "(")
                if not callable_kw:
                    raise ParserError(
                        f"unexpected keyword {t.value!r} in expression")
            name = self.next().value
            if self.accept_op("("):
                if self.accept_op("*"):
                    self.expect_op(")")
                    return self._maybe_over(Func(name, [Literal("*")]))
                args = []
                if not self.accept_op(")"):
                    if self.accept_kw("DISTINCT"):
                        args.append(Literal("__distinct__"))
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                    agg_order = None
                    if self.accept_kw("ORDER"):
                        # array_agg(x ORDER BY col [DESC]) — aggregate
                        # input ordering (reference via DataFusion)
                        self.expect_kw("BY")
                        oe = self.parse_expr()
                        asc = True
                        if self.accept_kw("DESC"):
                            asc = False
                        else:
                            self.accept_kw("ASC")
                        agg_order = (oe, asc)
                    self.expect_op(")")
                    return self._maybe_over(
                        Func(name, args, agg_order))
                # empty argument list: accept_op(")") above consumed it
                return self._maybe_over(Func(name, args))
            if self.accept_op("."):
                # qualified column: alias.col (relational FROM scopes)
                return Column(f"{name}.{self.expect_ident()}")
            return Column(name)
        raise ParserError(f"unexpected token {t.value!r} in expression")

    def _maybe_over(self, f: Func) -> Expr:
        """fn(...) [OVER (PARTITION BY ... ORDER BY ...)]"""
        if self.kw() != "OVER":
            return f
        self.next()
        self.expect_op("(")
        partition_by: list = []
        order_by: list = []
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition_by.append(self.parse_expr())
            while self.accept_op(","):
                partition_by.append(self.parse_expr())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order_by.append(self.parse_order_item())
            while self.accept_op(","):
                order_by.append(self.parse_order_item())
        frame = None
        if self.accept_kw("ROWS"):
            # ROWS BETWEEN <bound> AND <bound> — the reference corpus
            # uses the unbounded/current-row shapes
            self.expect_kw("BETWEEN")

            def bound():
                if self.accept_kw("UNBOUNDED"):
                    return self.expect_kw("PRECEDING", "FOLLOWING").lower()
                if self.accept_kw("CURRENT"):
                    self.expect_kw("ROW")
                    return "current"
                n = self.expect_number()
                kind = self.expect_kw("PRECEDING", "FOLLOWING").lower()
                return (int(n), kind)

            lo = bound()
            self.expect_kw("AND")
            hi = bound()
            if lo == "preceding" and hi == "current":
                frame = "cum"
            elif lo == "preceding" and hi == "following":
                frame = "full"
            elif lo == "current" and hi == "following":
                frame = "rev"
            else:
                raise ParserError(
                    "unsupported window frame (supported: UNBOUNDED "
                    "PRECEDING/CURRENT ROW/UNBOUNDED FOLLOWING bounds)")
        self.expect_op(")")
        return WindowFunc(f.name, f.args, partition_by, order_by, frame)


def _expand_ctes(stmt, ctes: dict):
    """Inline every CTE reference as a derived relation. Each reference
    gets its OWN deep copy of the body (a CTE used twice materializes
    twice — correctness first; the planner sees plain SubqueryRefs).
    Walks FROM trees, set-op branches, and subquery expressions; a real
    table shadowed by a CTE name resolves to the CTE (standard scoping).
    """
    if not ctes:
        return stmt
    import copy as _copy

    from .expr import Expr, iter_child_exprs

    def walk_from(fi):
        if isinstance(fi, ast.TableRef):
            if fi.database is None and fi.name in ctes:
                return ast.SubqueryRef(_copy.deepcopy(ctes[fi.name]),
                                       fi.alias or fi.name)
            return fi
        if isinstance(fi, ast.Join):
            fi.left = walk_from(fi.left)
            fi.right = walk_from(fi.right)
            walk_expr(fi.on)
            return fi
        if isinstance(fi, ast.SubqueryRef):
            fi.select = _expand_ctes(fi.select, ctes)
            return fi
        return fi

    def walk_expr(e):
        if not isinstance(e, Expr):
            return
        sel = getattr(e, "select", None)
        if isinstance(sel, (ast.SelectStmt, ast.UnionStmt)):
            e.select = _expand_ctes(sel, ctes)
        for c in iter_child_exprs(e):
            walk_expr(c)

    if isinstance(stmt, ast.UnionStmt):
        stmt.selects = [_expand_ctes(s, ctes) for s in stmt.selects]
        for oe, _ in stmt.order_by:
            walk_expr(oe)
        return stmt
    if not isinstance(stmt, ast.SelectStmt):
        return stmt
    if stmt.table is not None and stmt.database is None \
            and stmt.table in ctes:
        stmt.from_item = ast.SubqueryRef(_copy.deepcopy(ctes[stmt.table]),
                                         stmt.table)
        stmt.table = None
    elif stmt.from_item is not None:
        stmt.from_item = walk_from(stmt.from_item)
    for it in stmt.items:
        walk_expr(it.expr)
    walk_expr(stmt.where)
    walk_expr(stmt.having)
    for oe, _ in stmt.order_by:
        walk_expr(oe)
    for g in stmt.group_by:
        walk_expr(g)
    return stmt


def _apply_cte_columns(body, cols: list, name: str):
    """WITH name(c1, c2) AS (...) — rename the body's output columns.
    Output names come from the first branch of a set-op chain."""
    target = body
    while isinstance(target, ast.UnionStmt):
        target = target.selects[0]
    if any(it.expr == "*" for it in target.items):
        raise ParserError(
            f"CTE {name!r} with a column list requires explicit select "
            "items (no *)")
    if len(target.items) != len(cols):
        raise ParserError(
            f"CTE {name!r} column list has {len(cols)} names for "
            f"{len(target.items)} select items")
    target.items = [ast.SelectItem(it.expr, c)
                    for it, c in zip(target.items, cols)]
    return body


_RESERVED = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "LIMIT",
    "OFFSET", "AND", "OR", "NOT", "AS", "ASC", "DESC", "IN", "BETWEEN",
    "IS", "CASE", "WHEN", "THEN", "ELSE", "END", "UNION", "JOIN", "ON",
    "INNER", "LEFT", "RIGHT", "FULL", "CROSS", "INSERT", "INTO",
    "DELETE", "UPDATE", "SET", "INTERSECT", "EXCEPT", "WITH",
    # VALUES is deliberately NOT reserved: the reference corpus uses it
    # as a column name (function/common/time_functions/date_part.slt)
}


def _num(text: str):
    if re.fullmatch(r"\d+", text):
        return int(text)
    return float(text)


def _const_eval(e: Expr):
    """Fold a literal-only expression to a python value (INSERT VALUES)."""
    if isinstance(e, Literal):
        return e.value
    if isinstance(e, UnaryOp) and e.op == "-":
        v = _const_eval(e.operand)
        return -v
    if isinstance(e, Expr):
        # any column-free expression folds (sqlancer writes arbitrary
        # constant expressions into INSERT VALUES: casts, concat, IN, ...)
        if e.columns():
            raise ParserError(
                f"INSERT value references a column: {e!r}")
        import numpy as np

        try:
            v = e.eval({}, np)
        except ParserError:
            raise
        except Exception as ex:
            raise ParserError(f"bad INSERT value {e!r}: {ex}")
        # numpy scalars/0-d arrays must become python values: they ride
        # into WriteBatches (msgpack) and schema type checks
        if isinstance(v, np.ndarray):
            v = v[()] if v.shape == () else \
                (v.tolist() if v.size > 1 else v.ravel()[0])
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.bool_):
            return bool(v)
        return v
    raise ParserError(f"expected literal value, got {e!r}")


def parse_sql(sql: str) -> list:
    return Parser(sql).parse_statements()
