"""First-class analyzer rules: AST→AST rewrites applied before planning.

Counterpart of the reference's AnalyzerRule pipeline
(query_server/query/src/extension/analyse/: transform_topk_func_to_topk_node.rs,
transform_bottom_func_to_topk_node.rs, transform_exact_count_to_count.rs).
Each rule is a pure function SelectStmt → SelectStmt; `analyze()` runs them
in order. The executor calls analyze() once per statement, so BOTH the
scan-aggregate fast path and the relational fallback see the rewritten
tree — same layering as the reference, where analysis precedes logical
optimization.
"""
from __future__ import annotations

from ..errors import PlanError
from . import ast
from .expr import Between, BinOp, Case, Cast, Column, Expr, Func, InList, \
    IsNull, Like, Literal, UnaryOp

_SELECTOR_FUNCS = ("topk", "bottom")


def analyze(stmt):
    """Run every analyzer rule. Non-SELECT statements pass through.
    For UNION chains only the union-level ORDER BY needs rewriting here —
    each branch is a SelectStmt that re-enters analyze() when executed."""
    if isinstance(stmt, ast.UnionStmt):
        return _analyze_union_order_by(stmt)
    if not isinstance(stmt, ast.SelectStmt):
        return stmt
    stmt = rewrite_exact_count(stmt)
    stmt = rewrite_null_functions(stmt)
    stmt = rewrite_selector_functions(stmt)
    stmt = _normalize_time_comparisons(stmt)
    stmt = _wrap_time_string_args(stmt)
    stmt = _interval_for_time_subtraction(stmt)
    _reject_time_in_numeric_funcs(stmt)
    return stmt


def _time_typed(e) -> bool:
    """Conservatively: does this expression yield a TIMESTAMP? (bare
    time column, qualified .time, or selector/extremum aggregates and
    date_trunc/date_bin over one)."""
    from .expr import Column as _Col
    from .expr import Func as _Func

    if isinstance(e, _Col):
        return e.name == "time" or e.name.endswith(".time")
    if isinstance(e, _Func):
        n = e.name.lower()
        if n in ("min", "max", "first", "last", "first_value",
                 "last_value") and e.args:
            return _time_typed(e.args[0])
        if n in ("date_trunc", "date_bin") and e.args:
            return any(_time_typed(a) for a in e.args)
    return False


def _interval_for_time_subtraction(stmt):
    """timestamp - timestamp = INTERVAL (arrow semantics the reference
    inherits; gauge/time_delta.slt pins `max(time) - min(time)` rendered
    as '0 years 0 mons ... secs'): wrap qualifying subtractions in the
    __to_interval marker so the i64-ns result renders as an interval."""
    from .expr import BinOp as _BinOp
    from .expr import Func as _Func

    def rw(e):
        if isinstance(e, _BinOp) and e.op == "-" \
                and _time_typed(e.left) and _time_typed(e.right):
            return _Func("__to_interval", [e])
        return _map_children(e, rw)

    return _map_stmt_exprs(stmt, rw)


def _analyze_union_order_by(stmt):
    import dataclasses

    def rw(e):
        for r in _EXPR_REWRITERS:
            e = r(e)
        return e

    order_by = [(rw(oe) if isinstance(oe, Expr) else oe, asc)
                for oe, asc in stmt.order_by]
    if all(a is b for (a, _), (b, _) in zip(order_by, stmt.order_by)):
        return stmt
    return dataclasses.replace(stmt, order_by=order_by)


def _normalize_time_comparisons(stmt):
    """`now() >= '2024-01-01'`-style comparisons: a string literal
    against a timestamp-valued expression parses as a timestamp
    EVERYWHERE (the planner applies the same rule inside WHERE splits;
    this covers constant selects and projections)."""
    from .planner import _normalize_time_literals

    return _map_stmt_exprs(stmt, _normalize_time_literals)


_NUMERIC_FUNCS = {
    "abs", "floor", "ceil", "round", "sqrt", "cbrt", "exp", "ln", "log",
    "log10", "log2", "sin", "cos", "tan", "sinh", "cosh", "tanh", "asin",
    "acos", "atan", "asinh", "acosh", "atanh", "atan2", "pow", "power",
    "signum", "trunc", "radians", "degrees", "gcd", "lcm",
}


# positions whose time-column args see the ISO string form (reference
# implicit Timestamp→Utf8 casts; None = every position)
_TIME_AS_STRING_FUNCS = {"ascii": None, "concat": None, "concat_ws": None,
                         "replace": {1, 2}, "strpos": {1},
                         "translate": {1, 2}, "lpad": {2}, "rpad": {2},
                         "split_part": {1}}


def _wrap_time_string_args(stmt):
    """Lenient string functions over the time column see the ISO form
    (reference casts Timestamp→Utf8: ascii(TIME) is 49 — '1'…)."""
    def rw(e):
        if isinstance(e, Func) and e.name.lower() in _TIME_AS_STRING_FUNCS:
            allowed = _TIME_AS_STRING_FUNCS[e.name.lower()]
            new_args = []
            for i, a in enumerate(e.args):
                if isinstance(a, Column) and (
                        a.name == "time" or a.name.endswith(".time")) \
                        and (allowed is None or i in allowed):
                    a = Func("__iso__", [a])
                new_args.append(rw(a) if isinstance(a, Expr) else a)
            return Func(e.name, new_args, e.agg_order)
        return _map_children(e, rw)

    return _map_stmt_exprs(stmt, rw)


def _reject_time_in_numeric_funcs(stmt):
    """Math scalars reject Timestamp inputs (reference: 'No function
    matches ... exp(Timestamp(Nanosecond, None))'); the only int64 whose
    NAME identifies it as a timestamp is the time column."""
    def walk(e):
        if not isinstance(e, Expr):
            return
        if isinstance(e, Func) and e.name.lower() in _NUMERIC_FUNCS:
            for a in e.args:
                for c in (a.columns() if isinstance(a, Expr) else ()):
                    if c == "time" or c.endswith(".time"):
                        raise PlanError(
                            f"the function {e.name} does not support "
                            f"inputs of type TIMESTAMP")
        from .expr import iter_child_exprs

        for c in iter_child_exprs(e):
            walk(c)

    for it in stmt.items:
        if isinstance(it.expr, Expr):
            walk(it.expr)
    for e in (stmt.where, stmt.having):
        if e is not None:
            walk(e)
    for oe, _ in stmt.order_by:
        if isinstance(oe, Expr):
            walk(oe)


# ---------------------------------------------------------------------------
# coalesce/ifnull/nvl/nullif → CASE (NULL-aware by construction)
# ---------------------------------------------------------------------------
def rewrite_null_functions(stmt):
    """Desugar the NULL-choosing scalar set into CASE, whose evaluation
    consults validity masks (reference: DataFusion built-ins coalesce /
    nullif; ifnull/nvl are the common aliases). coalesce(a, b, c) →
    CASE WHEN a IS NOT NULL THEN a WHEN b IS NOT NULL THEN b ELSE c END;
    nullif(a, b) → CASE WHEN a = b THEN NULL ELSE a END."""
    return _map_stmt_exprs(stmt, _rw_null_funcs)


def _rw_null_funcs(e):
    if isinstance(e, Func) and e.name.lower() in (
            "coalesce", "ifnull", "nvl", "nullif"):
        name = e.name.lower()
        args = [_rw_null_funcs(a) if isinstance(a, Expr) else a
                for a in e.args]
        if name == "nullif":
            if len(args) != 2:
                raise PlanError("nullif takes exactly two arguments")
            return Case(None, [(BinOp("=", args[0], args[1]),
                                Literal(None))], args[0])
        if len(args) < 2:
            raise PlanError(f"{name} takes at least two arguments")
        whens = [(IsNull(a, negated=True), a) for a in args[:-1]]
        return Case(None, whens, args[-1])
    return _map_children(e, _rw_null_funcs)


# ---------------------------------------------------------------------------
# exact_count(<expr>) → count(<expr>)
# ---------------------------------------------------------------------------
def rewrite_exact_count(stmt):
    """exact_count(x) → count(x) (reference
    transform_exact_count_to_count.rs:41-53). The reference's pushed-down
    count can serve from page statistics; exact_count forces a real count.
    Here the scan kernels count actual surviving rows already, so the
    rewrite is a pure rename with identical semantics."""
    return _map_stmt_exprs(stmt, _rw_exact_count)


def _rw_exact_count(e):
    if isinstance(e, Func) and e.name.lower() == "exact_count":
        return Func("count", [_rw_exact_count(a) if isinstance(a, Expr)
                              else a for a in e.args])
    return _map_children(e, _rw_exact_count)


# The expression-level desugar rules, in application order. Statement-level
# analyze() applies each via its rewrite_* wrapper; _analyze_union_order_by
# consumes this list directly — add new scalar desugars HERE so both paths
# stay in sync.
_EXPR_REWRITERS = (_rw_exact_count, _rw_null_funcs)


# ---------------------------------------------------------------------------
# topk/bottom(field, k) → ORDER BY field DESC/ASC LIMIT k
# ---------------------------------------------------------------------------
def rewrite_selector_functions(stmt):
    """topk(field, k) / bottom(field, k) become a sort-with-fetch over the
    input and the function expression is replaced by the bare field
    (reference transform_topk_func_to_topk_node.rs:43-72 builds
    Sort{fetch=k} + projection + Limit(k)). Validation mirrors
    valid_exprs(): one selector function, not nested, k ∈ [1, 255]."""
    found = []
    for it in stmt.items:
        if isinstance(it.expr, Expr):
            _find_selectors(it.expr, found, nested=False)
    if not found:
        return stmt
    tops = [f for f, nested in found if not nested]
    if any(nested for _, nested in found) or len(found) > 1:
        raise PlanError(
            "invalid selector function use: no nested selection functions, "
            "no multiple selection functions")
    sel = tops[0]
    field_expr, k = _selector_args(sel)
    if stmt.group_by or stmt.having is not None:
        raise PlanError(f"{sel.name} cannot be combined with GROUP BY/HAVING")
    if stmt.order_by:
        raise PlanError(f"{sel.name} cannot be combined with ORDER BY "
                        "(it defines the ordering)")

    def replace(e):
        if e is sel:
            return field_expr
        return _map_children(e, replace)

    items = [ast.SelectItem(replace(it.expr)
                            if isinstance(it.expr, Expr) else it.expr,
                            it.alias or (sel.name if it.expr is sel else None))
             for it in stmt.items]
    import dataclasses

    # NULL field values never rank (reference sorts nulls_first=false with
    # fetch=k; the engine's ORDER BY places NULLs first on DESC, so the
    # rewrite filters them out instead — same selected rows whenever ≥k
    # non-null values exist)
    not_null = IsNull(field_expr, negated=True)
    where = not_null if stmt.where is None \
        else BinOp("and", stmt.where, not_null)
    # LIMIT/OFFSET paginate WITHIN the k selected rows; the executor
    # applies offset before limit, so the limit must shrink by the offset
    # or rows outside the top-k leak through the window
    avail = max(0, k - (stmt.offset or 0))
    return dataclasses.replace(
        stmt, items=items, where=where,
        order_by=[(field_expr, sel.name.lower() == "bottom")],
        limit=avail if stmt.limit is None else min(avail, stmt.limit))


def _find_selectors(e, out, nested):
    hit = isinstance(e, Func) and e.name.lower() in _SELECTOR_FUNCS
    if hit:
        out.append((e, nested))
    for c in _children(e):
        _find_selectors(c, out, nested or hit)


def _selector_args(f: Func):
    if len(f.args) != 2 or not isinstance(f.args[0], Column) \
            or not isinstance(f.args[1], Literal) \
            or not isinstance(f.args[1].value, int) \
            or isinstance(f.args[1].value, bool):
        raise PlanError(
            f"routine not match: {f.name}(field_name, k) — k is an integer "
            "literal in [1, 255]")
    k = f.args[1].value
    if not 1 <= k <= 255:
        raise PlanError(f"{f.name} k must be in [1, 255], got {k}")
    return f.args[0], k


# ---------------------------------------------------------------------------
# expression-tree plumbing
# ---------------------------------------------------------------------------
def _children(e) -> list:
    from .expr import iter_child_exprs

    return list(iter_child_exprs(e))


def _map_children(e, fn):
    """Rebuild `e` with fn applied to each child expression (identity when
    nothing changes, so untouched statements share structure)."""
    if isinstance(e, BinOp):
        l, r = fn(e.left), fn(e.right)
        return e if l is e.left and r is e.right else BinOp(e.op, l, r)
    if isinstance(e, UnaryOp):
        o = fn(e.operand)
        return e if o is e.operand else UnaryOp(e.op, o)
    if isinstance(e, Func):
        args = [fn(a) if isinstance(a, Expr) else a for a in e.args]
        if all(a is b for a, b in zip(args, e.args)):
            return e
        return Func(e.name, args)
    if isinstance(e, InList):
        x = fn(e.expr)
        return e if x is e.expr else InList(x, e.values, e.negated,
                                            e.null_present)
    if isinstance(e, Between):
        x, lo, hi = fn(e.expr), fn(e.low), fn(e.high)
        if x is e.expr and lo is e.low and hi is e.high:
            return e
        return Between(x, lo, hi, e.negated)
    if isinstance(e, IsNull):
        x = fn(e.expr)
        return e if x is e.expr else IsNull(x, e.negated)
    if isinstance(e, Like):
        x = fn(e.expr)
        return e if x is e.expr else Like(x, e.pattern, e.negated)
    if isinstance(e, Cast):
        x = fn(e.expr)
        return e if x is e.expr else Cast(x, e.target, e.safe)
    if isinstance(e, Case):
        op = fn(e.operand) if isinstance(e.operand, Expr) else e.operand
        whens = [(fn(c), fn(r)) for c, r in e.whens]
        els = fn(e.else_) if isinstance(e.else_, Expr) else e.else_
        if op is e.operand and els is e.else_ and all(
                a is c and b is r
                for (a, b), (c, r) in zip(whens, e.whens)):
            return e
        return Case(op, whens, els)
    return e


def _map_stmt_exprs(stmt, fn):
    import dataclasses

    items = [ast.SelectItem(fn(it.expr) if isinstance(it.expr, Expr)
                            else it.expr, it.alias) for it in stmt.items]
    having = fn(stmt.having) if isinstance(stmt.having, Expr) else stmt.having
    where = fn(stmt.where) if isinstance(stmt.where, Expr) else stmt.where
    order_by = [(fn(oe) if isinstance(oe, Expr) else oe, asc)
                for oe, asc in stmt.order_by]
    group_by = [fn(g) if isinstance(g, Expr) else g for g in stmt.group_by]
    from_item = _map_from_item(stmt.from_item, fn)
    return dataclasses.replace(stmt, items=items, having=having,
                               where=where, order_by=order_by,
                               group_by=group_by, from_item=from_item)


def _map_from_item(fi, fn):
    """Apply fn to JOIN ON conditions in a FROM tree. Without this,
    coalesce() in `JOIN ... ON coalesce(a.x,0) = b.y` would reach
    evaluation undesugared (round-3 advisor finding). Derived-relation
    (SubqueryRef) bodies are NOT rewritten here — they re-enter analyze()
    when the executor materializes them."""
    if isinstance(fi, ast.Join):
        left = _map_from_item(fi.left, fn)
        right = _map_from_item(fi.right, fn)
        on = fn(fi.on) if isinstance(fi.on, Expr) else fi.on
        if left is fi.left and right is fi.right and on is fi.on:
            return fi
        return ast.Join(left, right, fi.kind, on)
    return fi
