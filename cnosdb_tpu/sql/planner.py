"""Logical planning: AST → executable plans.

Role-parity with the reference's planner + analyzer + optimizer stack
(query_server/query/src/sql/planner.rs, extension/analyse/
transform_time_window.rs, extension/logical/optimizer_rule/
push_down_aggregation.rs, rewrite_tag_scan.rs): a SELECT becomes either an
AggregatePlan — aggregates pushed into the TpuExec scan with time ranges /
tag domains split out of WHERE for bucket+index pruning — or a RawScanPlan.
The WHERE split mirrors Predicate::push_down_filter
(common/models/src/predicate/domain.rs): exact time ranges from pure-time
conjuncts, a sound tag-domain over-approximation for the index, and the
residual expression re-checked at execution.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ..errors import PlanError
from ..models.predicate import ColumnDomains, TimeRange, TimeRanges, I64_MIN, I64_MAX
from ..models.schema import TskvTableSchema, ValueType
from ..ops.tpu_exec import AggSpec
from . import ast
from .expr import (
    Between, BinOp, Case, Cast, Column, Expr, Func, InList, IsNull, Literal,
    UnaryOp, extract_domains,
)
from .parser import parse_timestamp_string

# aggregates that only take numeric inputs (reference/DataFusion type
# signatures: Avg/Sum/Stddev/Median reject Timestamp, Utf8 and Boolean)
_NUMERIC_ONLY_AGGS = {"sum", "avg", "mean", "median", "stddev",
                      "stddev_samp", "stddev_pop", "var", "var_samp",
                      "var_pop", "corr", "covar", "covar_pop",
                      "covar_samp", "approx_median",
                      "approx_percentile_cont",
                      "approx_percentile_cont_with_weight",
                      "increase", "gauge_agg"}

# two-column statistical aggregates (reference statistical_agg/*.rs)
_TWO_COL_AGGS = {"corr", "covar", "covar_pop", "covar_samp"}

AGG_FUNCS = {"count", "sum", "avg", "mean", "min", "max", "first", "last",
             "bool_or", "bool_and", "bit_and", "bit_or", "bit_xor",
             "median", "stddev", "stddev_samp", "stddev_pop",
             "var", "var_samp", "var_pop",
             "corr", "covar", "covar_pop", "covar_samp",
             "approx_distinct", "approx_median", "approx_percentile_cont",
             "approx_percentile_cont_with_weight", "array_agg",
             "mode", "increase", "count_distinct",
             "sample", "gauge_agg", "state_agg", "compact_state_agg",
             "completeness", "consistency", "timeliness", "validity"}

# aggregates taking the reference's (time, value) signature whose leading
# time argument is implicit here (the collect_ts partial always carries
# timestamps): increase.rs:42-45, gauge/mod.rs, state_agg, data_quality
TS_PAIR_AGGS = {"increase", "gauge_agg", "state_agg", "compact_state_agg",
                "completeness", "consistency", "timeliness", "validity"}

TIME_COL = "time"


@dataclass
class AggregatePlan:
    table: str
    schema: TskvTableSchema
    time_ranges: TimeRanges
    tag_domains: ColumnDomains
    filter: Expr | None                  # residual, re-checked on device/host
    group_tags: list[str]
    group_fields: list[str]              # STRING field group keys (dict codes)
    bucket: tuple[int, int] | None       # (origin, interval)
    bucket_alias: str | None
    aggs: list[AggSpec]                  # internal partial aggregates
    output: list[tuple[str, Expr]]       # output name → expr over agg aliases/groups
    having: Expr | None
    order_by: list
    limit: int | None
    offset: int | None
    gapfill: bool = False                # dense bucket grid requested
    fill_methods: dict = field(default_factory=dict)  # output → locf|interpolate


@dataclass
class RawScanPlan:
    table: str
    schema: TskvTableSchema
    time_ranges: TimeRanges
    tag_domains: ColumnDomains
    filter: Expr | None
    output: list[tuple[str, Expr]]       # projections over row columns
    order_by: list
    limit: int | None
    offset: int | None
    distinct: bool = False


# ---------------------------------------------------------------------------
# WHERE splitting
# ---------------------------------------------------------------------------
def split_where(where: Expr | None, schema: TskvTableSchema):
    """→ (time_ranges, tag_domains, residual_expr)."""
    if where is None:
        return TimeRanges.all(), ColumnDomains.all(), None
    where = _normalize_time_literals(where)
    conjuncts = _split_and(where)
    time_trs = TimeRanges.all()
    residual = []
    for c in conjuncts:
        tr = _pure_time_ranges(c)
        if tr is not None:
            time_trs = time_trs.intersect(tr)
        else:
            residual.append(c)
    tag_cols = set(schema.tag_names())
    res_expr = _join_and(residual)
    tag_domains = extract_domains(res_expr, tag_cols)
    return time_trs, tag_domains, res_expr


def _split_and(e: Expr) -> list[Expr]:
    if isinstance(e, BinOp) and e.op == "and":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _join_and(es: list[Expr]) -> Expr | None:
    out = None
    for e in es:
        out = e if out is None else BinOp("and", out, e)
    return out


def _is_time_valued(e: Expr) -> bool:
    """Expressions statically known to be timestamps: the time column
    and the timestamp-returning scalars (now()/to_timestamp family)."""
    if _is_time_col(e):
        return True
    return isinstance(e, Func) and e.name.lower() in (
        "now", "current_timestamp", "to_timestamp",
        "to_timestamp_seconds", "to_timestamp_millis",
        "to_timestamp_micros", "from_unixtime", "date_trunc")


def _fold_now(e: Expr) -> Expr:
    """now()/current_timestamp fold to a constant at plan time (so time
    ranges still prune; the reference folds via DataFusion's
    simplify_expressions)."""
    if isinstance(e, Func) and not e.args and e.name.lower() in (
            "now", "current_timestamp"):
        import time as _time

        return Literal(int(_time.time() * 1e9))
    return e


def _normalize_time_literals(e: Expr) -> Expr:
    """Rewrite string literals compared against `time` (or a timestamp-
    valued expression, e.g. now()) into ns ints."""
    e = _fold_now(e)
    if isinstance(e, BinOp):
        l, r = _normalize_time_literals(e.left), _normalize_time_literals(e.right)
        if e.op in ("=", "!=", "<", "<=", ">", ">="):
            if _is_time_valued(l) and isinstance(r, Literal) \
                    and isinstance(r.value, str):
                r = Literal(parse_timestamp_string(r.value))
            if _is_time_valued(r) and isinstance(l, Literal) \
                    and isinstance(l.value, str):
                l = Literal(parse_timestamp_string(l.value))
        return BinOp(e.op, l, r)
    if isinstance(e, Between) and _is_time_valued(e.expr):
        lo, hi = _fold_now(e.low), _fold_now(e.high)
        if isinstance(lo, Literal) and isinstance(lo.value, str):
            lo = Literal(parse_timestamp_string(lo.value))
        if isinstance(hi, Literal) and isinstance(hi.value, str):
            hi = Literal(parse_timestamp_string(hi.value))
        return Between(_fold_now(e.expr), lo, hi, e.negated)
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, _normalize_time_literals(e.operand))
    if isinstance(e, InList) and _is_time_valued(e.expr):
        # time IN ('1999-12-31T00:00:00.045', …) — mode.slt; values are
        # plain python values, not wrapped Literals
        items = [parse_timestamp_string(v) if isinstance(v, str) else v
                 for v in e.values]
        return InList(e.expr, items, e.negated, e.null_present)
    if isinstance(e, Case):
        # comparisons live inside WHEN branches too:
        # CASE WHEN time = current_date() THEN … (current_date.slt)
        return Case(
            _normalize_time_literals(e.operand)
            if e.operand is not None else None,
            [(_normalize_time_literals(w), _normalize_time_literals(t))
             for w, t in e.whens],
            _normalize_time_literals(e.else_)
            if e.else_ is not None else None)
    return e


def _is_time_col(e: Expr) -> bool:
    return isinstance(e, Column) and e.name == TIME_COL


def _pure_time_ranges(e: Expr) -> TimeRanges | None:
    """If `e` constrains ONLY time, return its exact TimeRanges."""
    if isinstance(e, BinOp) and e.op in ("=", "<", "<=", ">", ">="):
        col, lit, op = _norm_cmp(e)
        if col == TIME_COL and isinstance(lit, (int, float)):
            v = int(lit)
            return {
                "=": TimeRanges([TimeRange(v, v)]),
                "<": TimeRanges([TimeRange(I64_MIN, v - 1)]),
                "<=": TimeRanges([TimeRange(I64_MIN, v)]),
                ">": TimeRanges([TimeRange(v + 1, I64_MAX)]),
                ">=": TimeRanges([TimeRange(v, I64_MAX)]),
            }[op]
    if isinstance(e, Between) and not e.negated and _is_time_col(e.expr):
        if isinstance(e.low, Literal) and isinstance(e.high, Literal):
            return TimeRanges([TimeRange(int(e.low.value), int(e.high.value))])
    if isinstance(e, BinOp) and e.op == "or":
        l = _pure_time_ranges(e.left)
        r = _pure_time_ranges(e.right)
        if l is not None and r is not None:
            return l.union(r)
    return None


def _norm_cmp(e: BinOp):
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    if isinstance(e.left, Column) and isinstance(e.right, Literal):
        return e.left.name, e.right.value, e.op
    if isinstance(e.left, Literal) and isinstance(e.right, Column):
        return e.right.name, e.left.value, flip[e.op]
    return None, None, None


# ---------------------------------------------------------------------------
# SELECT planning
# ---------------------------------------------------------------------------
# scalar signature table for schema-aware argument TYPE validation
# (reference: DataFusion signatures reject e.g. left(Utf8, UInt64),
# to_hex(UInt64), replace(Timestamp, ...)). 's' = string-typed arg,
# 'i' = Int64 (UNSIGNED and TIMESTAMP reject), '?' = unchecked.
_SCALAR_SIGS = {
    "left": "si", "right": "si", "lpad": "siS", "rpad": "siS",
    "repeat": "si", "strpos": "sS", "split_part": "sSi",
    "translate": "sSS", "replace": "s??", "to_hex": "i", "chr": "i",
    "initcap": "s", "reverse": "s", "md5": "s", "btrim": "s?",
    "lower": "s", "upper": "s", "trim": "s", "ltrim": "s?",
    "rtrim": "s?", "bit_length": "s", "octet_length": "s",
    "length": "s", "char_length": "s", "character_length": "s",
    "substr": "si?",
    "substring": "si?",
}


def _arg_type(a, schema):
    """'s'/'i'/'u'/'f'/'b'/'t'/None(unknown) for a scalar argument."""
    if isinstance(a, Column):
        name = a.name.split(".")[-1]
        if name == TIME_COL:
            return "t"
        if not schema.contains_column(name):
            return None
        ct = schema.column(name).column_type
        if ct.is_tag:
            return "s"
        return {ValueType.STRING: "s", ValueType.GEOMETRY: "s",
                ValueType.INTEGER: "i", ValueType.UNSIGNED: "u",
                ValueType.FLOAT: "f", ValueType.BOOLEAN: "b"}.get(
                    ct.value_type)
    if isinstance(a, Literal):
        from .expr import DateLit, TimeOfDayLit

        if isinstance(a, (DateLit, TimeOfDayLit)):
            return "d"
        v = a.value
        if isinstance(v, bool):
            return "b"
        if isinstance(v, str):
            return "s"
        if isinstance(v, int):
            return "i"
        if isinstance(v, float):
            return "f"
    return None


def _validate_scalar_sigs(e, schema):
    if not isinstance(e, Expr):
        return
    if isinstance(e, Func):
        sig = _SCALAR_SIGS.get(e.name.lower())
        if sig is not None:
            for a, want in zip(e.args, sig):
                got = _arg_type(a, schema)
                if got is None or want == "?":
                    continue
                if want == "i":
                    # Int64 strictly; a float LITERAL defers to the
                    # value check (2.0 casts, 2.7 errors there)
                    ok = got == "i" or (got == "f"
                                        and isinstance(a, Literal))
                elif want == "s":
                    ok = got == "s"
                elif want == "S":
                    # string with implicit numeric coercion (reference
                    # pads with bigint columns, searches int literals,
                    # casts time/date to ISO text)
                    ok = got in ("s", "i", "u", "f", "b", "t", "d")
                else:
                    ok = got == want
                if not ok:
                    raise PlanError(
                        f"no function matches {e.name}() for argument "
                        f"type {got!r} (expects {want!r})")
    from .expr import iter_child_exprs

    for c in iter_child_exprs(e):
        _validate_scalar_sigs(c, schema)


def _env_arg_type(a, env):
    """Argument type from a MATERIALIZED relational scope (joins): the
    time column by name, then dtype classification."""
    import numpy as np

    from ..models.strcol import DictArray

    if isinstance(a, Literal):
        from .expr import DateLit, TimeOfDayLit

        if isinstance(a, (DateLit, TimeOfDayLit)):
            return "d"
        return (
            "b" if isinstance(a.value, bool) else
            "s" if isinstance(a.value, str) else
            "i" if isinstance(a.value, int) else
            "f" if isinstance(a.value, float) else None)
    if not isinstance(a, Column):
        return None
    name = a.name
    if name == "time" or name.endswith(".time"):
        return "t"
    v = env.get(name)
    if v is None:
        return None
    if isinstance(v, DictArray):
        return "s"
    dt = getattr(v, "dtype", None)
    if dt is None:
        return None
    if dt == object:
        probe = next((x for x in v if x is not None), None)
        if isinstance(probe, str):
            return "s"
        if isinstance(probe, bool):
            return "b"
        if isinstance(probe, int):
            return "i"
        if isinstance(probe, float):
            return "f"
        return None
    return {"u": "u", "i": "i", "f": "f", "b": "b"}.get(dt.kind)


def validate_scalar_sigs_env(e, env):
    """Relational-path twin of _validate_scalar_sigs: argument types
    resolved from the materialized scope env."""
    if not isinstance(e, Expr):
        return
    if isinstance(e, Func):
        sig = _SCALAR_SIGS.get(e.name.lower())
        if sig is not None:
            for a, want in zip(e.args, sig):
                got = _env_arg_type(a, env)
                if got is None or want == "?":
                    continue
                if want == "i":
                    ok = got == "i" or (got == "f"
                                        and isinstance(a, Literal))
                elif want == "s":
                    ok = got == "s"
                elif want == "S":
                    # string with implicit numeric coercion (reference
                    # pads with bigint columns, searches int literals,
                    # casts time/date to ISO text)
                    ok = got in ("s", "i", "u", "f", "b", "t", "d")
                else:
                    ok = got == want
                if not ok:
                    raise PlanError(
                        f"no function matches {e.name}() for argument "
                        f"type {got!r} (expects {want!r})")
    from .expr import iter_child_exprs

    for c in iter_child_exprs(e):
        validate_scalar_sigs_env(c, env)


def _validate_stmt_scalar_sigs(stmt, schema):
    for it in stmt.items:
        if isinstance(it.expr, Expr):
            _validate_scalar_sigs(it.expr, schema)
    for e in (stmt.where, stmt.having):
        if e is not None:
            _validate_scalar_sigs(e, schema)


def plan_select(stmt: ast.SelectStmt, schema: TskvTableSchema):
    _validate_columns(stmt, schema)
    _validate_stmt_scalar_sigs(stmt, schema)
    time_trs, tag_domains, residual = split_where(stmt.where, schema)

    # aggregates may appear only in HAVING or ORDER BY (standard SQL:
    # `SELECT h FROM t GROUP BY h HAVING count(i) > 3`); a GROUP BY with
    # no aggregates anywhere is DISTINCT-on-keys — both are agg plans
    has_agg = any(_contains_agg(i.expr) for i in stmt.items
                  if isinstance(i.expr, Expr)) \
        or (stmt.having is not None and _contains_agg(stmt.having)) \
        or any(isinstance(oe, Expr) and _contains_agg(oe)
               for oe, _ in stmt.order_by)
    if not has_agg and not stmt.group_by:
        return _plan_raw(stmt, schema, time_trs, tag_domains, residual)
    return _plan_aggregate(stmt, schema, time_trs, tag_domains, residual)


def _validate_columns(stmt: ast.SelectStmt, schema: TskvTableSchema):
    """Unknown columns error at plan time (a column absent from one vnode's
    data is NULL, but a column absent from the schema is a user mistake)."""
    known = {c.name for c in schema.columns} | {TIME_COL}
    aliases = {it.alias for it in stmt.items if it.alias}

    def check(e, allow_alias=False):
        allowed = known | aliases if allow_alias else known
        unknown = e.columns() - allowed
        if unknown:
            raise PlanError(
                f"unknown column {sorted(unknown)[0]!r} in table {schema.name!r}")

    for it in stmt.items:
        if isinstance(it.expr, Expr):
            check(it.expr)
    if stmt.where is not None:
        check(stmt.where)
    if stmt.having is not None:
        check(stmt.having, allow_alias=True)
    for g in stmt.group_by:
        if isinstance(g, Expr):
            check(g, allow_alias=True)
    for oe, _asc in stmt.order_by:
        if isinstance(oe, Expr):
            check(oe, allow_alias=True)


def _contains_agg(e) -> bool:
    from .expr import iter_child_exprs

    if isinstance(e, Func) and e.name.lower() in AGG_FUNCS:
        return True
    return any(_contains_agg(c) for c in iter_child_exprs(e))


def _is_bucket_func(e) -> bool:
    return isinstance(e, Func) and e.name.lower() in (
        "date_bin", "time_window", "time_bucket", "time_window_gapfill")


def _bucket_params(e: Func) -> tuple[int, int]:
    """date_bin(INTERVAL, time[, origin]) / time_window(time, INTERVAL)."""
    name = e.name.lower()
    args = e.args
    if name == "date_bin":
        if not args or not isinstance(args[0], Literal) \
                or not isinstance(args[0].value, ast.IntervalValue):
            raise PlanError("date_bin needs INTERVAL first argument")
        interval = args[0].value.ns
        origin = 0
        if len(args) >= 3 and isinstance(args[2], Literal):
            v = args[2].value
            origin = parse_timestamp_string(v) if isinstance(v, str) else int(v)
        return origin, interval
    # time_window(time, interval) / time_bucket(interval, time)
    for a in args:
        if isinstance(a, Literal) and isinstance(a.value, ast.IntervalValue):
            return 0, a.value.ns
        if isinstance(a, Literal) and isinstance(a.value, str):
            from .parser import parse_interval_string

            return 0, parse_interval_string(a.value)
    raise PlanError(f"cannot extract interval from {e.to_sql()}")


class _AggCollector:
    def __init__(self, schema: TskvTableSchema):
        self.schema = schema
        self.aggs: list[AggSpec] = []
        self._by_key: dict[tuple, str] = {}

    def rewrite(self, e: Expr) -> Expr:
        """Replace aggregate calls with Column(alias) over partial results."""
        if isinstance(e, Func) and e.name.lower() in AGG_FUNCS:
            return Column(self._register(e))
        if isinstance(e, BinOp):
            return BinOp(e.op, self.rewrite(e.left), self.rewrite(e.right))
        if isinstance(e, UnaryOp):
            return UnaryOp(e.op, self.rewrite(e.operand))
        if isinstance(e, Func):
            return Func(e.name, [self.rewrite(a) for a in e.args])
        if isinstance(e, Case):
            return Case(
                self.rewrite(e.operand) if isinstance(e.operand, Expr)
                else e.operand,
                [(self.rewrite(c), self.rewrite(r)) for c, r in e.whens],
                self.rewrite(e.else_) if isinstance(e.else_, Expr)
                else e.else_)
        if isinstance(e, IsNull):
            return IsNull(self.rewrite(e.expr), e.negated)
        if isinstance(e, Between):
            return Between(self.rewrite(e.expr), self.rewrite(e.low),
                           self.rewrite(e.high), e.negated)
        if isinstance(e, InList):
            return InList(self.rewrite(e.expr), e.values, e.negated,
                          e.null_present)
        if isinstance(e, Cast):
            return Cast(self.rewrite(e.expr), e.target, e.safe)
        return e

    def _register(self, f: Func) -> str:
        name = f.name.lower()
        if name == "avg":
            name = "mean"
        # bool_or/bool_and over BOOLEAN == max/min (true > false), same
        # NULL-group semantics and true/false rendering
        name = {"bool_or": "max", "bool_and": "min"}.get(name, name)
        distinct = bool(f.args and isinstance(f.args[0], Literal)
                        and f.args[0].value == "__distinct__")
        args = [a for a in f.args
                if not (isinstance(a, Literal) and a.value == "__distinct__")]
        param = None
        ts_stripped = False
        if name in ("gauge_agg", "state_agg", "compact_state_agg") \
                and len(args) != 2:
            # strict reference signature (state_agg.slt pins errors for
            # 0/1/3-argument forms)
            raise PlanError(
                f"the function {name} takes (time, value), got "
                f"{len(args)} arguments: {f.to_sql()}")
        if (name in TS_PAIR_AGGS or name in ("first", "last")) \
                and len(args) == 2:
            ts_stripped = True
            if not (isinstance(args[0], Column) and args[0].name == TIME_COL):
                raise PlanError(
                    f"{name}(time, value): first argument must be the time "
                    f"column, got {f.to_sql()}")
            args = args[1:]   # reference signature f(time, value)
        if name in ("first", "last") and len(args) == 1 \
                and isinstance(args[0], Column) \
                and args[0].name == TIME_COL:
            # reference first/last take (time, value); a lone time column
            # is rejected there ("does not accept 1 function arguments")
            raise PlanError(
                f"the function {name} takes (time, value); min/max(time) "
                f"orders timestamps")
        if name == "sample":
            if len(args) != 2 or not isinstance(args[1], Literal):
                raise PlanError("sample(column, k) takes a column and a "
                                "constant size")
            param = int(args[1].value)
            args = args[:1]
        if name in _TWO_COL_AGGS:
            if len(args) == 2 and all(isinstance(a, Literal)
                                      and a.value is not None
                                      for a in args):
                # constants have zero variance: corr/covar → 0.0 when
                # rows exist (reference corr.slt: corr(1, 2) → 0.0)
                param = 0.0
                name, col = "const_agg:zero", None
                args = []
            elif len(args) != 2 or not all(isinstance(a, Column)
                                           for a in args):
                raise PlanError(
                    f"{name}(x, y) takes exactly two columns")
            else:
                param = args[1].name
                args = args[:1]
        if name == "approx_percentile_cont":
            # optional third arg = t-digest centroid count (validated,
            # then ignored: the exact computation needs no sketch size)
            if len(args) == 3 and isinstance(args[2], Literal) \
                    and isinstance(args[2].value, (int, float)) \
                    and not isinstance(args[2].value, bool):
                args = args[:2]
            if len(args) != 2 or not isinstance(args[1], Literal):
                raise PlanError(
                    "approx_percentile_cont(col, q) takes a column and "
                    "a constant quantile")
            param = float(args[1].value)
            if not 0.0 <= param <= 1.0:
                raise PlanError(
                    "Percentile value must be between 0.0 and 1.0 "
                    f"inclusive, {param} is invalid")
            args = args[:1]
        if name == "approx_percentile_cont_with_weight":
            if len(args) != 3 \
                    or not isinstance(args[1], (Column, Literal)) \
                    or not isinstance(args[2], Literal):
                raise PlanError(
                    "approx_percentile_cont_with_weight(col, w, q) takes "
                    "two columns and a constant quantile")
            q = float(args[2].value)
            if not 0.0 <= q <= 1.0:
                raise PlanError(
                    "Percentile value must be between 0.0 and 1.0 "
                    f"inclusive, {q} is invalid")
            if isinstance(args[1], Literal):
                # constant weight column (incl. negative constants, which
                # the weighted-cumsum computation handles the same way)
                param = (("__const_w__", float(args[1].value)), q)
            else:
                param = (args[1].name, q)
            args = args[:1]
        if name not in TS_PAIR_AGGS and name not in ("sample", "count") \
                and name not in _TWO_COL_AGGS \
                and not name.startswith("approx_percentile") \
                and len(args) > 1:
            raise PlanError(
                f"the function {name} takes exactly one argument, got "
                f"{len(args)}: {f.to_sql()}")
        if name == "count" and len(args) > 1:
            # count(a, b): rows where EVERY argument is non-NULL
            # (reference count.slt: count(t0, t1) over 8 rows → 8);
            # non-NULL constants never reduce the count, a NULL constant
            # zeroes it (sqlancer: count(1,2,3) == count(*))
            if any(isinstance(a, Literal) and a.value is None
                   for a in args):
                # count(x, NULL, ...) counts nothing: reduce to the
                # single-arg count(NULL) shape the dispatch below handles
                args = [Literal(None)]
            else:
                cols_only = [a for a in args if isinstance(a, Column)]
                if not all(isinstance(a, (Column, Literal))
                           for a in args):
                    raise PlanError("multi-argument count takes columns")
                if not cols_only:
                    args = [Literal("*")]   # all constants: count(*)
                elif len(cols_only) == 1:
                    args = cols_only
                else:
                    param = tuple(a.name for a in cols_only[1:])
                    args = cols_only[:1]
                    name = "count_multi" 
        if name == "count" and args and isinstance(args[0], Literal) \
                and args[0].value == "*":
            col = None
        elif name == "count" and args and isinstance(args[0], Literal):
            # count(<constant>): NULL counts nothing, any other constant
            # counts every row (reference/DataFusion count(0) == count(*))
            if args[0].value is None:
                name, col = "count_null_const", None
            else:
                col = None
        elif name in ("sum", "avg", "mean", "min", "max", "median",
                      "stddev", "stddev_samp", "stddev_pop", "var",
                      "var_samp", "var_pop", "first", "last",
                      "bit_and", "bit_or", "bit_xor") and args \
                and isinstance(args[0], Literal) \
                and args[0].value != "*":
            # aggregate over a CONSTANT (reference: avg(3) → 3.0): ride
            # the row count, finalize from the constant. A NULL constant
            # is rejected EXCEPT for first/last(time, NULL), which yield
            # NULL (reference last.slt).
            if args[0].value is None and not (
                    name in ("first", "last") and ts_stripped):
                # NULL constants reject except first/last(time, NULL)
                raise PlanError(f"{name}(NULL) is not supported")
            param = args[0].value
            name, col = "const_agg:" + name, None
        elif name.startswith("const_agg:"):
            pass   # already resolved to a constant aggregate above
        elif name == "array_agg" and args \
                and isinstance(args[0], Literal) \
                and args[0].value != "*":
            # constant element (array_agg(3), array_agg(NULL)): ride the
            # time column for the row count, substitute at finalize
            param = ("const_array", args[0].value,
                     param[1] if isinstance(param, tuple)
                     and param and param[0] == "order_time" else True)
            col = TIME_COL
        elif name in ("gauge_agg", "state_agg", "compact_state_agg") \
                and args and isinstance(args[0], Literal):
            # constant value column (compact_state_agg(time, 1)): collect
            # timestamps, substitute the constant at finalize
            param = ("const_state", args[0].value)
            col = TIME_COL
        else:
            if not args or not isinstance(args[0], Column):
                raise PlanError(f"aggregate argument must be a column: {f.to_sql()}")
            col = args[0].name
            if col != TIME_COL and not self.schema.contains_column(col):
                raise PlanError(f"unknown column {col!r} in {f.to_sql()}")
        if distinct:
            if name != "count":
                raise PlanError("DISTINCT only supported in count()")
            name = "count_distinct"
        if name == "array_agg" and getattr(f, "agg_order", None) \
                is not None and not (isinstance(param, tuple) and param
                                     and param[0] == "const_array"):
            oe, asc = f.agg_order
            if not (isinstance(oe, Column) and oe.name == TIME_COL):
                raise PlanError(
                    "array_agg ORDER BY supports the time column")
            param = ("order_time", asc)
        if name == "approx_distinct" and col is not None \
                and col != TIME_COL and self.schema.contains_column(col):
            c = self.schema.column(col)
            vt = getattr(getattr(c, "column_type", None), "value_type",
                         None)
            if vt is not None and vt.name in ("FLOAT", "BOOLEAN"):
                # DataFusion's HLL has no Float64/Boolean accumulators
                # (approx_distinct.slt pins both as errors)
                raise PlanError(
                    f"Support for 'approx_distinct' for data type "
                    f"{vt.name} is not implemented")
        if name == "approx_distinct" and col == TIME_COL:
            raise PlanError(
                "the function approx_distinct does not support inputs "
                "of type TIMESTAMP")
        # input-type validation (reference: "The function Avg does not
        # support inputs of type Timestamp(Nanosecond)/Utf8")
        if name in _NUMERIC_ONLY_AGGS:
            check_cols = [col] if col is not None else []
            if name in _TWO_COL_AGGS and isinstance(param, str):
                check_cols.append(param)
            if isinstance(param, tuple) and name.startswith(
                    "approx_percentile") \
                    and isinstance(param[0], str):   # weight column name
                check_cols.append(param[0])
            for cc in check_cols:
                if cc == TIME_COL:
                    raise PlanError(
                        f"the function {name} does not support inputs "
                        f"of type TIMESTAMP")
                if not self.schema.contains_column(cc):
                    raise PlanError(f"unknown column {cc!r} in {name}")
                c = self.schema.column(cc)
                if not c.column_type.is_tag \
                        and c.column_type.value_type in (
                            ValueType.STRING, ValueType.GEOMETRY) \
                        and name in _TWO_COL_AGGS:
                    # corr/covar over a string FIELD yield NULL
                    # (reference corr.slt/covar.slt); tags still error
                    name, col = "const_agg:null", None
                    param = None
                    break
                if c.column_type.is_tag or c.column_type.value_type in (
                        ValueType.STRING, ValueType.GEOMETRY):
                    raise PlanError(
                        f"the function {name} does not support inputs "
                        f"of type STRING")
                if c.column_type.value_type == ValueType.BOOLEAN:
                    raise PlanError(
                        f"the function {name} does not support inputs "
                        f"of type BOOLEAN")
        key = (name, col, param)
        if key in self._by_key:
            return self._by_key[key]
        alias = f"__agg{len(self.aggs)}"
        self.aggs.append(AggSpec(name if name != "count_star" else "count",
                                 col, alias, param))
        self._by_key[key] = alias
        return alias


def _plan_aggregate(stmt, schema, time_trs, tag_domains, residual):
    coll = _AggCollector(schema)
    tag_names = set(schema.tag_names())

    # aliases from select items (group by may reference them)
    alias_map: dict[str, Expr] = {}
    for it in stmt.items:
        if isinstance(it.expr, Expr) and it.alias:
            alias_map[it.alias] = it.expr

    group_tags: list[str] = []
    group_fields: list[str] = []
    all_fields = {c.name for c in schema.field_columns}
    bucket = None
    bucket_alias = None
    group_exprs: list[Expr] = []

    def classify_group(g):
        nonlocal bucket, bucket_alias
        if isinstance(g, int):
            if g < 1 or g > len(stmt.items):
                raise PlanError(f"GROUP BY position {g} out of range")
            g = stmt.items[g - 1].expr
        if isinstance(g, Column) and g.name in alias_map:
            alias = g.name
            g = alias_map[g.name]
            if _is_bucket_func(g):
                bucket = _bucket_params(g)
                bucket_alias = alias
                return
        if _is_bucket_func(g):
            bucket = _bucket_params(g)
            return
        if isinstance(g, Column):
            if g.name in tag_names:
                group_tags.append(g.name)
                return
            if g.name == TIME_COL:
                raise PlanError("GROUP BY time requires date_bin/time_window")
            if g.name in all_fields:
                # FIELD keys group on codes inside the segment kernels —
                # dictionary codes for strings, per-batch factorization
                # for numerics; same integer path as tags. Cardinality
                # blow-ups fall back to the relational pipeline at
                # execution (segment-budget guard).
                group_fields.append(g.name)
                return
            e = PlanError(
                f"can only GROUP BY tags, fields or time buckets, "
                f"got {g.name!r}")
            e.fallback_relational = True
            raise e
        e = PlanError(f"unsupported GROUP BY expression {g!r}")
        e.fallback_relational = True
        raise e

    for g in stmt.group_by:
        classify_group(g)

    # outputs
    gapfill = False
    fill_methods: dict[str, str] = {}
    output: list[tuple[str, Expr]] = []
    for idx, it in enumerate(stmt.items):
        e = it.expr
        if e == "*":
            raise PlanError("SELECT * cannot be combined with aggregates")
        if _is_bucket_func(e):
            name = it.alias or "time"
            if bucket is None:
                bucket = _bucket_params(e)
                bucket_alias = it.alias
            if e.name.lower() == "time_window_gapfill":
                gapfill = True
            output.append((name, Column("time")))
            continue
        # locf(...)/interpolate(...) wrap an aggregate output with a fill rule
        if isinstance(e, Func) and e.name.lower() in ("locf", "interpolate") \
                and len(e.args) == 1:
            name = it.alias or _default_agg_name(e)
            fill_methods[name] = e.name.lower()
            output.append((name, coll.rewrite(e.args[0])))
            continue
        if isinstance(e, Column) and e.name in tag_names:
            if e.name not in group_tags:
                raise PlanError(f"column {e.name!r} must appear in GROUP BY")
            output.append((it.alias or e.name, e))
            continue
        if isinstance(e, Column) and e.name in group_fields:
            output.append((it.alias or e.name, e))
            continue
        rewritten = coll.rewrite(e)
        name = it.alias or (e.to_sql() if not isinstance(e, Func)
                            else _default_agg_name(e))
        output.append((name, rewritten))

    having = coll.rewrite(stmt.having) if stmt.having is not None else None

    order_by = []
    for oe, asc in stmt.order_by:
        if isinstance(oe, Column):
            order_by.append((oe, asc))
        else:
            order_by.append((coll.rewrite(oe), asc))

    if (gapfill or fill_methods) and bucket is None:
        raise PlanError("gapfill/locf/interpolate require a time bucket")
    # Field group keys ride the fused path for every aggregate: kernel
    # aggregates reduce over the combined (tag × field × bucket) segment
    # ids directly, and the host-merged rest (count_distinct / collect* /
    # count_multi) decode the same segment layout in _merge_distinct_vec,
    # so their keys line up with the kernel partials. Only gapfill/fill
    # still needs the relational pipeline's dense group grid.
    if group_fields and (gapfill or fill_methods):
        e = PlanError(
            "field GROUP BY does not combine with gapfill/fill")
        e.fallback_relational = True
        raise e
    return AggregatePlan(
        table=stmt.table, schema=schema, time_ranges=time_trs,
        tag_domains=tag_domains, filter=residual, group_tags=group_tags,
        group_fields=group_fields,
        bucket=bucket, bucket_alias=bucket_alias, aggs=coll.aggs,
        output=output, having=having, order_by=order_by,
        limit=stmt.limit, offset=stmt.offset,
        gapfill=gapfill or bool(fill_methods), fill_methods=fill_methods)


def _default_agg_name(e: Func) -> str:
    args = ", ".join(a.to_sql() for a in e.args)
    return f"{e.name}({args})"


def _plan_raw(stmt, schema, time_trs, tag_domains, residual):
    output: list[tuple[str, Expr]] = []
    for it in stmt.items:
        if it.expr == "*":
            # declared column order (time first, then tags/fields exactly
            # as CREATE TABLE/ALTER laid them out — the reference keeps
            # schema order in SELECT *, it does not group tags)
            for c in schema.columns:
                output.append((c.name, Column(c.name)))
        else:
            name = it.alias or (it.expr.name if isinstance(it.expr, Column)
                                else it.expr.to_sql())
            output.append((name, it.expr))
    seen: set[str] = set()
    for name, _e in output:
        if name in seen:
            raise PlanError(
                f"Projections require unique expression names: {name!r} "
                f"appears more than once — alias one of them")
        seen.add(name)
    # ORDER BY <output alias> sorts by the aliased expression (standard
    # SQL; a real schema column of the same name wins to stay stable)
    alias_exprs = {it.alias: it.expr for it in stmt.items
                   if it.alias and isinstance(it.expr, Expr)}
    order_by = []
    for oe, asc in stmt.order_by:
        if isinstance(oe, Column) and oe.name in alias_exprs \
                and oe.name != TIME_COL \
                and not schema.contains_column(oe.name):
            oe = alias_exprs[oe.name]
        order_by.append((oe, asc))
    return RawScanPlan(
        table=stmt.table, schema=schema, time_ranges=time_trs,
        tag_domains=tag_domains, filter=residual, output=output,
        order_by=order_by, limit=stmt.limit, offset=stmt.offset,
        distinct=stmt.distinct)
