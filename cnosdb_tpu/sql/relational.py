"""Relational operators over columnar results: join / union / window /
host-side group-by.

The reference gets JOIN/UNION/subqueries/window functions from DataFusion
(query_server/query/src/sql/planner.rs lowers to DataFusion plans); here
they run host-side over the numpy columns the scan layer produces. The
single-table aggregate path stays on the fused device kernel (ops/fused);
these operators compose ABOVE materialized relations, which is where the
reference also runs them (DataFusion operators above TskvExec — SURVEY
§3.3 "the part to push to TPU"; TSDB joins are small dimension joins, so
host execution is the right default placement).

A `Scope` is the working shape: display-ordered output columns plus an env
that also exposes alias-qualified names ("a.col") for expression eval.
"""
from __future__ import annotations

import copy

import numpy as np

from ..errors import PlanError
from ..models.strcol import DictArray, dict_encode_strict
from ..ops import group_agg as _ga
from ..utils import stages
from .expr import BinOp, Column, Expr, Func, WindowFunc


class Scope:
    """Columns of one relational stage.

    names/cols: display order (SELECT * order); env: every addressable
    name including alias-qualified forms."""

    def __init__(self, names: list[str], cols: list, env: dict | None = None):
        self.names = list(names)
        self.cols = [np.asarray(c) for c in cols]
        self.env = dict(env) if env is not None else \
            {n: c for n, c in zip(self.names, self.cols)}
        self.quals: set[str] = set()   # relation qualifiers in scope

    @classmethod
    def from_relation(cls, names, cols, alias: str | None) -> "Scope":
        s = cls(names, cols)
        if alias:
            for n, c in zip(s.names, s.cols):
                s.env[f"{alias}.{n}"] = c
            s.quals = {alias}
        return s

    @property
    def n(self) -> int:
        return len(self.cols[0]) if self.cols else 0

    def filter(self, mask: np.ndarray) -> "Scope":
        out = Scope(self.names, [c[mask] for c in self.cols],
                    {k: v[mask] for k, v in self.env.items()})
        out.quals = set(self.quals)
        return out

    def take(self, idx: np.ndarray) -> "Scope":
        return Scope(self.names, [c[idx] for c in self.cols],
                     {k: v[idx] for k, v in self.env.items()})


def _null_take(col: np.ndarray, idx: np.ndarray):
    """col[idx] with idx == -1 yielding NULL (object None / float NaN);
    int/bool columns go to OBJECT arrays with None so values keep their
    integer identity (a float-promoted 100 would render as 100.0 and lose
    exactness past 2^53 — DataFusion likewise keeps Int64+null)."""
    missing = idx < 0
    if not missing.any():
        return col[idx]
    safe = np.where(missing, 0, idx)
    if len(col) == 0:
        return np.full(len(idx), None, dtype=object)
    out = col[safe].astype(object)
    out[missing] = None   # join padding is NULL, never NaN (NaN is a
    # value the reference renders as 'NaN')
    return out


def null_safe_key(v: np.ndarray):
    """→ (sortable values, null flags | None) — object columns with Nones
    are not directly orderable (shared with executor._order_limit)."""
    v = np.asarray(v)
    if v.dtype != object:
        return v, None
    nulls = np.array([x is None for x in v], dtype=np.int8)
    non_null = [x for x in v if x is not None]
    if non_null and all(isinstance(x, dict) for x in non_null):
        # composite struct column (time_window / gauge dicts): sort by
        # the natural ordering of its fields — windows by (start, end)
        def skey(x):
            if x is None:
                return ""
            if x.get("kind") == "window":
                # bias to unsigned so lexicographic == chronological
                # for pre-epoch starts too
                return (f"{x['start'] + (1 << 63):020d}:"
                        f"{x['end'] + (1 << 63):020d}")
            return str(x)

        return np.array([skey(x) for x in v], dtype=object), \
            (nulls if nulls.any() else None)
    if non_null and all(
            isinstance(x, (int, np.integer))
            and not isinstance(x, (bool, np.bool_)) for x in non_null):
        # all-integer object column: int64 keys keep exactness past 2^53
        # (the whole reason NULL-bearing int columns ride as objects)
        try:
            vals = np.array([0 if x is None else int(x) for x in v],
                            dtype=np.int64)
            return vals, (nulls if nulls.any() else None)
        except OverflowError:
            pass   # u64-range values: fall through to float keys
    if non_null and all(
            isinstance(x, (int, float, np.integer, np.floating))
            and not isinstance(x, (bool, np.bool_)) for x in non_null):
        # mixed numeric object column (NULL-bearing floats as objects):
        # order NUMERICALLY — stringifying would sort '12' before '5'
        vals = np.array([0.0 if x is None else float(x) for x in v],
                        dtype=np.float64)
        return vals, (nulls if nulls.any() else None)
    vals = v
    if nulls.any():
        vals = np.array([("" if x is None else x) for x in v], dtype=object)
    try:
        vals = vals.astype("U")
    except (TypeError, ValueError):
        pass
    return vals, (nulls if nulls.any() else None)


def bit_reduce(kind: str, vals):
    """BIT_AND/BIT_OR/BIT_XOR over a value sequence (NULL/NaN skipped;
    NULL when nothing remains) — the one shared implementation for the
    grouped and finalize paths."""
    import functools
    import operator as _op

    ints = [int(x) for x in vals
            if x is not None and not (isinstance(x, float) and x != x)]
    if not ints:
        return None
    red = {"bit_and": _op.and_, "bit_or": _op.or_,
           "bit_xor": _op.xor}[kind]
    return functools.reduce(red, ints)


def _split_conjuncts(e: Expr | None) -> list[Expr]:
    if e is None:
        return []
    if isinstance(e, BinOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _equi_keys(on: Expr | None, lscope: set[str], rscope: set[str]):
    """Split ON into equi-join key pairs + residual conjuncts."""
    keys, residual = [], []
    for c in _split_conjuncts(on):
        if isinstance(c, BinOp) and c.op == "=":
            lc, rc = c.left.columns(), c.right.columns()
            if lc and rc:
                if lc <= lscope and rc <= rscope:
                    keys.append((c.left, c.right))
                    continue
                if lc <= rscope and rc <= lscope:
                    keys.append((c.right, c.left))
                    continue
        residual.append(c)
    return keys, residual


def _key_tuple(arrays: list, i: int) -> tuple | None:
    """Row i's join key; None when any component is NULL — SQL equi-joins
    never match on NULL (NULL = NULL is unknown)."""
    out = []
    for a in arrays:
        v = a[i].item() if hasattr(a[i], "item") else a[i]
        if v is None or (isinstance(v, float) and v != v):
            return None
        out.append(v)
    return tuple(out)


def _factorize_key_pair(lk: np.ndarray, rk: np.ndarray):
    """→ (lcodes, rcodes, lvalid, rvalid) with equal values sharing a code
    across both sides, or None when the dtypes defeat vectorization.
    NULL (None) and NaN keys never match — they get valid=False."""
    def prep(a):
        if a.dtype == object:
            valid = np.array([x is not None for x in a], dtype=bool)
            if not all(isinstance(x, str) for x, v in zip(a, valid) if v):
                return None   # mixed object types: python-equality fallback
            filled = a.copy()
            filled[~valid] = ""
            return filled.astype("U"), valid, "str"
        if np.issubdtype(a.dtype, np.floating):
            valid = ~np.isnan(a)
            return a.astype(np.float64), valid, "float"
        if np.issubdtype(a.dtype, np.integer) or a.dtype == bool:
            # keep ints exact: float64 would alias keys above 2^53
            return a.astype(np.int64), np.ones(len(a), dtype=bool), "int"
        if a.dtype.kind in ("U", "S"):
            return a.astype("U"), np.ones(len(a), dtype=bool), "str"
        return None

    pl, pr = prep(lk), prep(rk)
    if pl is None or pr is None:
        return None
    (lv, lvalid, lkind), (rv, rvalid, rkind) = pl, pr
    if {lkind, rkind} == {"int", "float"}:
        # mixed int/float equality (5 == 5.0): widen the int side only here
        lv, rv = lv.astype(np.float64), rv.astype(np.float64)
    elif lkind != rkind:
        return None   # string-vs-number keys: fallback decides equality
    both = np.concatenate([lv, rv])
    _, inv = np.unique(both, return_inverse=True)
    return (inv[:len(lv)].astype(np.int64), inv[len(lv):].astype(np.int64),
            lvalid, rvalid)


def _vector_join_indices(lkeys, rkeys, ln: int, rn: int):
    """Vectorized equi-join matching: factorize each key pair, combine to
    one id per row, sort the right side once, then searchsorted expansion
    builds (li, ri) without a per-row python probe loop (the HashJoinExec
    role, done the columnar way)."""
    lid = np.zeros(ln, dtype=np.int64)
    rid = np.zeros(rn, dtype=np.int64)
    lvalid = np.ones(ln, dtype=bool)
    rvalid = np.ones(rn, dtype=bool)
    for lk, rk in zip(lkeys, rkeys):
        f = _factorize_key_pair(lk, rk)
        if f is None:
            return None
        lc, rc, lv, rv = f
        card = int(max(lc.max(initial=0), rc.max(initial=0))) + 1
        lid = lid * card + lc
        rid = rid * card + rc
        lvalid &= lv
        rvalid &= rv
    order = np.flatnonzero(rvalid)[
        np.argsort(rid[rvalid], kind="stable")]
    rs = rid[order]
    lsel = np.flatnonzero(lvalid)
    lo = np.searchsorted(rs, lid[lsel], "left")
    hi = np.searchsorted(rs, lid[lsel], "right")
    counts = hi - lo
    total = int(counts.sum())
    li = np.repeat(lsel, counts)
    # right side: concatenated order[lo_i : hi_i] ranges, vectorized
    if total:
        starts = np.repeat(lo, counts)
        prior = np.repeat(np.cumsum(counts) - counts, counts)
        ri = order[starts + (np.arange(total) - prior)]
    else:
        ri = np.empty(0, dtype=np.int64)
    return li.astype(np.int64), ri.astype(np.int64)


def hash_join(left: Scope, right: Scope, kind: str,
              on: Expr | None) -> Scope:
    """Hash equi-join with residual filter; inner/left/right/full/cross
    (reference defers to DataFusion's HashJoinExec)."""
    if kind != "cross" and on is None:
        raise PlanError("JOIN requires an ON condition (use CROSS JOIN)")
    dup = left.quals & right.quals
    if dup:
        raise PlanError(
            f"table name {sorted(dup)[0]!r} specified more than once — "
            "alias one side")
    keys, residual = ([], []) if kind == "cross" else \
        _equi_keys(on, set(left.env), set(right.env))
    ln, rn = left.n, right.n
    if keys:
        def key_arr(e, env):
            v = e.eval(env, np)
            # materialize dictionary columns HERE: np.asarray would wrap
            # a DictArray as one opaque object, breaking key comparison
            return v.materialize() if isinstance(v, DictArray) \
                else np.asarray(v)

        lkeys = [key_arr(le, left.env) for le, _ in keys]
        rkeys = [key_arr(re, right.env) for _, re in keys]
        vec = _vector_join_indices(lkeys, rkeys, ln, rn)
        if vec is not None:
            li, ri = vec
        else:
            # fallback for key types numpy can't factorize (mixed objects)
            table: dict = {}
            for j in range(rn):
                k = _key_tuple(rkeys, j)
                if k is not None:
                    table.setdefault(k, []).append(j)
            li_l, ri_l = [], []
            for i in range(ln):
                k = _key_tuple(lkeys, i)
                for j in (table.get(k, ()) if k is not None else ()):
                    li_l.append(i)
                    ri_l.append(j)
            li = np.asarray(li_l, dtype=np.int64)
            ri = np.asarray(ri_l, dtype=np.int64)
    else:
        li = np.repeat(np.arange(ln, dtype=np.int64), rn)
        ri = np.tile(np.arange(rn, dtype=np.int64), ln)

    if residual and len(li):
        env = {}
        for k, v in right.env.items():
            env[k] = v[ri]
        for k, v in left.env.items():
            env[k] = v[li]   # left wins bare-name collisions
        mask = np.ones(len(li), dtype=bool)
        for c in residual:
            m = np.asarray(c.eval(env, np))
            mask &= m if m.shape else np.full(len(li), bool(m))
        li, ri = li[mask], ri[mask]

    if kind in ("left", "full"):
        matched = np.zeros(ln, dtype=bool)
        matched[li[li >= 0]] = True
        extra = np.nonzero(~matched)[0]
        li = np.concatenate([li, extra])
        ri = np.concatenate([ri, np.full(len(extra), -1, dtype=np.int64)])
    if kind in ("right", "full"):
        matched = np.zeros(rn, dtype=bool)
        matched[ri[ri >= 0]] = True
        extra = np.nonzero(~matched)[0]
        li = np.concatenate([li, np.full(len(extra), -1, dtype=np.int64)])
        ri = np.concatenate([ri, extra])

    names, cols, env = [], [], {}
    taken_l = {k: _null_take(v, li) for k, v in left.env.items()}
    taken_r = {k: _null_take(v, ri) for k, v in right.env.items()}
    # display columns POSITIONALLY: duplicate bare names (several `time`
    # columns under SELECT *) must each keep their own values, which a
    # name-keyed lookup would collapse to the leftmost; reuse the env take
    # when the display column IS the env column (the common, unique case)
    for n_, c in zip(left.names, left.cols):
        names.append(n_)
        cols.append(taken_l[n_] if left.env.get(n_) is c
                    else _null_take(c, li))
    for n_, c in zip(right.names, right.cols):
        names.append(n_)
        cols.append(taken_r[n_] if right.env.get(n_) is c
                    else _null_take(c, ri))
    env.update(taken_r)
    env.update(taken_l)   # left wins bare-name collisions
    out = Scope(names, cols, env)
    out.quals = left.quals | right.quals
    return out


# ---------------------------------------------------------------------------
# host group-by (relational path; the single-table path uses fused kernels)
# ---------------------------------------------------------------------------
def group_indices(key_cols: list, n: int):
    """→ (group id per row [n], representative row per group).

    Per-axis dense codes (ops.group_agg key factorization) chained into
    one combined id, then re-densified — the same factorize → combine
    layout the segment kernels use, timed under the factorize_ms stage."""
    if n == 0:
        return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.int64)
    if not key_cols:
        stages.count("group_count", 1)
        return np.zeros(n, dtype=np.int64), np.zeros(1, dtype=np.int64)
    with stages.stage("factorize_ms"):
        parts = []
        for kc in key_cols:
            if isinstance(kc, DictArray):
                # already factorized: codes are ranks into the sorted
                # dictionary (re-densified by the final np.unique below)
                parts.append((kc.codes.astype(np.int64).ravel(),
                              len(kc.values)))
                continue
            kc = np.asarray(kc)
            if kc.dtype == object:
                enc = dict_encode_strict(kc)
                if enc is not None:
                    parts.append((enc.codes.astype(np.int64).ravel(),
                                  len(enc.values)))
                    continue
                # mixed/null keys keep the legacy stringified sort
                kc = kc.astype("U")
            _, inv = np.unique(kc, return_inverse=True)
            inv = inv.astype(np.int64).ravel()
            parts.append((inv, int(inv.max()) + 1))
        ids, _ = _ga.combine_codes(parts)
        _, first_idx, gid = np.unique(ids, return_index=True,
                                      return_inverse=True)
    stages.count("group_count", len(first_idx))
    return gid.astype(np.int64).ravel(), first_idx.astype(np.int64)


def _col_valid(col) -> np.ndarray:
    if col.dtype == object:
        return np.array([v is not None and not (isinstance(v, float)
                                                and v != v) for v in col],
                        dtype=bool)
    if np.issubdtype(col.dtype, np.floating):
        return ~np.isnan(col)
    return np.ones(len(col), dtype=bool)


def host_aggregate(func: str, col, gid: np.ndarray, n_groups: int,
                   distinct: bool = False, col2=None, param=None):
    """One aggregate over grouped rows (relational/host path)."""
    func = func.lower()
    func = {"approx_median": "median", "stddev_samp": "stddev",
            "var": "var_samp", "approx_distinct": "count_distinct_",
            "covar": "covar_samp", "mean": "avg",
            "bool_or": "max", "bool_and": "min"}.get(func, func)
    if func == "count_distinct_":
        return host_aggregate("count", col, gid, n_groups, distinct=True)
    if func == "count" and col is None:
        return np.bincount(gid, minlength=n_groups).astype(np.int64)
    if col is None:
        raise PlanError(f"aggregate {func} needs an argument")
    col = np.asarray(col)
    if col.shape == ():
        # constant argument (count(1), sum(2)): broadcast over the rows
        col = np.full(len(gid), col[()])
    if col.dtype == object:
        valid = np.array([v is not None for v in col], dtype=bool)
    elif np.issubdtype(col.dtype, np.floating):
        valid = ~np.isnan(col)
    else:
        valid = np.ones(len(col), dtype=bool)
    g, v = gid[valid], col[valid]
    if func == "count":
        if distinct:
            fast = _ga.distinct_count(g, v, n_groups)
            if fast is not None:
                return fast
            # unfactorizable payload (mixed-type / NaN objects): the
            # per-row set fold is the only path with exact Python
            # equality semantics
            out = np.zeros(n_groups, dtype=np.int64)
            seen: dict[int, set] = {}
            for i in range(len(g)):
                seen.setdefault(int(g[i]), set()).add(
                    v[i] if col.dtype == object else v[i].item())
            for k, s in seen.items():
                out[k] = len(s)
            return out
        return np.bincount(g, minlength=n_groups).astype(np.int64)
    if func in ("sum", "avg", "mean"):
        c = np.bincount(g, minlength=n_groups)
        if func == "sum":
            # integer columns sum in their own arithmetic (exact past
            # 2^53, and 12 must not render as 12.0); object columns of
            # NULL-bearing ints get the same treatment — this matches the
            # fused kernel path and DataFusion's Sum(Int64) → Int64
            acc_dtype = None
            if np.issubdtype(col.dtype, np.integer):
                acc_dtype = np.uint64 if col.dtype.kind == "u" else np.int64
            elif col.dtype == object and len(v) and all(
                    isinstance(x, (int, np.integer))
                    and not isinstance(x, (bool, np.bool_)) for x in v):
                acc_dtype = np.int64
            if acc_dtype is not None:
                try:
                    vi = v.astype(acc_dtype)
                    # wrap guard: if |max| * largest-group-count could
                    # exceed the accumulator, sum exactly in python ints
                    # (DataFusion errors here; exact beats both)
                    lim = 2**64 - 1 if acc_dtype == np.uint64 else 2**63 - 1
                    mx = max(abs(int(vi.min())), abs(int(vi.max()))) \
                        if len(vi) else 0
                    if mx and mx > lim // max(int(c.max()), 1):
                        out = np.full(n_groups, None, dtype=object)
                        accs: dict[int, int] = {}
                        for gi, val in zip(g.tolist(), vi.tolist()):
                            accs[gi] = accs.get(gi, 0) + int(val)
                        for gi, s_ in accs.items():
                            out[gi] = s_
                        return out
                    acc = np.zeros(n_groups, dtype=acc_dtype)
                    np.add.at(acc, g, vi)
                    if (c == 0).any():   # SUM over no rows is NULL
                        out = acc.astype(object)
                        out[c == 0] = None
                        return out
                    return acc
                except (OverflowError, ValueError):
                    pass   # out-of-range values: fall through to float
            s = np.bincount(g, weights=v.astype(np.float64),
                            minlength=n_groups)
            return _null_where(s, c == 0)
        s = np.bincount(g, weights=v.astype(np.float64), minlength=n_groups)
        with np.errstate(invalid="ignore", divide="ignore"):
            out = s / np.maximum(c, 1)
        return _null_where(out, c == 0)
    if func in ("min", "max"):
        fast = _ga.group_min_max(func, g, v, n_groups)
        if fast is not None:
            best, filled = fast
            if col.dtype == object:
                return best          # None holes already in place
            if np.issubdtype(col.dtype, np.integer) and filled.all():
                return best.astype(col.dtype)
            if col.dtype == bool and filled.all():
                return best.astype(bool)
            return _null_where(best.astype(np.float64), ~filled)
        # unfactorizable object payload: scalar Python compare fold
        out = np.full(n_groups, None, dtype=object)
        for i in range(len(g)):
            cur = out[g[i]]
            if cur is None or (func == "min" and v[i] < cur) \
                    or (func == "max" and v[i] > cur):
                out[g[i]] = v[i]
        return out
    if func in ("corr", "covar_samp", "covar_pop"):
        if col2 is None:
            raise PlanError(f"{func} takes two columns")
        col2 = np.asarray(col2)
        pair_ok = valid & _col_valid(col2)
        g2, x, y = gid[pair_ok], \
            col[pair_ok].astype(np.float64), col2[pair_ok].astype(np.float64)
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g2):
            xs, ys = x[g2 == k], y[g2 == k]
            if func == "corr":
                if len(xs) >= 2 and np.std(xs) > 0 and np.std(ys) > 0:
                    out[k] = float(np.corrcoef(xs, ys)[0, 1])
            else:
                ddof = 1 if func == "covar_samp" else 0
                if len(xs) > ddof:
                    out[k] = float(np.cov(xs, ys, ddof=ddof)[0, 1])
        return out
    if func == "approx_percentile_cont":
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g):
            grp = v[g == k].astype(np.float64)
            if len(grp):
                out[k] = float(np.quantile(grp, float(param)))
        return out
    if func == "approx_percentile_cont_with_weight":
        if col2 is None:
            raise PlanError(
                "approx_percentile_cont_with_weight takes a weight column")
        col2 = np.asarray(col2)
        pair_ok = valid & _col_valid(col2)
        g2 = gid[pair_ok]
        x = col[pair_ok].astype(np.float64)
        w = col2[pair_ok].astype(np.float64)
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g2):
            xs, ws = x[g2 == k], w[g2 == k]
            order = np.argsort(xs)
            xs, ws = xs[order], ws[order]
            cum = np.cumsum(ws)
            if len(xs) and cum[-1] > 0:
                idx = int(np.searchsorted(cum, float(param) * cum[-1],
                                          side="left"))
                out[k] = float(xs[min(idx, len(xs) - 1)])
        return out
    if func == "sample":
        from . import tsfuncs

        out = np.full(n_groups, None, dtype=object)
        for k_ in np.unique(g):
            out[k_] = tsfuncs.sample(v[g == k_],
                                     int(param) if param is not None
                                     else 1)
        return out
    if func == "array_agg":
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g):
            grp = v[g == k]
            out[k] = "[" + ", ".join(_arr_cell(x) for x in grp) + "]"
        return out
    if func in ("gauge_agg", "state_agg", "compact_state_agg"):
        # (time, value) pair aggregates: col carries the values, col2 the
        # timestamps (executor binds them); one tsfuncs call per group
        from . import tsfuncs

        if col2 is None:
            raise PlanError(f"{func} takes (time, value)")
        ts = np.asarray(col2)
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g):
            sel = g == k
            tsv = ts[valid][sel].astype(np.int64)
            if func == "gauge_agg":
                vals = v[sel].astype(np.float64)
                order = np.argsort(tsv, kind="stable")
                out[k] = tsfuncs.gauge_data(tsv[order], vals[order])
            else:
                out[k] = tsfuncs.state_data(
                    tsv, v[sel], compact=(func == "compact_state_agg"))
        return out
    if func in ("bit_and", "bit_or", "bit_xor"):
        out = np.full(n_groups, None, dtype=object)
        for k in np.unique(g):
            out[k] = bit_reduce(func, v[g == k])
        return out
    if func in ("median", "stddev", "stddev_pop", "var_samp", "var_pop",
                "mode"):
        # order-statistic / modal aggregates: one numpy pass per group
        # after a single stable group sort (reference: DataFusion's
        # accumulator set; time-ordered first/last stay kernel-only — row
        # order after a join is arbitrary and would be silently wrong)
        order = np.argsort(g, kind="stable")
        gs, vs = g[order], v[order]
        starts = np.flatnonzero(np.diff(gs, prepend=-1))
        out = np.full(n_groups, None, dtype=object)
        for k, s0 in enumerate(starts):
            s1 = starts[k + 1] if k + 1 < len(starts) else len(gs)
            grp = vs[s0:s1]
            gi = int(gs[s0])
            if func == "median":
                from .executor import _median_value

                out[gi] = _median_value(grp)
            elif func == "stddev":
                out[gi] = (float(np.std(grp.astype(np.float64), ddof=1))
                           if len(grp) > 1 else None)
            elif func == "stddev_pop":
                out[gi] = float(np.std(grp.astype(np.float64), ddof=0))
            elif func == "var_samp":
                out[gi] = (float(np.var(grp.astype(np.float64), ddof=1))
                           if len(grp) > 1 else None)
            elif func == "var_pop":
                out[gi] = float(np.var(grp.astype(np.float64), ddof=0))
            else:
                uniq, cnt = np.unique(grp, return_counts=True)
                out[gi] = uniq[int(np.argmax(cnt))]
        return out
    raise PlanError(f"unsupported aggregate {func!r} over joined relations")


def _null_where(arr: np.ndarray, mask: np.ndarray):
    """NULL out slots (object/None) — NaN stays a value."""
    if not mask.any():
        return arr
    out = arr.astype(object)
    out[mask] = None
    return out


def _arr_cell(v) -> str:
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    if isinstance(v, (bool, np.bool_)):
        return "true" if v else "false"
    if isinstance(v, np.integer):
        return str(int(v))
    return str(v)


# ---------------------------------------------------------------------------
# expression tree utilities (agg / window discovery + rewrite)
# ---------------------------------------------------------------------------
_CHILD_ATTRS = ("left", "right", "operand", "expr", "low", "high",
                "else_", "pattern")


def walk_exprs(e, fn):
    """Depth-first visit of every Expr node."""
    if not isinstance(e, Expr):
        return
    fn(e)
    for attr in _CHILD_ATTRS:
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            walk_exprs(child, fn)
    for a in getattr(e, "args", None) or []:
        walk_exprs(a, fn)
    for c, r in getattr(e, "whens", None) or []:   # CASE arms
        walk_exprs(c, fn)
        walk_exprs(r, fn)


def rewrite_exprs(e, pred, replace):
    """Copy-on-write rewrite: nodes matching pred become replace(node)."""
    if not isinstance(e, Expr):
        return e
    if pred(e):
        return replace(e)
    out = copy.copy(e)
    for attr in _CHILD_ATTRS:
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            setattr(out, attr, rewrite_exprs(child, pred, replace))
    if getattr(e, "args", None):
        out.args = [rewrite_exprs(a, pred, replace) for a in e.args]
    if getattr(e, "whens", None):
        out.whens = [(rewrite_exprs(c, pred, replace),
                      rewrite_exprs(r, pred, replace))
                     for c, r in e.whens]
    return out


def contains_window(e) -> bool:
    found = []
    walk_exprs(e, lambda x: found.append(x) if isinstance(x, WindowFunc)
               else None)
    return bool(found)


def collect_aggs(e, agg_names: set) -> list:
    """Top-level aggregate calls (not recursing INTO them — their args are
    row-level expressions)."""
    out = []

    def visit(x):
        if isinstance(x, Func) and not isinstance(x, WindowFunc) \
                and x.name.lower() in agg_names:
            out.append(x)
            return
        for attr in _CHILD_ATTRS:
            child = getattr(x, attr, None)
            if isinstance(child, Expr):
                visit(child)
        for a in getattr(x, "args", None) or []:
            if isinstance(a, Expr):
                visit(a)

    if isinstance(e, Expr):
        visit(e)
    return out


# ---------------------------------------------------------------------------
# window functions
# ---------------------------------------------------------------------------
_RANKERS = {"row_number", "rank", "dense_rank", "percent_rank",
            "cume_dist"}
_OFFSETS = {"lag", "lead"}
_VALUES = {"first_value", "last_value", "nth_value"}
_WINAGGS = {"sum", "avg", "mean", "min", "max", "count"}

WINDOW_FUNCS = _RANKERS | _OFFSETS | _VALUES | _WINAGGS


def eval_window(wf: WindowFunc, env: dict, n: int) -> np.ndarray:
    """Evaluate one window function over an n-row scope.

    SQL default frame semantics: ranking functions require ORDER BY;
    aggregates are running when ORDER BY is present (UNBOUNDED PRECEDING
    .. CURRENT ROW) and whole-partition otherwise."""
    name = wf.name.lower()
    if n == 0:
        return np.zeros(0, dtype=np.int64 if name in _RANKERS else np.float64)
    part_cols = [np.asarray(e.eval(env, np)) for e in (wf.partition_by or [])]
    gid, _ = group_indices(part_cols, n)
    order_keys = []
    for e, asc in reversed(wf.order_by or []):
        vals, nulls = null_safe_key(np.asarray(e.eval(env, np)))
        if not asc:
            _, inv = np.unique(vals, return_inverse=True)
            vals = -inv.astype(np.int64)
        order_keys.append(vals)
        if nulls is not None:
            order_keys.append(nulls if asc else -nulls)
    order_keys.append(gid)
    perm = np.lexsort(order_keys)  # partition-major, order-keyed inside
    sorted_gid = gid[perm]
    starts = np.nonzero(np.r_[True, sorted_gid[1:] != sorted_gid[:-1]])[0]
    ends = np.r_[starts[1:], n]
    out = np.empty(n, dtype=np.float64)

    def ordered_vals(e: Expr):
        v = np.asarray(e.eval(env, np))
        if v.shape == ():
            v = np.full(n, v[()])
        return v[perm]

    if name in _RANKERS:
        if wf.args and not (len(wf.args) == 1 and getattr(
                wf.args[0], "value", None) == "*"):
            raise PlanError(f"{name}() takes no arguments")
        # without ORDER BY the input order ranks (reference accepts
        # row_number() OVER (); every row is then its own peer group)
        keys = [ordered_vals(e) for e, _ in wf.order_by] \
            if wf.order_by else []
        res = np.empty(n, dtype=np.float64) \
            if name in ("percent_rank", "cume_dist") \
            else np.empty(n, dtype=np.int64)
        for s, e_ in zip(starts, ends):
            cnt = e_ - s
            if name == "row_number":
                res[perm[s:e_]] = np.arange(1, cnt + 1)
                continue
            if name == "cume_dist":
                # rows ≤ current (peers count together)
                i = s
                while i < e_:
                    j = i
                    while j + 1 < e_ and all(
                            np.array_equal(k[j + 1], k[i]) for k in keys):
                        j += 1
                    for t in range(i, j + 1):
                        res[perm[t]] = (j + 1 - s) / cnt
                    i = j + 1
                continue
            r = d = 1
            for i in range(s, e_):
                if i > s and keys and not all(
                        np.array_equal(k[i], k[i - 1]) for k in keys):
                    r = (i - s) + 1
                    d += 1
                if name == "percent_rank":
                    res[perm[i]] = 0.0 if cnt <= 1 else (r - 1) / (cnt - 1)
                else:
                    res[perm[i]] = r if name == "rank" else d
        return res

    if name in _OFFSETS:
        if len(wf.args) > 3:
            raise PlanError(
                f"{name} takes at most 3 arguments (value, offset, "
                f"default)")
        src = ordered_vals(wf.args[0])
        offset = 1
        if len(wf.args) > 1:
            try:
                ov = wf.args[1].eval({}, np)
                if not isinstance(ov, (bool, np.bool_)) \
                        and float(ov) == int(ov):
                    # 2.5 / booleans degrade like a bad string would
                    offset = int(ov)
            except (TypeError, ValueError):
                pass
            # non-integral / non-numeric offsets degrade to the default
            # of 1 (reference lag.slt: 'invalid_offset' and 2.5 both
            # behave as LAG(v, 1, ...))
        default = None
        if len(wf.args) > 2:
            default = wf.args[2].eval({}, np)
            if hasattr(default, "item"):
                default = default.item()
            # the default must match the value column's type family
            # (reference lag.slt/lead.slt: bool/str vs numeric and float
            # vs Int64 all error)
            src_probe = np.asarray(wf.args[0].eval(env, np))
            num_kind = src_probe.dtype.kind in "iuf" or (
                src_probe.dtype == object and any(
                    isinstance(x, (int, float))
                    and not isinstance(x, bool)
                    for x in src_probe if x is not None))
            int_kind = src_probe.dtype.kind in "iu" or (
                src_probe.dtype == object and all(
                    isinstance(x, (int, np.integer))
                    and not isinstance(x, bool)
                    for x in src_probe if x is not None))
            if isinstance(default, bool) \
                    or (isinstance(default, str) and num_kind) \
                    or (isinstance(default, float) and int_kind):
                raise PlanError(
                    "lag/lead default must match the value type")
        shift = offset if name == "lag" else -offset
        res = np.empty(n, dtype=object)
        for s, e_ in zip(starts, ends):
            seg = src[s:e_]
            for i in range(len(seg)):
                j = i - shift
                res[perm[s + i]] = seg[j] if 0 <= j < len(seg) else default
        # every input keeps value identity in an object array with None
        # at the frame edges (NULL ≠ NaN: NaN renders 'NaN')
        return res

    if name in _VALUES:
        if name in ("first_value", "last_value") and len(wf.args) != 1:
            raise PlanError(f"{name} takes exactly one argument")
        if name == "nth_value" and len(wf.args) != 2:
            raise PlanError("nth_value takes (expr, n)")
        src = ordered_vals(wf.args[0])
        # frame semantics (reference/standard SQL): with ORDER BY the
        # default frame is UNBOUNDED PRECEDING..CURRENT ROW ('cum'),
        # without it the whole partition; ROWS BETWEEN overrides
        frame = wf.frame or ("cum" if wf.order_by else "full")
        nth = None
        if name == "nth_value":
            if len(wf.args) < 2:
                raise PlanError("nth_value takes (expr, n)")
            n_raw = np.asarray(wf.args[1].eval(env, np)).reshape(-1)[0]
            if isinstance(n_raw, (float, np.floating)) \
                    and float(n_raw) != int(n_raw):
                raise PlanError("nth_value expects an integer n")
            nth = int(n_raw)
            if nth == 0:
                # n = 0 errors; NEGATIVE n yields NULL rows (reference
                # nth_value.slt pins both behaviors)
                raise PlanError("nth_value expects n > 0")
        res = np.empty(n, dtype=object)
        for s, e_ in zip(starts, ends):
            for i in range(s, e_):
                lo = s if frame in ("cum", "full") else i
                hi = (i + 1) if frame == "cum" else e_
                if name == "first_value":
                    v = src[lo]
                elif name == "last_value":
                    v = src[hi - 1]
                else:   # nth_value
                    v = src[lo + nth - 1] \
                        if nth > 0 and (hi - lo) >= nth else None
                res[perm[i]] = v
        return res

    if name in _WINAGGS:
        star = (len(wf.args) == 1
                and getattr(wf.args[0], "value", None) == "*")
        src = None if (name == "count" and star) else ordered_vals(wf.args[0])
        cumulative = bool(wf.order_by)
        # sum/min/max of an integral NULL-free column stay INTEGERS
        # (DataFusion: sum(Int64) → Int64); only NULL-bearing or float
        # inputs go through the NaN-carrying float path
        if src is not None and src.dtype.kind in "iu" \
                and name in ("sum", "min", "max", "count"):
            out = np.empty(n, dtype=np.int64)
            for s, e_ in zip(starts, ends):
                seg = src[s:e_]
                if name == "count":
                    vals = (np.arange(1, e_ - s + 1) if cumulative
                            else np.full(e_ - s, e_ - s))
                elif cumulative:
                    vals = {"sum": np.cumsum,
                            "min": np.minimum.accumulate,
                            "max": np.maximum.accumulate}[name](seg)
                else:
                    vals = np.full(e_ - s, {"sum": np.sum, "min": np.min,
                                            "max": np.max}[name](seg))
                out[perm[s:e_]] = vals
            return out
        for s, e_ in zip(starts, ends):
            seg = None if src is None else src[s:e_]
            if name == "count":
                if seg is None:
                    vals = (np.arange(1, e_ - s + 1) if cumulative
                            else np.full(e_ - s, e_ - s))
                else:
                    ok = (np.array([x is not None for x in seg])
                          if seg.dtype == object
                          else ~np.isnan(seg.astype(np.float64)))
                    vals = (np.cumsum(ok) if cumulative
                            else np.full(e_ - s, int(ok.sum())))
                out[perm[s:e_]] = vals
                continue
            segf = seg.astype(np.float64)
            if cumulative:
                if name in ("sum", "avg", "mean"):
                    cs = np.nancumsum(segf)
                    if name == "sum":
                        vals = cs
                    else:
                        cnt = np.cumsum(~np.isnan(segf))
                        vals = cs / np.maximum(cnt, 1)
                elif name == "min":
                    vals = np.fmin.accumulate(segf)
                else:
                    vals = np.fmax.accumulate(segf)
            else:
                agg = {"sum": np.nansum, "avg": np.nanmean,
                       "mean": np.nanmean, "min": np.nanmin,
                       "max": np.nanmax}[name](segf) if len(segf) else np.nan
                vals = np.full(e_ - s, agg)
            out[perm[s:e_]] = vals
        return out

    raise PlanError(f"unsupported window function {wf.name!r}")
