"""Time-series function families: counter increase, sampling, gauge/state
aggregation, data-quality metrics, data repair, and GIS scalars.

Behavior-parity with the reference's extension functions
(query_server/query/src/extension/expr/):
- increase: aggregate_function/increase.rs:82-107 — counter resets add the
  post-reset value instead of a negative delta;
- sample: aggregate_function/sample.rs — k-reservoir;
- gauge_agg + accessors: aggregate_function/gauge/mod.rs:44-118;
- state_agg / compact_state_agg, duration_in, state_at:
  aggregate_function/state_agg/state_agg_data.rs:89-152;
- completeness/consistency/timeliness/validity:
  aggregate_function/data_quality/common.rs (NaN interpolation, windowed
  timestamp anomaly detection, MAD outlier counting);
- timestamp_repair / value_fill / value_repair:
  ts_gen_func/data_repair/*.rs (median/mode interval reconstruction,
  mean/previous/linear fill, SCREEN speed clamping);
- st_* GIS: scalar_function/gis/ (WKT geometries).

All functions are pure numpy over (time, value) arrays — they run host-side
at aggregate finalize (whole-group context), which is also where the
reference runs them (DataFusion accumulators, not the scan kernel).
"""
from __future__ import annotations

import math
import re

import numpy as np

from ..errors import FunctionError

NS = 1_000_000_000


# ---------------------------------------------------------------------------
# counter increase (exact reset handling)
# ---------------------------------------------------------------------------
def increase(ts: np.ndarray, vals: np.ndarray) -> float | None:
    """Counter increase with reset handling (increase.rs:98-103): a drop
    means the counter restarted, so the post-reset value is the delta.
    Integer inputs stay integer (reference: increase(Int64) renders 7,
    not 7.0)."""
    if len(vals) == 0:
        return None
    integral = all(isinstance(x, (int, np.integer))
                   and not isinstance(x, (bool, np.bool_))
                   for x in np.asarray(vals).tolist())
    v = np.asarray(vals, dtype=np.float64)
    if len(v) == 1:
        return 0 if integral else 0.0
    d = np.diff(v)
    out = float(np.where(d > 0, d, np.where(d < 0, v[1:], 0.0)).sum())
    return int(out) if integral else out


# ---------------------------------------------------------------------------
# sample (k-reservoir)
# ---------------------------------------------------------------------------
def sample(vals: np.ndarray, k: int) -> list:
    """k-reservoir sample (sample.rs). Deterministic seed per call keeps
    query results reproducible across replicas."""
    n = len(vals)
    if k <= 0 or k > 2000:
        # reference bound: sample size in (0, 2000] (sample.slt)
        raise FunctionError("sample size must be in (0, 2000]")

    def plain(x):
        return x.item() if hasattr(x, "item") else x

    if n <= k:
        return [plain(v) for v in vals]
    rng = np.random.default_rng(abs(hash((n, k))) % (2**32))
    idx = np.sort(rng.choice(n, size=k, replace=False))
    return [plain(v) for v in np.asarray(vals)[idx]]


# ---------------------------------------------------------------------------
# gauge_agg
# ---------------------------------------------------------------------------
def gauge_data(ts: np.ndarray, vals: np.ndarray) -> dict | None:
    """GaugeData (gauge/mod.rs): first/second/penultimate/last TSPoints."""
    n = len(ts)
    if n == 0:
        return None
    t = np.asarray(ts, dtype=np.int64)
    v = np.asarray(vals, dtype=np.float64)
    return {
        "kind": "gauge",
        "first": [int(t[0]), float(v[0])],
        "second": [int(t[min(1, n - 1)]), float(v[min(1, n - 1)])],
        "penultimate": [int(t[max(0, n - 2)]), float(v[max(0, n - 2)])],
        "last": [int(t[-1]), float(v[-1])],
        "num_elements": int(n),
    }


def gauge_delta(g: dict) -> float:
    return g["last"][1] - g["first"][1]


class IntervalNs(int):
    """A nanosecond span that RENDERS as an arrow interval (the value
    stays an int for arithmetic/comparisons; server._cell formats it)."""

    def __repr__(self):
        return format_interval_ns(int(self))


def format_interval_ns(ns: int) -> str:
    """Arrow IntervalMonthDayNano rendering: '0 years 0 mons 0 days
    0 hours 0 mins 0.005 secs'. The seconds field uses float repr
    (shortest round-trip) — the reference renders 9 fixed digits, which
    the slt port normalizes through repr(float(...)): 0.035000000 →
    0.035, 0.000000000 → 0.0, 0.000000007 → 7e-09."""
    neg = ns < 0
    ns = abs(int(ns))
    days, rem = divmod(ns, 86_400_000_000_000)
    hours, rem = divmod(rem, 3_600_000_000_000)
    mins, rem = divmod(rem, 60_000_000_000)
    secs = rem / 1e9
    sign = "-" if neg else ""
    return (f"{sign}0 years 0 mons {days} days {hours} hours "
            f"{mins} mins {secs!r} secs")


def chrono_iso(ns: int) -> str:
    """chrono NaiveDateTime rendering: ISO seconds plus a fractional
    part of exactly 0, 3, 6 or 9 digits (the least that is exact) —
    how the reference renders timestamps inside gauge/window structs."""
    from datetime import datetime, timezone

    secs, frac = divmod(int(ns), 1_000_000_000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if frac == 0:
        return base
    if frac % 1_000_000 == 0:
        return f"{base}.{frac // 1_000_000:03d}"
    if frac % 1_000 == 0:
        return f"{base}.{frac // 1_000:06d}"
    return f"{base}.{frac:09d}"


def render_composite(v: dict) -> str:
    """Reference Display text for composite aggregate values (gauge
    structs, time_window structs); other dicts fall back to str()."""
    kind = v.get("kind")
    if kind == "gauge":
        def tsp(p):
            return f"{{ts: {chrono_iso(p[0])}, val: {float(p[1])!r}}}"

        return (f"{{first: {tsp(v['first'])}, second: {tsp(v['second'])}, "
                f"penultimate: {tsp(v['penultimate'])}, "
                f"last: {tsp(v['last'])}, "
                f"num_elements: {v['num_elements']}}}")
    if kind == "window":
        return (f"{{start: {chrono_iso(v['start'])}, "
                f"end: {chrono_iso(v['end'])}}}")
    return str(v)


def gauge_time_delta(g: dict) -> "IntervalNs":
    """Interval between first and last sample (gauge/time_delta.rs
    returns an Interval; IntervalNs renders it in arrow's format)."""
    return IntervalNs(g["last"][0] - g["first"][0])


def _gauge_time_delta_ns(g: dict) -> int:
    return g["last"][0] - g["first"][0]


def gauge_rate(g: dict) -> float | None:
    td = _gauge_time_delta_ns(g)
    if td == 0:
        return None
    return gauge_delta(g) / float(td)


def gauge_idelta_left(g: dict) -> float:
    return g["second"][1] - g["first"][1]


def gauge_idelta_right(g: dict) -> float:
    return g["last"][1] - g["penultimate"][1]


# ---------------------------------------------------------------------------
# state_agg / compact_state_agg
# ---------------------------------------------------------------------------
def state_data(ts: np.ndarray, states: np.ndarray,
               compact: bool = False) -> dict | None:
    """StateAggData (state_agg_data.rs): per-state total duration and, for
    the non-compact form, the [start, end) periods. A state's period runs
    until the NEXT reading's timestamp; the final reading contributes no
    duration (no successor), matching the reference accumulator."""
    n = len(ts)
    if n == 0:
        return None
    t = np.asarray(ts, dtype=np.int64)
    order = np.argsort(t, kind="stable")
    t = t[order]
    s = np.asarray(states)[order]
    durations: dict = {}
    periods: dict = {}
    cur_state = s[0]
    cur_start = int(t[0])
    for i in range(1, n):
        if s[i] != cur_state:
            end = int(t[i])
            durations[cur_state] = durations.get(cur_state, 0) + (end - cur_start)
            if not compact:
                periods.setdefault(cur_state, []).append([cur_start, end])
            cur_state = s[i]
            cur_start = end
    end = int(t[-1])
    if end > cur_start:
        durations[cur_state] = durations.get(cur_state, 0) + (end - cur_start)
        if not compact:
            periods.setdefault(cur_state, []).append([cur_start, end])
    d = {str(k): int(v) for k, v in durations.items()}
    p = {str(k): v for k, v in periods.items()}
    return {"kind": "state", "compact": compact,
            "durations": d, "periods": p,
            # reference StateAggData struct field names (dotted access:
            # state.state_duration / state.state_periods)
            "state_duration": d, "state_periods": p}


def duration_in(sa: dict, state, start: int | None = None,
                interval: int | None = None) -> int:
    """Total time in `state` (state_agg_data.rs:89-136), optionally
    restricted to [start, start+interval)."""
    if interval is not None and hasattr(interval, "ns"):
        interval = interval.ns   # ast.IntervalValue literal
    key = str(state)
    if start is None:
        return IntervalNs(sa["durations"].get(key, 0))
    if sa.get("compact"):
        raise FunctionError("duration_in with a time range needs state_agg "
                            "(not compact_state_agg)")
    periods = sa["periods"].get(key, [])
    total = 0
    end = start + interval if interval is not None else None
    for p_start, p_end in periods:
        if p_end <= start:
            continue
        if end is not None and p_start >= end:
            continue
        lo = max(p_start, start)
        hi = p_end if end is None else min(p_end, end)
        if hi > lo:
            total += hi - lo
    return IntervalNs(total)


def state_at(sa: dict, ts: int):
    """State whose period covers ts (state_agg_data.rs:138-152)."""
    if sa.get("compact"):
        raise FunctionError("state_at needs state_agg (not compact form)")
    for state, periods in sa["periods"].items():
        for p_start, p_end in periods:
            if p_start <= ts < p_end:
                return state
    return None


# ---------------------------------------------------------------------------
# data-quality metrics (data_quality/common.rs)
# ---------------------------------------------------------------------------
def _dq_median(x: np.ndarray) -> float:
    return float(np.median(x)) if len(x) else 0.0


def _dq_mad(x: np.ndarray) -> float:
    mid = _dq_median(x)
    return 1.4826 * _dq_median(np.abs(x - mid))


def _dq_outliers(x: np.ndarray, k: float = 3.0) -> int:
    if len(x) == 0:
        return 0
    mid = _dq_median(x)
    sigma = _dq_mad(x)
    return int((np.abs(x - mid) > k * sigma).sum())


class _DataQuality:
    """Port of DataSeriesQuality: NaN interpolation then timestamp-window
    and value-outlier counting (common.rs:40-215)."""

    WINDOW = 10

    def __init__(self, ts: np.ndarray, vals: np.ndarray):
        t = np.asarray(ts, dtype=np.float64)
        v = np.asarray(vals, dtype=np.float64).copy()
        self.cnt = len(t)
        bad = ~np.isfinite(v)
        self.specialcnt = int(bad.sum())
        v[bad] = np.nan
        good = np.nonzero(~np.isnan(v))[0]
        if len(good) < 2:
            raise FunctionError("at least two finite values are needed")
        # linear interpolation through NaNs, extrapolating the edges from
        # the first/last pair of good points (common.rs nan_process)
        v = np.interp(t, t[good], v[good])
        i1, i2 = good[0], good[1]
        slope = (v[i2] - v[i1]) / (t[i2] - t[i1]) if t[i2] != t[i1] else 0.0
        head = np.arange(len(t)) < i1
        v[head] = v[i1] + slope * (t[head] - t[i1])
        j1, j2 = good[-2], good[-1]
        slope = (v[j2] - v[j1]) / (t[j2] - t[j1]) if t[j2] != t[j1] else 0.0
        tail = np.arange(len(t)) > j2
        v[tail] = v[j1] + slope * (t[tail] - t[j1])
        self.t, self.v = t, v
        self.misscnt = self.latecnt = self.redundancycnt = 0
        self._time_detect()
        self._value_detect()

    def _time_detect(self):
        t = self.t
        if len(t) < 2:
            return
        base = _dq_median(np.diff(t))
        if base == 0:
            return
        window = list(t[:self.WINDOW])
        i = len(window)
        while len(window) > 1:
            times = (window[1] - window[0]) / base
            if times <= 0.5:
                window.pop(1)
                self.redundancycnt += 1
            elif 2.0 <= times <= 9.0:
                temp = 0
                j = 2
                while j < len(window):
                    times2 = (window[j] - window[j - 1]) / base
                    if times2 >= 2.0:
                        break
                    if times2 <= 0.5:
                        temp += 1
                        window.pop(j)
                        j -= 1
                        if temp == round(times - 1.0):
                            break
                    j += 1
                self.latecnt += temp
                self.misscnt += round(times - 1.0) - temp
            window.pop(0)
            while len(window) < self.WINDOW and i < self.cnt:
                window.append(t[i])
                i += 1

    def _value_detect(self):
        v, t = self.v, self.t
        self.valuecnt = _dq_outliers(v)
        self.variationcnt = _dq_outliers(np.diff(v))
        with np.errstate(invalid="ignore", divide="ignore"):
            speed = np.diff(v) / np.diff(t)
        self.speedcnt = _dq_outliers(speed)
        self.speedchangecnt = _dq_outliers(np.diff(speed))

    def completeness(self) -> float:
        return 1.0 - (self.misscnt + self.specialcnt) / (self.cnt + self.misscnt)

    def consistency(self) -> float:
        return 1.0 - self.redundancycnt / self.cnt

    def timeliness(self) -> float:
        return 1.0 - self.latecnt / self.cnt

    def validity(self) -> float:
        return 1.0 - 0.25 * (self.valuecnt + self.variationcnt
                             + self.speedcnt + self.speedchangecnt) / self.cnt


def data_quality(metric: str, ts: np.ndarray, vals: np.ndarray) -> float:
    dq = _DataQuality(ts, vals)
    return getattr(dq, metric)()


# ---------------------------------------------------------------------------
# data repair (ts_gen_func/data_repair/)
# ---------------------------------------------------------------------------
def _median_quirk(x) -> float:
    """The reference's interval/f64 median: sorts the DIFF array but
    indexes it with the SERIES length n (timestamps count), i.e.
    interval[n/2] over n-1 intervals (value_repair.rs interval_median /
    timestamp_repair.rs get_interval_median) — kept bit-for-bit, except
    the out-of-range read a 2-point series triggers upstream (a Rust
    panic) clamps to the last interval here."""
    x = sorted(x)
    n = len(x) + 1
    hi = len(x) - 1
    if n % 2 == 0:
        return (x[min(n // 2 - 1, hi)] + x[min(n // 2, hi)]) / 2
    return x[min(n // 2, hi)]


def _fdiv(a, b) -> float:
    """Rust f64 division semantics: x/0 → ±inf, 0/0 → NaN (Python would
    raise; duplicate timestamps across merged series hit this)."""
    a, b = float(a), float(b)
    if b == 0.0:
        if a == 0.0 or a != a:
            return float("nan")
        return float("inf") if a > 0 else float("-inf")
    return a / b


def _f64_median(x) -> float:
    x = sorted(x)
    n = len(x)
    if n % 2 == 0:
        return (x[n // 2 - 1] + x[n // 2]) / 2.0
    return x[n // 2]


def _mad_ref(x) -> float:
    mid = _f64_median(x)
    return 1.4826 * _f64_median([abs(v - mid) for v in x])


def _kmeans_1d(data: list[int], k: int = 3) -> int:
    """k-means over interval samples; returns the mean of the largest
    cluster (timestamp_repair.rs k_means_clustering, integer math)."""
    if not data:
        return 0
    lo, hi = min(data), max(data)
    means = [lo + (i + 1) * (hi - lo) // (k + 1) for i in range(k)]
    results = [0] * len(data)
    changed = True
    clusters: dict[int, list[int]] = {}
    while changed:
        changed = False
        for i, d in enumerate(data):
            best = min(range(k), key=lambda j: abs(d - means[j]))
            if best != results[i]:
                changed = True
                results[i] = best
        clusters = {}
        for i, r in enumerate(results):
            clusters.setdefault(r, []).append(data[i])
        for j in range(k):
            s = clusters.get(j, [])
            if s:
                means[j] = sum(s) // len(s)
    cnts = [len(clusters.get(j, [])) for j in range(k)]
    biggest = max(range(k), key=lambda j: cnts[j])
    s = clusters.get(biggest, [])
    return sum(s) // len(s) if s else 0


def _interval_estimate(t: np.ndarray, method: str) -> int:
    d = [int(x) for x in np.diff(t)]
    if not d:
        return 1
    if method == "mode":
        best_key, best = 0, 0
        counts: dict[int, int] = {}
        for x in d:
            counts[x] = counts.get(x, 0) + 1
        for key, times in counts.items():
            if times > best:
                best, best_key = times, key
        return best_key
    if method == "cluster":
        return _kmeans_1d(d, 3)
    return int(_median_quirk(d))


def _start_estimate(t: np.ndarray, delta: int, start_mode: str) -> int:
    if start_mode == "linear":
        total = 0
        for i, v in enumerate(t):
            total += int(v) - i * delta
        return total // len(t)
    # mode: most common residue class; latest sample in it, walked back
    # to at/below the first timestamp
    counts: dict[int, int] = {}
    mods = []
    for v in t:
        m = int(v) % delta
        mods.append(m)
        counts[m] = counts.get(m, 0) + 1
    best_key, best = 0, 0
    for key, times in counts.items():
        if times > best:
            best, best_key = times, key
    result = 0
    for i, m in enumerate(mods):
        if m == best_key:
            result = int(t[i])
    first = int(t[0])
    while result > first:
        result -= delta
    return result


_REPAIR_DP_CELL_CAP = 25_000_000


def timestamp_repair(ts: np.ndarray, vals: np.ndarray,
                     method: str | None = None,
                     interval: int | None = None,
                     start_mode: str | None = None
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Edit-distance timestamp repair (timestamp_repair.rs dp_repair):
    estimate interval (median/mode/cluster or explicit, ms→ns) and grid
    start (mode/linear), then DP-align the samples onto the grid with
    insert/remove/shift costs. Inserted slots carry NaN — the reference
    never interpolates here."""
    t = np.asarray(ts, dtype=np.int64)
    v = np.asarray(vals, dtype=np.float64).copy()
    v[~np.isfinite(v)] = np.nan
    if len(t) <= 2:
        return t, v
    order = np.argsort(t, kind="stable")
    t, v = t[order], v[order]
    if interval is not None:
        if interval <= 0:
            raise FunctionError("interval must be positive")
        step = int(interval) * 1_000_000   # ms → ns
    else:
        step = max(1, _interval_estimate(t, method or "median"))
    start = _start_estimate(t, step, start_mode or "mode")
    m = len(t)
    import math

    n = math.ceil((int(t[-1]) - start) / step + 1.0)
    if n <= 0 or n * m > _REPAIR_DP_CELL_CAP:
        raise FunctionError(
            f"timestamp_repair DP over {n}x{m} cells exceeds the cap")
    ADD = 100_000_000_000
    # f[i][j]: cost of producing i grid slots from the first j samples
    f = np.empty((n + 1, m + 1), dtype=np.int64)
    steps = np.zeros((n + 1, m + 1), dtype=np.int8)   # 0=nothing 1=ins 2=rm
    f[:, 0] = ADD * np.arange(n + 1, dtype=np.int64)
    steps[:, 0] = 1
    f[0, :] = ADD * np.arange(m + 1, dtype=np.int64)
    steps[0, :] = 2
    tj = t.astype(np.int64)
    for i in range(1, n + 1):
        slot_ts = start + step * (i - 1)
        for j in range(1, m + 1):
            if tj[j - 1] == slot_ts:
                f[i, j] = f[i - 1, j - 1]
                steps[i, j] = 0
            else:
                if f[i - 1, j] < f[i, j - 1]:
                    f[i, j] = f[i - 1, j] + ADD
                    steps[i, j] = 1
                else:
                    f[i, j] = f[i, j - 1] + ADD
                    steps[i, j] = 2
                modify = f[i - 1, j - 1] + abs(int(tj[j - 1]) - slot_ts)
                if modify < f[i, j]:
                    f[i, j] = modify
                    steps[i, j] = 0
    out_ts = np.zeros(n, dtype=np.int64)
    out_v = np.zeros(n, dtype=np.float64)
    i, j = n, m
    while i >= 1 and j >= 1:
        ps = start + step * (i - 1)
        s = steps[i, j]
        if s == 0:
            out_ts[i - 1] = ps
            out_v[i - 1] = v[j - 1]
            i -= 1
            j -= 1
        elif s == 1:
            out_ts[i - 1] = ps
            out_v[i - 1] = np.nan
            i -= 1
        else:
            j -= 1
    return out_ts, out_v


def value_fill(ts: np.ndarray, vals: np.ndarray,
               method: str = "linear") -> np.ndarray:
    """Fill NaN values (value_fill.rs): mean / previous / linear (by
    INDEX distance, edges carried from the nearest sample) / AR(1) /
    5-wide moving average."""
    v = np.asarray(vals, dtype=np.float64).copy()
    v[~np.isfinite(v)] = np.nan
    good = np.nonzero(~np.isnan(v))[0]
    if len(good) == 0:
        raise FunctionError("All values are Invalid")
    method = method.lower()
    n = len(v)
    if method == "mean":
        out = v.copy()
        out[np.isnan(v)] = v[good].mean()
        return out
    if method == "previous":
        idx = np.maximum.accumulate(
            np.where(~np.isnan(v), np.arange(n), -1))
        out = np.where(idx >= 0, v[np.maximum(idx, 0)], np.nan)
        return out
    if method == "linear":
        # index-based interpolation (the reference interpolates by sample
        # POSITION, not timestamp); leading gap takes the first sample,
        # trailing gap the last
        out = v.copy()
        out[np.isnan(v)] = np.interp(np.nonzero(np.isnan(v))[0], good,
                                     v[good])
        return out
    if method == "ar":
        mean = v[good].mean()
        left = np.nan_to_num(v[:-1], nan=0.0)
        right = np.nan_to_num(v[1:], nan=0.0)
        factor = float((left * left).sum())
        if factor == 0.0:
            raise FunctionError(
                "Cannot fit AR(1) model. Please try another method.")
        theta = float((left * right).sum()) / factor
        both = ~np.isnan(v[:-1]) & ~np.isnan(v[1:])
        if not both.any():
            raise FunctionError(
                "Cannot fit AR(1) model. Please try another method.")
        eps = float((v[1:][both] - theta * v[:-1][both]).mean())
        out = np.empty(n)
        for i in range(n):
            if np.isnan(v[i]):
                out[i] = theta * out[i - 1] + eps if i else mean
            else:
                out[i] = v[i]
        return out
    if method == "ma":
        # sliding 5-window mean over known values, advanced exactly as
        # the reference does (window trails for the first/last two rows)
        w = 5
        r = w - 1
        win_sum = float(np.nansum(v[:min(r, n)]))
        win_cnt = int((~np.isnan(v[:min(r, n)])).sum())
        out = np.empty(n)
        for i in range(n):
            out[i] = v[i] if not np.isnan(v[i]) \
                else _fdiv(win_sum, win_cnt)
            if i <= (w - 1) // 2 or i >= n - (w - 1) // 2 - 1:
                continue
            if r < n and not np.isnan(v[r]):
                win_sum += v[r]
                win_cnt += 1
            r += 1
        return out
    raise FunctionError(f"Invalid fill method: {method}")


def _process_nan_inplace(t: np.ndarray, v: np.ndarray):
    """value_repair.rs process_nan: linear-fill every NaN through the
    surrounding finite samples BY TIMESTAMP, extrapolating the edges from
    the first/last finite pair. Needs ≥ 2 finite values."""
    good = np.nonzero(np.isfinite(v))[0]
    if len(good) < 2:
        raise FunctionError("At least two non-NaN values are needed")
    i1, i2 = int(good[0]), int(good[1])
    for i in range(i2):
        v[i] = v[i1] + (v[i2] - v[i1]) * _fdiv(
            int(t[i]) - int(t[i1]), int(t[i2]) - int(t[i1]))
    for i in range(i2 + 1, len(v)):
        if np.isfinite(v[i]):
            i1, i2 = i2, i
            for j in range(i1 + 1, i2):
                v[j] = v[i1] + (v[i2] - v[i1]) * _fdiv(
                    int(t[j]) - int(t[i1]), int(t[i2]) - int(t[i1]))
    for i in range(i2 + 1, len(v)):
        v[i] = v[i1] + (v[i2] - v[i1]) * _fdiv(
            int(t[i]) - int(t[i1]), int(t[i2]) - int(t[i1]))


def _screen_repair(t: np.ndarray, v: np.ndarray,
                   smin: float | None, smax: float | None) -> np.ndarray:
    """SCREEN (value_repair.rs screen): windowed-median speed repair.
    Window = 5× median interval; bounds default to median speed ± 3·MAD."""
    n = len(v)
    w = 5 * int(_median_quirk([int(x) for x in np.diff(t)]))
    speeds = [_fdiv(v[i + 1] - v[i], int(t[i + 1]) - int(t[i]))
              for i in range(n - 1)]
    sigma = _mad_ref(speeds)
    mid = _f64_median(speeds)
    if smin is None:
        smin = mid - 3.0 * sigma
    if smax is None:
        smax = mid + 3.0 * sigma
    ans = [[int(t[i]), float(v[i])] for i in range(n)]

    def get_median(start):
        m = 0
        while start + m + 1 < len(ans) and \
                ans[start + m + 1][0] <= ans[start][0] + w:
            m += 1
        x = [0.0] * (2 * m + 1)
        x[0] = ans[start][1]
        for i in range(1, m + 1):
            x[i] = ans[start + i][1] + smin * (ans[start][0]
                                               - ans[start + i][0])
            x[i + m] = ans[start + i][1] + smax * (ans[start][0]
                                                   - ans[start + i][0])
        x.sort()
        return x[m]

    def local(start):
        mid_v = get_median(start)
        if start == 0:
            ans[start][1] = mid_v
        else:
            xmin = ans[start - 1][1] + smin * (ans[start][0]
                                               - ans[start - 1][0])
            xmax = ans[start - 1][1] + smax * (ans[start][0]
                                               - ans[start - 1][0])
            ans[start][1] = max(xmin, min(xmax, mid_v))

    start_index = 0
    for i in range(1, n):
        while ans[start_index][0] + w < ans[i][0]:
            local(start_index)
            start_index += 1
    while start_index < n:
        local(start_index)
        start_index += 1
    return np.array([a[1] for a in ans])


def _lsgreedy_repair(t: np.ndarray, v: np.ndarray,
                     center: float | None, sigma: float | None) -> np.ndarray:
    """LsGreedy (value_repair.rs lsgreedy): greedily flatten the largest
    speed-change outlier until all |u - center| fall within 3σ."""
    n = len(v)
    out = v.astype(np.float64).copy()
    if n < 3:
        return out
    speeds = [_fdiv(out[i + 1] - out[i], int(t[i + 1]) - int(t[i]))
              for i in range(n - 1)]
    changes = [speeds[i + 1] - speeds[i] for i in range(len(speeds) - 1)]
    center = 0.0 if center is None else center
    if sigma is None:
        sigma = _mad_ref(changes) if changes else 0.0
    eps = 1e-12

    def u_of(i):
        v1 = _fdiv(out[i + 1] - out[i], int(t[i + 1]) - int(t[i]))
        v2 = _fdiv(out[i] - out[i - 1], int(t[i]) - int(t[i - 1]))
        return v1 - v2

    for _ in range(10 * n + 100):   # greedy loop; provably shrinks u
        cand = [(abs(u_of(i) - center), i) for i in range(1, n - 1)]
        cand = [c for c in cand if c[0] > 3.0 * sigma]
        if not cand:
            break
        top_u, idx = max(cand)
        if top_u < max(eps, 3.0 * sigma):
            break
        u = u_of(idx)
        if sigma < eps:
            temp = abs(u - center)
        else:
            temp = max(sigma, abs((u - center) / 3.0))
        temp *= _fdiv((int(t[idx + 1]) - int(t[idx]))
                      * (int(t[idx]) - int(t[idx - 1])),
                      int(t[idx + 1]) - int(t[idx - 1]))
        if u > center:
            out[idx] += temp
        else:
            out[idx] -= temp
    return out


def value_repair(ts: np.ndarray, vals: np.ndarray,
                 method: str = "screen",
                 min_speed: float | None = None,
                 max_speed: float | None = None,
                 center: float | None = None,
                 sigma: float | None = None) -> np.ndarray:
    """Value repair (value_repair.rs): NaNs linear-filled first, then
    SCREEN (windowed-median speed clamp) or LsGreedy."""
    t = np.asarray(ts, dtype=np.int64)
    v = np.asarray(vals, dtype=np.float64).copy()
    v[~np.isfinite(v)] = np.nan
    if len(v) < 2:
        return v
    _process_nan_inplace(t, v)
    if method == "lsgreedy":
        return _lsgreedy_repair(t, v, center, sigma)
    return _screen_repair(t, v, min_speed, max_speed)


# ---------------------------------------------------------------------------
# GIS (scalar_function/gis/ — WKT geometries)
# ---------------------------------------------------------------------------
_WKT_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"


def _parse_wkt(wkt: str):
    """→ (type, list of (x, y)) — flattened points of any WKT geometry
    (full grammar incl. EMPTY and multi types via sql.gis)."""
    if wkt is None:
        return None
    from . import gis

    try:
        g = gis.parse_wkt(str(wkt))
    except Exception:
        return _parse_wkt_legacy(wkt)
    return (g.kind, list(gis._points(g)))


def _parse_wkt_legacy(wkt: str):
    if wkt is None:
        return None
    m = re.match(r"\s*(POINT|LINESTRING|POLYGON)\s*\((.*)\)\s*$",
                 str(wkt).strip(), re.IGNORECASE)
    if not m:
        raise FunctionError(f"bad WKT geometry: {wkt!r}")
    gtype = m.group(1).upper()
    body = m.group(2)
    if gtype == "POLYGON":
        ring = re.match(r"\s*\((.*?)\)", body)
        if not ring:
            raise FunctionError(f"bad WKT polygon: {wkt!r}")
        body = ring.group(1)
    pts = []
    for pair in body.split(","):
        nums = re.findall(_WKT_NUM, pair)
        if len(nums) < 2:
            raise FunctionError(f"bad WKT coordinates: {pair!r}")
        pts.append((float(nums[0]), float(nums[1])))
    return gtype, pts


def _seg_point_dist(px, py, ax, ay, bx, by) -> float:
    dx, dy = bx - ax, by - ay
    if dx == dy == 0:
        return math.hypot(px - ax, py - ay)
    u = ((px - ax) * dx + (py - ay) * dy) / (dx * dx + dy * dy)
    u = max(0.0, min(1.0, u))
    return math.hypot(px - (ax + u * dx), py - (ay + u * dy))


def st_distance(wkt1: str, wkt2: str) -> float:
    """Planar euclidean distance (gis/st_distance.rs, geo crate
    EuclideanDistance): exact for point↔point / point↔linestring;
    min vertex-to-segment distance otherwise."""
    k1 = str(wkt1).strip().upper() if wkt1 is not None else ""
    k2 = str(wkt2).strip().upper() if wkt2 is not None else ""
    coll = any(k.startswith("GEOMETRYCOLLECTION") for k in (k1, k2))
    multi_pair = (any(k.startswith("MULTI") for k in (k1, k2))
                  and not (k1.startswith("POINT")
                           or k2.startswith("POINT")))
    if coll or multi_pair:
        from ..errors import FunctionError

        # the reference's geo crate EuclideanDistance covers
        # POINT×anything and POINT/LINESTRING/POLYGON pairs; other
        # MULTI*/collection combinations error (st_distance.slt)
        raise FunctionError(
            "st_distance does not support this geometry combination")
    if wkt1 is None or wkt2 is None:
        return None
    from . import gis

    ga, gb = gis.parse_wkt(str(wkt1)), gis.parse_wkt(str(wkt2))
    # touching/crossing/contained geometries are at distance 0 (geo
    # EuclideanDistance; a linestring crossing a polygon interior → 0.0)
    try:
        if gis.st_intersects(str(wkt1), str(wkt2)):
            return 0.0
    except Exception:
        pass
    best = math.inf
    for (pa, gb_) in ((list(gis._points(ga)), gb),
                      (list(gis._points(gb)), ga)):
        segs = list(gis._segments(gb_))
        if not segs:
            segs = [(p, p) for p in gis._points(gb_)]
        for (px, py) in pa:
            for (s1, s2) in segs:
                best = min(best, _seg_point_dist(px, py, *s1, *s2))
    return best


def st_area(wkt: str) -> float:
    """Planar area (gis/st_area.rs, geo unsigned_area): outer rings
    minus holes, multipolygons summed; 0 for points/lines."""
    if wkt is None:
        return None
    from . import gis

    return gis.st_area_geom(gis.parse_wkt(str(wkt)))
