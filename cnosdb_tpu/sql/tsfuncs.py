"""Time-series function families: counter increase, sampling, gauge/state
aggregation, data-quality metrics, data repair, and GIS scalars.

Behavior-parity with the reference's extension functions
(query_server/query/src/extension/expr/):
- increase: aggregate_function/increase.rs:82-107 — counter resets add the
  post-reset value instead of a negative delta;
- sample: aggregate_function/sample.rs — k-reservoir;
- gauge_agg + accessors: aggregate_function/gauge/mod.rs:44-118;
- state_agg / compact_state_agg, duration_in, state_at:
  aggregate_function/state_agg/state_agg_data.rs:89-152;
- completeness/consistency/timeliness/validity:
  aggregate_function/data_quality/common.rs (NaN interpolation, windowed
  timestamp anomaly detection, MAD outlier counting);
- timestamp_repair / value_fill / value_repair:
  ts_gen_func/data_repair/*.rs (median/mode interval reconstruction,
  mean/previous/linear fill, SCREEN speed clamping);
- st_* GIS: scalar_function/gis/ (WKT geometries).

All functions are pure numpy over (time, value) arrays — they run host-side
at aggregate finalize (whole-group context), which is also where the
reference runs them (DataFusion accumulators, not the scan kernel).
"""
from __future__ import annotations

import math
import re

import numpy as np

from ..errors import FunctionError

NS = 1_000_000_000


# ---------------------------------------------------------------------------
# counter increase (exact reset handling)
# ---------------------------------------------------------------------------
def increase(ts: np.ndarray, vals: np.ndarray) -> float | None:
    """Counter increase with reset handling (increase.rs:98-103): a drop
    means the counter restarted, so the post-reset value is the delta.
    Integer inputs stay integer (reference: increase(Int64) renders 7,
    not 7.0)."""
    if len(vals) == 0:
        return None
    integral = all(isinstance(x, (int, np.integer))
                   and not isinstance(x, (bool, np.bool_))
                   for x in np.asarray(vals).tolist())
    v = np.asarray(vals, dtype=np.float64)
    if len(v) == 1:
        return 0 if integral else 0.0
    d = np.diff(v)
    out = float(np.where(d > 0, d, np.where(d < 0, v[1:], 0.0)).sum())
    return int(out) if integral else out


# ---------------------------------------------------------------------------
# sample (k-reservoir)
# ---------------------------------------------------------------------------
def sample(vals: np.ndarray, k: int) -> list:
    """k-reservoir sample (sample.rs). Deterministic seed per call keeps
    query results reproducible across replicas."""
    n = len(vals)
    if k <= 0 or k > 2000:
        # reference bound: sample size in (0, 2000] (sample.slt)
        raise FunctionError("sample size must be in (0, 2000]")

    def plain(x):
        return x.item() if hasattr(x, "item") else x

    if n <= k:
        return [plain(v) for v in vals]
    rng = np.random.default_rng(abs(hash((n, k))) % (2**32))
    idx = np.sort(rng.choice(n, size=k, replace=False))
    return [plain(v) for v in np.asarray(vals)[idx]]


# ---------------------------------------------------------------------------
# gauge_agg
# ---------------------------------------------------------------------------
def gauge_data(ts: np.ndarray, vals: np.ndarray) -> dict | None:
    """GaugeData (gauge/mod.rs): first/second/penultimate/last TSPoints."""
    n = len(ts)
    if n == 0:
        return None
    t = np.asarray(ts, dtype=np.int64)
    v = np.asarray(vals, dtype=np.float64)
    return {
        "kind": "gauge",
        "first": [int(t[0]), float(v[0])],
        "second": [int(t[min(1, n - 1)]), float(v[min(1, n - 1)])],
        "penultimate": [int(t[max(0, n - 2)]), float(v[max(0, n - 2)])],
        "last": [int(t[-1]), float(v[-1])],
        "num_elements": int(n),
    }


def gauge_delta(g: dict) -> float:
    return g["last"][1] - g["first"][1]


def format_interval_ns(ns: int) -> str:
    """Arrow IntervalMonthDayNano rendering: '0 years 0 mons 0 days
    0 hours 0 mins 0.005 secs' (reference renders time_delta this
    way)."""
    neg = ns < 0
    ns = abs(int(ns))
    days, rem = divmod(ns, 86_400_000_000_000)
    hours, rem = divmod(rem, 3_600_000_000_000)
    mins, rem = divmod(rem, 60_000_000_000)
    secs = rem / 1e9
    sign = "-" if neg else ""
    sec_txt = f"{secs:.9f}".rstrip("0").rstrip(".")
    if "." not in sec_txt and not sec_txt:
        sec_txt = "0"
    return (f"{sign}0 years 0 mons {days} days {hours} hours "
            f"{mins} mins {sec_txt} secs")


def gauge_time_delta(g: dict) -> str:
    """Interval between first and last sample, rendered in arrow's
    interval format (gauge/time_delta.rs returns an Interval)."""
    return format_interval_ns(g["last"][0] - g["first"][0])


def _gauge_time_delta_ns(g: dict) -> int:
    return g["last"][0] - g["first"][0]


def gauge_rate(g: dict) -> float | None:
    td = _gauge_time_delta_ns(g)
    if td == 0:
        return None
    return gauge_delta(g) / float(td)


def gauge_idelta_left(g: dict) -> float:
    return g["second"][1] - g["first"][1]


def gauge_idelta_right(g: dict) -> float:
    return g["last"][1] - g["penultimate"][1]


# ---------------------------------------------------------------------------
# state_agg / compact_state_agg
# ---------------------------------------------------------------------------
def state_data(ts: np.ndarray, states: np.ndarray,
               compact: bool = False) -> dict | None:
    """StateAggData (state_agg_data.rs): per-state total duration and, for
    the non-compact form, the [start, end) periods. A state's period runs
    until the NEXT reading's timestamp; the final reading contributes no
    duration (no successor), matching the reference accumulator."""
    n = len(ts)
    if n == 0:
        return None
    t = np.asarray(ts, dtype=np.int64)
    order = np.argsort(t, kind="stable")
    t = t[order]
    s = np.asarray(states)[order]
    durations: dict = {}
    periods: dict = {}
    cur_state = s[0]
    cur_start = int(t[0])
    for i in range(1, n):
        if s[i] != cur_state:
            end = int(t[i])
            durations[cur_state] = durations.get(cur_state, 0) + (end - cur_start)
            if not compact:
                periods.setdefault(cur_state, []).append([cur_start, end])
            cur_state = s[i]
            cur_start = end
    end = int(t[-1])
    if end > cur_start:
        durations[cur_state] = durations.get(cur_state, 0) + (end - cur_start)
        if not compact:
            periods.setdefault(cur_state, []).append([cur_start, end])
    return {"kind": "state", "compact": compact,
            "durations": {str(k): int(v) for k, v in durations.items()},
            "periods": {str(k): v for k, v in periods.items()}}


def duration_in(sa: dict, state, start: int | None = None,
                interval: int | None = None) -> int:
    """Total time in `state` (state_agg_data.rs:89-136), optionally
    restricted to [start, start+interval)."""
    key = str(state)
    if start is None:
        return int(sa["durations"].get(key, 0))
    if sa.get("compact"):
        raise FunctionError("duration_in with a time range needs state_agg "
                            "(not compact_state_agg)")
    periods = sa["periods"].get(key, [])
    total = 0
    end = start + interval if interval is not None else None
    for p_start, p_end in periods:
        if p_end <= start:
            continue
        if end is not None and p_start >= end:
            continue
        lo = max(p_start, start)
        hi = p_end if end is None else min(p_end, end)
        if hi > lo:
            total += hi - lo
    return int(total)


def state_at(sa: dict, ts: int):
    """State whose period covers ts (state_agg_data.rs:138-152)."""
    if sa.get("compact"):
        raise FunctionError("state_at needs state_agg (not compact form)")
    for state, periods in sa["periods"].items():
        for p_start, p_end in periods:
            if p_start <= ts < p_end:
                return state
    return None


# ---------------------------------------------------------------------------
# data-quality metrics (data_quality/common.rs)
# ---------------------------------------------------------------------------
def _dq_median(x: np.ndarray) -> float:
    return float(np.median(x)) if len(x) else 0.0


def _dq_mad(x: np.ndarray) -> float:
    mid = _dq_median(x)
    return 1.4826 * _dq_median(np.abs(x - mid))


def _dq_outliers(x: np.ndarray, k: float = 3.0) -> int:
    if len(x) == 0:
        return 0
    mid = _dq_median(x)
    sigma = _dq_mad(x)
    return int((np.abs(x - mid) > k * sigma).sum())


class _DataQuality:
    """Port of DataSeriesQuality: NaN interpolation then timestamp-window
    and value-outlier counting (common.rs:40-215)."""

    WINDOW = 10

    def __init__(self, ts: np.ndarray, vals: np.ndarray):
        t = np.asarray(ts, dtype=np.float64)
        v = np.asarray(vals, dtype=np.float64).copy()
        self.cnt = len(t)
        bad = ~np.isfinite(v)
        self.specialcnt = int(bad.sum())
        v[bad] = np.nan
        good = np.nonzero(~np.isnan(v))[0]
        if len(good) < 2:
            raise FunctionError("at least two finite values are needed")
        # linear interpolation through NaNs, extrapolating the edges from
        # the first/last pair of good points (common.rs nan_process)
        v = np.interp(t, t[good], v[good])
        i1, i2 = good[0], good[1]
        slope = (v[i2] - v[i1]) / (t[i2] - t[i1]) if t[i2] != t[i1] else 0.0
        head = np.arange(len(t)) < i1
        v[head] = v[i1] + slope * (t[head] - t[i1])
        j1, j2 = good[-2], good[-1]
        slope = (v[j2] - v[j1]) / (t[j2] - t[j1]) if t[j2] != t[j1] else 0.0
        tail = np.arange(len(t)) > j2
        v[tail] = v[j1] + slope * (t[tail] - t[j1])
        self.t, self.v = t, v
        self.misscnt = self.latecnt = self.redundancycnt = 0
        self._time_detect()
        self._value_detect()

    def _time_detect(self):
        t = self.t
        if len(t) < 2:
            return
        base = _dq_median(np.diff(t))
        if base == 0:
            return
        window = list(t[:self.WINDOW])
        i = len(window)
        while len(window) > 1:
            times = (window[1] - window[0]) / base
            if times <= 0.5:
                window.pop(1)
                self.redundancycnt += 1
            elif 2.0 <= times <= 9.0:
                temp = 0
                j = 2
                while j < len(window):
                    times2 = (window[j] - window[j - 1]) / base
                    if times2 >= 2.0:
                        break
                    if times2 <= 0.5:
                        temp += 1
                        window.pop(j)
                        j -= 1
                        if temp == round(times - 1.0):
                            break
                    j += 1
                self.latecnt += temp
                self.misscnt += round(times - 1.0) - temp
            window.pop(0)
            while len(window) < self.WINDOW and i < self.cnt:
                window.append(t[i])
                i += 1

    def _value_detect(self):
        v, t = self.v, self.t
        self.valuecnt = _dq_outliers(v)
        self.variationcnt = _dq_outliers(np.diff(v))
        with np.errstate(invalid="ignore", divide="ignore"):
            speed = np.diff(v) / np.diff(t)
        self.speedcnt = _dq_outliers(speed)
        self.speedchangecnt = _dq_outliers(np.diff(speed))

    def completeness(self) -> float:
        return 1.0 - (self.misscnt + self.specialcnt) / (self.cnt + self.misscnt)

    def consistency(self) -> float:
        return 1.0 - self.redundancycnt / self.cnt

    def timeliness(self) -> float:
        return 1.0 - self.latecnt / self.cnt

    def validity(self) -> float:
        return 1.0 - 0.25 * (self.valuecnt + self.variationcnt
                             + self.speedcnt + self.speedchangecnt) / self.cnt


def data_quality(metric: str, ts: np.ndarray, vals: np.ndarray) -> float:
    dq = _DataQuality(ts, vals)
    return getattr(dq, metric)()


# ---------------------------------------------------------------------------
# data repair (ts_gen_func/data_repair/)
# ---------------------------------------------------------------------------
def _interval_estimate(ts: np.ndarray, method: str = "median",
                       interval: int | None = None) -> int:
    if interval is not None:
        return int(interval)
    d = np.diff(ts)
    if len(d) == 0:
        return 1
    if method == "mode":
        u, c = np.unique(d, return_counts=True)
        return int(u[np.argmax(c)])
    return int(np.median(d))


def timestamp_repair(ts: np.ndarray, vals: np.ndarray,
                     method: str = "median",
                     interval: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Rebuild an even timestamp grid (timestamp_repair.rs): estimate the
    sampling interval (median/mode of diffs or explicit), regenerate
    start..end on that grid, and map each original reading to its nearest
    slot (first writer wins); empty slots fill by linear interpolation."""
    t = np.asarray(ts, dtype=np.int64)
    v = np.asarray(vals, dtype=np.float64)
    if len(t) == 0:
        return t, v
    order = np.argsort(t, kind="stable")
    t, v = t[order], v[order]
    step = max(1, _interval_estimate(t, method, interval))
    start, end = int(t[0]), int(t[-1])
    n_slots = (end - start) // step + 1
    grid = start + step * np.arange(n_slots, dtype=np.int64)
    slot = np.clip(np.round((t - start) / step).astype(np.int64), 0,
                   n_slots - 1)
    filled = np.full(n_slots, np.nan)
    for i in range(len(t) - 1, -1, -1):   # first writer wins
        filled[slot[i]] = v[i]
    missing = np.isnan(filled)
    if missing.any() and (~missing).any():
        good = np.nonzero(~missing)[0]
        filled = np.interp(np.arange(n_slots), good, filled[good])
    return grid, filled


def value_fill(ts: np.ndarray, vals: np.ndarray,
               method: str = "linear") -> np.ndarray:
    """Fill NaN values (value_fill.rs): mean / previous / linear."""
    t = np.asarray(ts, dtype=np.float64)
    v = np.asarray(vals, dtype=np.float64).copy()
    bad = np.isnan(v)
    if not bad.any():
        return v
    good = np.nonzero(~bad)[0]
    if len(good) == 0:
        return v
    method = method.lower()
    if method == "mean":
        v[bad] = v[good].mean()
    elif method == "previous":
        idx = np.maximum.accumulate(
            np.where(~bad, np.arange(len(v)), -1))
        has_prev = idx >= 0
        v[bad & has_prev] = v[idx[bad & has_prev]]
    elif method == "linear":
        v[bad] = np.interp(t[bad], t[good], v[good])
    else:
        raise FunctionError(f"unsupported fill method {method!r} "
                            "(mean|previous|linear)")
    return v


def value_repair(ts: np.ndarray, vals: np.ndarray,
                 min_speed: float | None = None,
                 max_speed: float | None = None) -> np.ndarray:
    """SCREEN repair (value_repair.rs screen method): clamp each step's
    rate of change into [smin, smax]; default bounds = median speed ±
    3·MAD (the reference's auto-threshold)."""
    t = np.asarray(ts, dtype=np.float64)
    v = np.asarray(vals, dtype=np.float64).copy()
    if len(v) < 2:
        return v
    with np.errstate(invalid="ignore", divide="ignore"):
        speed = np.diff(v) / np.diff(t)
    if min_speed is None or max_speed is None:
        mid = _dq_median(speed)
        sigma = _dq_mad(speed)
        if min_speed is None:
            min_speed = mid - 3 * sigma
        if max_speed is None:
            max_speed = mid + 3 * sigma
    for i in range(1, len(v)):
        dt = t[i] - t[i - 1]
        lo = v[i - 1] + min_speed * dt
        hi = v[i - 1] + max_speed * dt
        if v[i] < lo:
            v[i] = lo
        elif v[i] > hi:
            v[i] = hi
    return v


# ---------------------------------------------------------------------------
# GIS (scalar_function/gis/ — WKT geometries)
# ---------------------------------------------------------------------------
_WKT_NUM = r"[-+]?\d*\.?\d+(?:[eE][-+]?\d+)?"


def _parse_wkt(wkt: str):
    """→ (type, list of (x, y)) — flattened points of any WKT geometry
    (full grammar incl. EMPTY and multi types via sql.gis)."""
    if wkt is None:
        return None
    from . import gis

    try:
        g = gis.parse_wkt(str(wkt))
    except Exception:
        return _parse_wkt_legacy(wkt)
    return (g.kind, list(gis._points(g)))


def _parse_wkt_legacy(wkt: str):
    if wkt is None:
        return None
    m = re.match(r"\s*(POINT|LINESTRING|POLYGON)\s*\((.*)\)\s*$",
                 str(wkt).strip(), re.IGNORECASE)
    if not m:
        raise FunctionError(f"bad WKT geometry: {wkt!r}")
    gtype = m.group(1).upper()
    body = m.group(2)
    if gtype == "POLYGON":
        ring = re.match(r"\s*\((.*?)\)", body)
        if not ring:
            raise FunctionError(f"bad WKT polygon: {wkt!r}")
        body = ring.group(1)
    pts = []
    for pair in body.split(","):
        nums = re.findall(_WKT_NUM, pair)
        if len(nums) < 2:
            raise FunctionError(f"bad WKT coordinates: {pair!r}")
        pts.append((float(nums[0]), float(nums[1])))
    return gtype, pts


def _seg_point_dist(px, py, ax, ay, bx, by) -> float:
    dx, dy = bx - ax, by - ay
    if dx == dy == 0:
        return math.hypot(px - ax, py - ay)
    u = ((px - ax) * dx + (py - ay) * dy) / (dx * dx + dy * dy)
    u = max(0.0, min(1.0, u))
    return math.hypot(px - (ax + u * dx), py - (ay + u * dy))


def st_distance(wkt1: str, wkt2: str) -> float:
    """Planar euclidean distance (gis/st_distance.rs, geo crate
    EuclideanDistance): exact for point↔point / point↔linestring;
    min vertex-to-segment distance otherwise."""
    k1 = str(wkt1).strip().upper() if wkt1 is not None else ""
    k2 = str(wkt2).strip().upper() if wkt2 is not None else ""
    coll = any(k.startswith("GEOMETRYCOLLECTION") for k in (k1, k2))
    multi_pair = (any(k.startswith("MULTI") for k in (k1, k2))
                  and not (k1.startswith("POINT")
                           or k2.startswith("POINT")))
    if coll or multi_pair:
        from ..errors import FunctionError

        # the reference's geo crate EuclideanDistance covers
        # POINT×anything and POINT/LINESTRING/POLYGON pairs; other
        # MULTI*/collection combinations error (st_distance.slt)
        raise FunctionError(
            "st_distance does not support this geometry combination")
    if wkt1 is None or wkt2 is None:
        return None
    from . import gis

    ga, gb = gis.parse_wkt(str(wkt1)), gis.parse_wkt(str(wkt2))
    # touching/crossing/contained geometries are at distance 0 (geo
    # EuclideanDistance; a linestring crossing a polygon interior → 0.0)
    try:
        if gis.st_intersects(str(wkt1), str(wkt2)):
            return 0.0
    except Exception:
        pass
    best = math.inf
    for (pa, gb_) in ((list(gis._points(ga)), gb),
                      (list(gis._points(gb)), ga)):
        segs = list(gis._segments(gb_))
        if not segs:
            segs = [(p, p) for p in gis._points(gb_)]
        for (px, py) in pa:
            for (s1, s2) in segs:
                best = min(best, _seg_point_dist(px, py, *s1, *s2))
    return best


def st_area(wkt: str) -> float:
    """Planar area (gis/st_area.rs, geo unsigned_area): outer rings
    minus holes, multipolygons summed; 0 for points/lines."""
    if wkt is None:
        return None
    from . import gis

    return gis.st_area_geom(gis.parse_wkt(str(wkt)))
