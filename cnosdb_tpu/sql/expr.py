"""Expression IR.

The SQL layer plans WHERE/SELECT expressions into this tree; it evaluates
under EITHER numpy (host pre-filtering, string columns) or jax.numpy
(device filtering inside the fused scan kernel) via the `xp` module
parameter — one IR, two execution targets, no translation layer. Mirrors
the role of DataFusion's PhysicalExpr in the reference's scan filter
(tskv/src/reader/filter.rs) and domain extraction
(common/models/src/predicate/domain.rs push_down_filter).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from ..errors import PlanError
from ..models.predicate import (
    AllDomain, ColumnDomains, LikeDomain, NoneDomain, RangeDomain, SetDomain,
)
from ..models.strcol import DictArray


class Expr:
    def eval(self, env: dict, xp) -> Any:
        raise NotImplementedError

    def columns(self) -> set[str]:
        return set()

    def __repr__(self):
        return self.to_sql()

    def to_sql(self) -> str:
        raise NotImplementedError


@dataclass(repr=False)
class Column(Expr):
    name: str

    def eval(self, env, xp):
        if self.name not in env:
            # struct field access: `col.field` over a composite-valued
            # column (gauge/state/window dicts — reference struct columns
            # support dotted access, e.g. state.state_duration,
            # window.start)
            if "." in self.name:
                base, _, fld = self.name.rpartition(".")
                if base in env:
                    vals = env[base]
                    rows = vals if isinstance(vals, np.ndarray) \
                        and vals.dtype == object else None
                    if rows is not None and any(
                            isinstance(r, dict) for r in rows):
                        out = np.empty(len(rows), dtype=object)
                        for i, r in enumerate(rows):
                            out[i] = r.get(fld) if isinstance(r, dict) \
                                else None
                        return out
                    if isinstance(vals, dict):
                        return vals.get(fld)
            raise PlanError(f"unknown column {self.name!r}")
        return env[self.name]

    def columns(self):
        return {self.name}

    def to_sql(self):
        return self.name


@dataclass(repr=False)
class Literal(Expr):
    value: Any

    def eval(self, env, xp):
        return self.value

    def to_sql(self):
        if isinstance(self.value, str):
            return "'" + self.value.replace("'", "''") + "'"
        if self.value is None:
            return "NULL"
        if isinstance(self.value, bool):
            return "TRUE" if self.value else "FALSE"
        return str(self.value)


_BIN_OPS = {
    "+": lambda xp, a, b: a + b,
    "-": lambda xp, a, b: a - b,
    "*": lambda xp, a, b: a * b,
    "/": lambda xp, a, b: _div(xp, a, b),
    # SQL % is the REMAINDER (sign of the dividend, like DataFusion/C),
    # not python/numpy floor-mod: -7 % 3 = -1
    "%": lambda xp, a, b: xp.fmod(a, b),
    "=": lambda xp, a, b: _eq(xp, a, b),
    "!=": lambda xp, a, b: ~_eq(xp, a, b),
    "<": lambda xp, a, b: a < b,
    "<=": lambda xp, a, b: a <= b,
    ">": lambda xp, a, b: a > b,
    ">=": lambda xp, a, b: a >= b,
    "and": lambda xp, a, b: a & b,
    "or": lambda xp, a, b: a | b,
    # bitwise XOR over integers (DataFusion's ^)
    "^": lambda xp, a, b: _bit_xor(xp, a, b),
}


def _bit_xor(xp, a, b):
    def as_int(x):
        if isinstance(x, np.ndarray):
            if x.dtype.kind not in "iu":
                raise PlanError("^ takes integer operands")
            return x.astype(np.int64)
        if isinstance(x, (bool, np.bool_)) \
                or not isinstance(x, (int, np.integer)):
            raise PlanError("^ takes integer operands")
        return int(x)
    return as_int(a) ^ as_int(b)


def _math_float(xp, v):
    """Numeric math-function results promote to Float64 (DataFusion
    semantics the reference inherits); NULL-bearing object arrays map
    elementwise, NULLs preserved."""
    if isinstance(v, np.ndarray):
        if v.dtype == object:
            o = np.empty(len(v), dtype=object)
            o[:] = [None if x is None else float(x) for x in v]
            return o
        if v.dtype.kind in "iub":
            return v.astype(np.float64)
        return v
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        return float(v)
    return v


_LIBM_FNS = None


def _libm_f32(name):
    """glibc float math via ctypes: the reference's Float32 math path
    (DataFusion coerces Int64→Float32 for log/atan2, computed with
    Rust/libm log10f/atan2f whose results differ from numpy's by an
    ulp — math_function/log.slt pins 0.30102998, glibc's log10f(2))."""
    global _LIBM_FNS
    if _LIBM_FNS is None:
        import ctypes

        lib = ctypes.CDLL("libm.so.6")
        _LIBM_FNS = {}
        for n, arity in (("log10f", 1), ("atan2f", 2), ("logf", 1)):
            fn = getattr(lib, n)
            fn.restype = ctypes.c_float
            fn.argtypes = [ctypes.c_float] * arity
            _LIBM_FNS[n] = fn
    return _LIBM_FNS[name]


def _all_int(*vs):
    for v in vs:
        if isinstance(v, np.ndarray):
            if v.dtype.kind not in "iub":
                return False
        elif isinstance(v, bool) or not isinstance(v, (int, np.integer)):
            return False
    return True


def _f32_lift(cname, *vs):
    """Elementwise glibc f32 evaluation; returns float32 array/scalar."""
    fn = _libm_f32(cname)
    if any(isinstance(v, np.ndarray) for v in vs):
        n = next(len(v) for v in vs if isinstance(v, np.ndarray))
        cols = [v if isinstance(v, np.ndarray) else [v] * n for v in vs]
        return np.array([fn(*(float(x) for x in row))
                         for row in zip(*cols)], dtype=np.float32)
    return np.float32(fn(*(float(v) for v in vs)))


def _f32_log10(xp, a):
    """DataFusion's Float32 log10: ln(x)/ln(10) evaluated in f32 —
    one ulp below glibc's log10f at 2.0 (log.slt pins 0.30102998)."""
    a32 = (a.astype(np.float32) if isinstance(a, np.ndarray)
           else np.float32(a))
    with np.errstate(divide="ignore", invalid="ignore"):
        return (xp.log(a32) / xp.log(np.float32(10.0))).astype(np.float32)


def _rust_atanh(xp, a):
    """Rust std's atanh: 0.5 * ln_1p(2x/(1-x)) — bit-different from
    numpy's arctanh (math_function/atanh.slt pins the last ulp)."""
    a = _math_float(xp, a)
    if isinstance(a, np.ndarray) and a.dtype == object:
        o = np.empty(len(a), dtype=object)
        o[:] = [None if x is None else _rust_atanh(xp, x) for x in a]
        return o
    if not isinstance(a, np.ndarray):
        a = np.float64(a)
    with np.errstate(divide="ignore", invalid="ignore"):
        return 0.5 * xp.log1p(2.0 * a / (1.0 - a))


def _div(xp, a, b):
    # SQL division: integer/integer stays integral in CnosDB? DataFusion
    # yields float for `/` on floats, TRUNC-div on ints (toward zero —
    # numpy's // floors, so -7/2 would wrongly give -4). Follow DataFusion.
    a_int = _is_int(a) and _is_int(b)
    if a_int:
        safe_b = xp.where(b == 0, 1, b)
        qf = a // safe_b
        rem = a - qf * safe_b
        q = qf + ((rem != 0) & ((a < 0) != (b < 0)))
        zero = b == 0
        if xp is np and bool(np.any(zero)):
            # integer x/0 is NULL (arrow divide_opt — sqlancer pins the
            # 0/0 row surviving through IS NULL)
            if np.isscalar(q) or getattr(q, "shape", None) == ():
                return None
            out = np.asarray(q).astype(object)
            out[np.asarray(zero)] = None
            return out
        return xp.where(b != 0, q, 0)
    if xp is np:
        # IEEE semantics for scalar constants too (1.0/0 → inf, 0.0/0 →
        # nan — same as the column path), and no warning spam in logs
        with np.errstate(divide="ignore", invalid="ignore"):
            if np.isscalar(a) and np.isscalar(b):
                return float(np.float64(a) / np.float64(b))
            return a / b
    return a / b


def _is_int(v):
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        return True
    dt = getattr(v, "dtype", None)
    return dt is not None and np.issubdtype(dt, np.integer)


def _eq(xp, a, b):
    return a == b


def _is_obj_arr(v) -> bool:
    return isinstance(v, np.ndarray) and v.dtype == object


def _mask_operand_validity(out, env, *exprs):
    """3VL at the comparison LEAF: a predicate over a NULL operand is
    UNKNOWN → False as a filter. Masking here (instead of post-hoc over
    the whole filter) keeps disjunctions correct: in
    `a IS NULL OR b = 0`, a NULL-b row can still match through the left
    branch. Typed columns carry NULLs out-of-band as __valid__ masks."""
    if not isinstance(out, np.ndarray) or out.dtype != bool:
        return out
    masked = out
    for e in exprs:
        for c in e.columns():
            vm = env.get(f"__valid__:{c}")
            if vm is not None and len(vm) == len(out) and not vm.all():
                if masked is out:
                    masked = out.copy()
                masked &= vm
    return masked


def _obj_binop(op: str, f, xp, a, b):
    """NULL-propagating elementwise op when an operand is an OBJECT array
    (NULL-bearing int columns ride as objects to keep integer identity):
    arithmetic yields NULL where any operand is NULL; comparisons yield
    FALSE there (3VL as a filter)."""
    n = next((len(x) for x in (a, b)
              if isinstance(x, (np.ndarray, DictArray))), 1)

    def clean(v):
        if isinstance(v, DictArray):
            return v.materialize(), np.zeros(n, dtype=bool)
        if not _is_obj_arr(v):
            return v, np.zeros(n, dtype=bool)
        nulls = np.array([x is None for x in v], dtype=bool)
        vals = [0 if x is None else x for x in v]
        # int64 only when every value IS an integer — np.array(...,
        # dtype=int64) silently truncates floats (1.5 → 1)
        if all(isinstance(x, (int, np.integer))
               and not isinstance(x, (bool, np.bool_)) for x in vals):
            try:
                return np.array(vals, dtype=np.int64), nulls
            except (TypeError, ValueError, OverflowError):
                pass
        if all(isinstance(x, (int, float, np.integer, np.floating))
               and not isinstance(x, (bool, np.bool_)) for x in vals):
            try:
                return np.array(vals, dtype=np.float64), nulls
            except (TypeError, ValueError, OverflowError):
                pass
        # strings etc: operate on objects, with NULL slots filled so
        # elementwise comparisons don't hit None >= None TypeErrors
        # (the nulls mask zeroes those lanes afterwards). Numeric
        # STRINGS must stay strings — '12' < '5' lexicographically.
        if all(isinstance(x, str) for x, isn in zip(v, nulls)
               if not isn):
            filled = np.array(["" if x is None else x for x in v],
                              dtype=object)
            return filled, nulls
        return v, nulls

    aa, an = clean(a)
    bb, bn = clean(b)
    nulls = an | bn
    try:
        out = f(xp, aa, bb)
    except TypeError:
        if op not in ("=", "!=", "<", "<=", ">", ">="):
            raise
        # genuinely mixed object operands (fuzzer-built expressions):
        # compare same-type pairs row-wise; cross-type pairs don't match
        def rows(x):
            if isinstance(x, np.ndarray):
                return list(x)
            return [x] * n

        ra, rb = rows(aa), rows(bb)
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            x, y = ra[i], rb[i]
            if x is None or y is None:
                continue
            try:
                out[i] = bool(f(xp, x, y))
            except TypeError:
                sx = x if isinstance(x, str) else _str_coerce(x)
                sy = y if isinstance(y, str) else _str_coerce(y)
                out[i] = bool(f(xp, sx, sy))
    if op in ("=", "!=", "<", "<=", ">", ">=", "and", "or"):
        out = np.asarray(out, dtype=bool)
        if nulls.any():
            out = out & ~nulls
        return out
    if nulls.any():
        out = np.asarray(out).astype(object)
        out[nulls] = None
    return out


@dataclass(repr=False)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def eval(self, env, xp):
        f = _BIN_OPS.get(self.op)
        if f is None:
            raise PlanError(f"unknown operator {self.op!r}")
        if xp is np and self.op in ("=", "!=", "<", "<=", ">", ">=") \
                and "__unique_eval__" not in env:
            out = self._per_unique_cmp(env)
            if out is not None:
                return out
        a = self.left.eval(env, xp)
        b = self.right.eval(env, xp)
        if xp is np and (_is_obj_arr(a) or _is_obj_arr(b)):
            return _obj_binop(self.op, f, xp, a, b)
        if a is None or b is None:
            # SQL three-valued logic: NULL compares unknown (false as a
            # filter, e.g. an empty scalar subquery); NULL arithmetic is
            # NULL
            if self.op in ("=", "!=", "<", "<=", ">", ">="):
                other = b if a is None else a
                shape = getattr(other, "shape", None)
                if shape:
                    return xp.zeros(shape, dtype=bool)
                return False
            return None
        if self.op in ("=", "!=", "<", "<=", ">", ">=") and xp is np:
            # timestamp-column vs date/timestamp-string comparison:
            # coerce the literal to i64 ns (DataFusion's implicit
            # Utf8→Timestamp coercion; tpch.slt compares CSV-inferred
            # timestamp columns against DATE literals)
            a, b = _coerce_ts_cmp(a, b)
        if self.op in ("+", "-", "*", "/", "%"):
            # arithmetic over BOOLEAN is a type error (DataFusion:
            # 'SELECT 3 + TRUE' cannot coerce — example/world.slt)
            for side in (a, b):
                if isinstance(side, (bool, np.bool_)) or (
                        isinstance(side, np.ndarray)
                        and side.dtype == bool):
                    raise PlanError(
                        f"cannot apply {self.op!r} to a BOOLEAN operand")
        if self.op in ("+", "-"):
            iv = b if _is_interval(b) else (a if _is_interval(a) else None)
            if iv is not None and not (_is_interval(a) and _is_interval(b)):
                other = a if iv is b else b
                if not (iv is a and self.op == "-"):   # interval - ts: no
                    return _ts_interval_arith(other, iv, self.op)
        out = f(xp, a, b)
        if xp is np and self.op in ("=", "!=", "<", "<=", ">", ">="):
            out = _mask_operand_validity(out, env, self.left, self.right)
        return out

    def _per_unique_cmp(self, env):
        """substr-equality lane (ops/strkernels): a comparison whose only
        column is a DictArray reached through pure string funcs evaluates
        once per UNIQUE — the same tree runs against a one-row-per-unique
        surrogate env (host semantics by construction, `__unique_eval__`
        stops recursion) and the bool mask gathers through the codes.
        Returns None for any shape outside the lane (caller books nothing:
        the row path itself is not a string-plane fallback for e.g.
        numeric cmps)."""
        if not (isinstance(self.left, Func) or isinstance(self.right, Func)):
            return None   # bare col-vs-literal is already per-unique
        if not (_unique_safe(self.left) and _unique_safe(self.right)):
            return None
        cols = self.columns()
        if len(cols) != 1:
            return None
        (cname,) = cols
        try:
            da = env.get(cname)
        except AttributeError:
            return None
        if not isinstance(da, DictArray) or not len(da.values):
            return None
        from ..ops import strkernels

        if not strkernels.enabled():
            strkernels.note_path("host_fallback", "lane_disabled")
            return None
        senv = {cname: strkernels.unique_surrogate(da),
                "__unique_eval__": True}
        try:
            um = self.eval(senv, np)
        except Exception:
            return None
        if not (isinstance(um, np.ndarray) and um.dtype == bool
                and um.shape == (len(da.values),)):
            return None
        strkernels.note_path("per_unique", "cmp")
        out = strkernels.broadcast_codes(um, da.codes)
        return _mask_operand_validity(out, env, self.left, self.right)

    def columns(self):
        return self.left.columns() | self.right.columns()

    def to_sql(self):
        op = self.op.upper() if self.op in ("and", "or") else self.op
        return f"({self.left.to_sql()} {op} {self.right.to_sql()})"


_UNIQUE_SAFE_FUNCS = frozenset({
    # pure value→value string scalars: per-unique evaluation is exact
    "substr", "substring", "lower", "upper", "trim", "ltrim", "rtrim",
    "btrim", "reverse", "replace", "left", "right", "repeat", "length",
    "char_length", "character_length", "octet_length", "bit_length",
    "concat", "translate", "lpad", "rpad", "split_part", "strpos",
    "position", "starts_with", "ends_with", "initcap", "md5", "ascii",
    "chr", "to_hex",
})


def _unique_safe(e) -> bool:
    """True when `e` is a pure scalar tree (columns, literals, whitelisted
    string funcs) whose value depends only on the row's own value — the
    admission test for BinOp's per-unique surrogate lane."""
    if isinstance(e, Column):
        return True
    if isinstance(e, Literal):
        return not isinstance(e.value, Expr)
    if isinstance(e, Func):
        return (e.name.lower() in _UNIQUE_SAFE_FUNCS
                and e.agg_order is None
                and all(isinstance(a, Expr) and _unique_safe(a)
                        for a in e.args))
    return False


def _is_interval(v) -> bool:
    return hasattr(v, "ns") and hasattr(v, "months")


def _add_months_ns(ts_ns: int, months: int) -> int:
    """Calendar month addition on an ns timestamp (day clamps to the
    target month's end — arrow IntervalMonthDayNano semantics)."""
    import calendar
    from datetime import datetime, timezone

    secs, frac = divmod(int(ts_ns), 1_000_000_000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    total = dt.year * 12 + (dt.month - 1) + months
    y, m = divmod(total, 12)
    day = min(dt.day, calendar.monthrange(y, m + 1)[1])
    out = dt.replace(year=y, month=m + 1, day=day)
    return int(out.timestamp()) * 1_000_000_000 + frac


def _ts_interval_arith(other, iv, op: str):
    """timestamp ± INTERVAL: calendar-true months plus the fixed ns
    remainder (tpch date '1993-07-01' + interval '3' month)."""
    sign = 1 if op == "+" else -1
    months = sign * iv.months
    ns = sign * (iv.sub_ns if iv.sub_ns is not None
                 and iv.months else iv.ns)

    def one(x):
        if x is None:
            return None
        if isinstance(x, str):
            from .parser import parse_timestamp_string

            x = parse_timestamp_string(x)
        x = int(x)
        if months:
            x = _add_months_ns(x, months)
        return x + ns

    if isinstance(other, np.ndarray):
        if other.dtype.kind in "iu" and not months:
            return other.astype(np.int64) + ns
        out = np.empty(len(other), dtype=object)
        for i, v in enumerate(other):
            out[i] = one(None if v is None else
                         (v.item() if hasattr(v, "item") else v))
        if all(o is not None for o in out):
            return out.astype(np.int64)
        return out
    return one(other.item() if hasattr(other, "item") else other)


def _coerce_ts_cmp(a, b):
    """If one side is an integer array and the other a date-looking
    string, parse the string to i64 ns (only strings containing '-' or
    ':' qualify — bare numeric strings keep erroring like DataFusion's
    Int64-vs-Utf8)."""
    def datey(s):
        return isinstance(s, str) and ("-" in s[1:] or ":" in s)

    def ints(x):
        return isinstance(x, np.ndarray) and x.dtype.kind in "iu"

    try:
        if ints(a) and datey(b):
            from .parser import parse_timestamp_string

            return a, int(parse_timestamp_string(b))
        if ints(b) and datey(a):
            from .parser import parse_timestamp_string

            return int(parse_timestamp_string(a)), b
    except Exception:
        pass
    return a, b


def _eval_false_mask(e, env, xp):
    """Definite-FALSE mask under 3VL, or None when not derivable.

    Filter evaluation produces definite-TRUE masks (comparison leaves are
    validity-masked). NOT needs the definite-FALSE mask of its operand —
    `NOT (i = 5 OR i < 0)` must exclude NULL-i rows (inner UNKNOWN →
    NOT UNKNOWN = UNKNOWN), which ~true_mask would wrongly include."""
    if isinstance(e, BinOp):
        if e.op == "and":
            fa = _eval_false_mask(e.left, env, xp)
            fb = _eval_false_mask(e.right, env, xp)
            return None if fa is None or fb is None else (fa | fb)
        if e.op == "or":
            fa = _eval_false_mask(e.left, env, xp)
            fb = _eval_false_mask(e.right, env, xp)
            return None if fa is None or fb is None else (fa & fb)
        neg = {"=": "!=", "!=": "=", "<": ">=", "<=": ">",
               ">": "<=", ">=": "<"}.get(e.op)
        if neg is not None:
            # the negated comparison, leaf-masked: exactly definite-false
            return np.asarray(BinOp(neg, e.left, e.right).eval(env, xp),
                              dtype=bool)
        return None
    if isinstance(e, UnaryOp) and e.op == "not":
        v = e.operand.eval(env, xp)   # definite-true of the operand
        return np.asarray(v, dtype=bool) if isinstance(v, np.ndarray) \
            else None
    if isinstance(e, IsNull):
        return np.asarray(IsNull(e.expr, not e.negated).eval(env, xp),
                          dtype=bool)
    if isinstance(e, Between):
        return np.asarray(
            Between(e.expr, e.low, e.high, not e.negated).eval(env, xp),
            dtype=bool)
    if isinstance(e, InList):
        return np.asarray(
            InList(e.expr, e.values, not e.negated,
                   e.null_present).eval(env, xp), dtype=bool)
    if isinstance(e, Like):
        return np.asarray(
            Like(e.expr, e.pattern, not e.negated).eval(env, xp),
            dtype=bool)
    if isinstance(e, Column):
        v = e.eval(env, xp)
        if not isinstance(v, np.ndarray):
            return None
        out = ~np.asarray(v, dtype=bool)
        return _mask_operand_validity(out, env, e)
    if isinstance(e, Literal):
        return None if e.value is None else (not bool(e.value))
    return None


@dataclass(repr=False)
class UnaryOp(Expr):
    op: str  # 'not' | '-'
    operand: Expr

    def eval(self, env, xp):
        if self.op == "not":
            if xp is np:
                fm = _eval_false_mask(self.operand, env, xp)
                if isinstance(fm, np.ndarray):
                    return fm
            v = self.operand.eval(env, xp)
            if v is None:
                return None   # NOT NULL is NULL
            if isinstance(v, (bool, np.bool_)):
                return not v   # ~True is -2 (bitwise), not False
            if isinstance(v, np.ndarray) and v.dtype == object:
                out = np.empty(len(v), dtype=object)
                out[:] = [None if x is None else (not bool(x)) for x in v]
                return out
            return ~v
        v = self.operand.eval(env, xp)
        if self.op == "-":
            if v is None:
                return None
            return -v
        raise PlanError(f"unknown unary {self.op!r}")

    def columns(self):
        return self.operand.columns()

    def to_sql(self):
        return f"({'NOT ' if self.op == 'not' else '-'}{self.operand.to_sql()})"


@dataclass(repr=False)
class InList(Expr):
    expr: Expr
    values: list
    negated: bool = False
    # a NULL among the comparison values (e.g. from an IN-subquery): per
    # SQL three-valued logic it can never satisfy IN, and it makes NOT IN
    # unknown (hence false as a filter) for EVERY row
    null_present: bool = False

    def eval(self, env, xp):
        v = self.expr.eval(env, xp)
        if self.negated and self.null_present:
            return xp.zeros(getattr(v, "shape", (1,)), dtype=bool)
        m = self._isin_fast(v, xp)
        if m is None:
            for lit in self.values:
                c = _eq(xp, v, lit)
                m = c if m is None else (m | c)
        if m is None:
            m = xp.zeros(getattr(v, "shape", (1,)), dtype=bool)
        if self.negated:
            # python-bool scalars: `~True` is the INT -2, not False
            out = (not m) if isinstance(m, (bool, np.bool_)) else ~m
        else:
            out = m
        if xp is np:
            out = _mask_operand_validity(out, env, self.expr)
        return out

    def _isin_fast(self, v, xp):
        """np.isin for long homogeneous lists (decorrelated EXISTS can
        carry thousands of keys; one vectorized pass per VALUE would be
        O(list) column scans). None → per-literal fallback."""
        if xp is not np or len(self.values) < 9:
            return None
        if not isinstance(v, np.ndarray) or v.dtype == object:
            return None
        vals = self.values

        def plain_num(x):
            return isinstance(x, (int, float, np.integer, np.floating)) \
                and not isinstance(x, (bool, np.bool_))

        if np.issubdtype(v.dtype, np.integer):
            # int column vs float keys would compare through float64 and
            # alias above 2^53 — keep the exact per-literal path there
            if all(isinstance(x, (int, np.integer))
                   and not isinstance(x, (bool, np.bool_)) for x in vals):
                try:
                    return np.isin(v, np.asarray(vals, dtype=np.int64))
                except OverflowError:
                    return None
            return None
        if np.issubdtype(v.dtype, np.floating) and all(
                plain_num(x) for x in vals):
            return np.isin(v, np.asarray([float(x) for x in vals]))
        if v.dtype.kind == "U" and all(isinstance(x, str) for x in vals):
            return np.isin(v, np.asarray(vals))
        return None

    def columns(self):
        return self.expr.columns()

    def to_sql(self):
        vals = ", ".join(Literal(v).to_sql() for v in self.values)
        neg = " NOT" if self.negated else ""
        return f"({self.expr.to_sql()}{neg} IN ({vals}))"


@dataclass(repr=False)
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False

    def eval(self, env, xp):
        v = self.expr.eval(env, xp)
        lo = self.low.eval(env, xp)
        hi = self.high.eval(env, xp)
        if xp is np:
            v2, lo = _coerce_ts_cmp(v, lo)
            v2, hi = _coerce_ts_cmp(v, hi)
            v = v2
        if xp is np and any(
                _is_obj_arr(x) or isinstance(x, DictArray)
                for x in (v, lo, hi)):
            # NULL-bearing object operands (lower(NULL) etc) go through
            # the 3VL comparison path — raw >= would TypeError on None
            m = (_obj_binop(">=", _BIN_OPS[">="], xp, v, lo)
                 & _obj_binop("<=", _BIN_OPS["<="], xp, v, hi))
        else:
            m = (v >= lo) & (v <= hi)
        out = ~m if self.negated else m
        if xp is np:
            out = _mask_operand_validity(out, env, self.expr,
                                         self.low, self.high)
        return out

    def columns(self):
        return self.expr.columns() | self.low.columns() | self.high.columns()

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        return f"({self.expr.to_sql()}{neg} BETWEEN {self.low.to_sql()} AND {self.high.to_sql()})"


@dataclass(repr=False)
class IsNull(Expr):
    expr: Expr
    negated: bool = False

    def eval(self, env, xp):
        # validity masks ride in env under '__valid__:<col>'
        cols = self.expr.columns()
        if len(cols) == 1 and isinstance(self.expr, Column):
            key = f"__valid__:{next(iter(cols))}"
            if key in env:
                valid = env[key]
                return valid if self.negated else ~valid
        v = self.expr.eval(env, xp)
        if v is None:   # NULL literal / NULL-valued scalar expression
            return np.array([not self.negated])
        dt = getattr(v, "dtype", None)
        if dt is not None and dt.kind == "f":
            m = xp.isnan(v)
        elif dt is not None and dt == object:
            # join-filled columns ride as object arrays with None holes
            m = np.array([x is None or (isinstance(x, float) and x != x)
                          for x in v], dtype=bool)
        else:
            m = xp.zeros(getattr(v, "shape", (1,)), dtype=bool)
        if not isinstance(self.expr, (Column, Literal)):
            # composite expression: a NULL in any null-propagating input
            # makes the result NULL (SQL 3VL — `(NOT (x = t0)) IS NULL`
            # is TRUE on NULL-t0 rows even though the bool eval says
            # False); NULL-defining nodes (CASE/IS NULL) are excluded by
            # propagating_columns
            for c in propagating_columns(self.expr):
                nm = _column_null_mask(c, env, xp)
                if nm is not None:
                    m = m | nm
        return ~m if self.negated else m

    def columns(self):
        return self.expr.columns()

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        return f"({self.expr.to_sql()} IS{neg} NULL)"


@dataclass(repr=False)
class Like(Expr):
    """SQL LIKE with % and _ wildcards (host-evaluated; string columns
    never ride to the device anyway)."""

    expr: Expr
    pattern: str
    negated: bool = False

    def _regex(self):
        rx = getattr(self, "_rx", None)
        if rx is None:
            import re as _re

            out = []
            for ch in self.pattern:
                if ch == "%":
                    out.append(".*")
                elif ch == "_":
                    out.append(".")
                else:
                    out.append(_re.escape(ch))
            rx = _re.compile("^" + "".join(out) + "$", _re.DOTALL)
            object.__setattr__(self, "_rx", rx)
        return rx

    @staticmethod
    def _compile(pattern: str):
        import re as _re

        out = []
        for ch in pattern:
            if ch == "%":
                out.append(".*")
            elif ch == "_":
                out.append(".")
            else:
                out.append(_re.escape(ch))
        return _re.compile("^" + "".join(out) + "$", _re.DOTALL)

    def _eval_dynamic(self, env, xp):
        """Pattern is an EXPRESSION (sqlancer: x LIKE (cast(...)||t0)):
        evaluate both sides row-wise, compile per distinct pattern."""
        from ..ops import strkernels

        strkernels.note_path("host_fallback", "dynamic_pattern")
        v = self.expr.eval(env, xp)
        p = self.pattern.eval(env, xp)
        n = _env_rows(env)
        vr = _rows_of(v, n)
        pr = _rows_of(p, n)
        cache: dict = {}
        out = np.zeros(n, dtype=bool)
        nulls = np.zeros(n, dtype=bool)
        for i in range(n):
            val, pat = vr[i], pr[i]
            if val is None or pat is None:
                nulls[i] = True   # NULL operand: UNKNOWN either way
                continue
            rx = cache.get(pat)
            if rx is None:
                rx = cache[pat] = self._compile(str(pat))
            out[i] = bool(rx.match(str(val)))
        if self.negated:
            out = ~out & ~nulls
        if xp is np:
            out = _mask_operand_validity(out, env, self.expr)
        return out

    def eval(self, env, xp):
        if isinstance(self.pattern, Expr):
            return self._eval_dynamic(env, xp)
        from ..ops import strkernels

        v = self.expr.eval(env, xp)
        rx = self._regex()
        if isinstance(v, DictArray):
            if strkernels.enabled():
                # per-unique lane: classified vectorized mask over the
                # dictionary (or regex-per-unique), gathered through codes
                out = strkernels.like_rows(v, self.pattern, rx=rx,
                                           negated=self.negated)
            else:
                strkernels.note_path("host_fallback", "lane_disabled")
                out = v.map_values(
                    lambda x: bool(rx.match(x))
                    if isinstance(x, str) else False,
                    out_dtype=bool)
                out = ~out if self.negated else out
            if xp is np:
                out = _mask_operand_validity(out, env, self.expr)
            return out
        arr = np.asarray(v, dtype=object) if not np.isscalar(v) else None
        if arr is None:
            m = bool(rx.match(str(v)))
            return (not m) if self.negated else m
        strkernels.note_path("host_fallback", "unencoded_rows")
        out = np.fromiter(
            (bool(rx.match(x)) if isinstance(x, str) else False for x in arr),
            dtype=bool, count=len(arr))
        out = ~out if self.negated else out
        if xp is np:
            out = _mask_operand_validity(out, env, self.expr)
        return out

    def columns(self):
        out = set(self.expr.columns())
        if isinstance(self.pattern, Expr):
            out |= self.pattern.columns()
        return out

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        pat = self.pattern.to_sql() if isinstance(self.pattern, Expr) \
            else Literal(self.pattern).to_sql()
        return f"({self.expr.to_sql()}{neg} LIKE {pat})"


@dataclass(repr=False)
class Func(Expr):
    """Scalar function call evaluated row-wise (abs, floor, ceil, sqrt...)."""

    name: str
    args: list
    # aggregate-call ordering: array_agg(x ORDER BY time DESC) — (col, asc)
    agg_order: tuple | None = None

    # math scalars return Float64 regardless of input type (reference via
    # DataFusion's math_expressions: abs(BIGINT) renders 1.0 — pinned by
    # function/common/math_function/abs.slt)
    _FUNCS = {
        "abs": lambda xp, a: _math_float(xp, xp.abs(a)),
        "floor": lambda xp, a: xp.floor(a),
        "ceil": lambda xp, a: xp.ceil(a),
        "round": lambda xp, a, *nd: _math_float(
            xp, xp.round(a, *[int(d) for d in nd])),
        "sqrt": lambda xp, a: xp.sqrt(a),
        "cbrt": lambda xp, a: xp.cbrt(a),
        "exp": lambda xp, a: xp.exp(a),
        "ln": lambda xp, a: xp.log(a),
        "log10": lambda xp, a: xp.log10(a),
        "log2": lambda xp, a: xp.log2(a),
        "sin": lambda xp, a: xp.sin(a),
        "cos": lambda xp, a: xp.cos(a),
        "tan": lambda xp, a: xp.tan(a),
        "sinh": lambda xp, a: xp.sinh(a),
        "cosh": lambda xp, a: xp.cosh(a),
        "tanh": lambda xp, a: xp.tanh(a),
        "asin": lambda xp, a: xp.arcsin(a),
        "acos": lambda xp, a: xp.arccos(a),
        "atan": lambda xp, a: xp.arctan(a),
        "asinh": lambda xp, a: xp.arcsinh(a),
        "acosh": lambda xp, a: xp.arccosh(_math_float(xp, a)),
        "atanh": _rust_atanh,
        "atan2": lambda xp, a, b: (_f32_lift("atan2f", a, b)
                                   if _all_int(a, b)
                                   else xp.arctan2(a, b)),
        "pow": lambda xp, a, b: xp.power(a, b),
        "power": lambda xp, a, b: xp.power(a, b),
        # reference signum(0) = 1.0 (math_function/signum.slt) — sign
        # of the IEEE positive zero, not the three-valued sign
        "signum": lambda xp, a: _math_float(
            xp, xp.where(xp.isnan(a), a,
                         xp.where(xp.asarray(a) >= 0, 1.0, -1.0))
            if hasattr(a, "__len__") or hasattr(a, "shape")
            else (float("nan") if a != a else (1.0 if a >= 0 else -1.0))),
        "trunc": lambda xp, a: xp.trunc(a),
        "radians": lambda xp, a: xp.radians(a),
        "degrees": lambda xp, a: xp.degrees(a),
        "gcd": lambda xp, a, b: xp.gcd(_as_i64(xp, a), _as_i64(xp, b)),
        "lcm": lambda xp, a, b: xp.lcm(_as_i64(xp, a), _as_i64(xp, b)),
        "pi": lambda xp: xp.pi,
        # log(x) = log10 in the reference (DataFusion math_expressions);
        # log(base, x) is explicit-base
        "log": lambda xp, a, *b: (xp.log(b[0]) / xp.log(a)) if b
        else (_f32_log10(xp, a) if _all_int(a) else xp.log10(a)),
        "random": lambda xp: float(np.random.random()),
        "nullif": lambda xp, a, b: _fn_nullif(a, b),
        # analyzer-injected marker: timestamp - timestamp yields an
        # INTERVAL (arrow-rendered); wraps the subtraction's ns result
        "__to_interval": lambda xp, a: _to_interval(a),
        # scalar/constant form (SELECT time_window(cast(1 as timestamp),
        # interval '3 day')): the row-expanding form is rewritten by the
        # executor before evaluation (executor._expand_time_window)
        "time_window": lambda xp, t, window, *rest: _time_window_scalar(
            t, window, *rest),
    }

    def eval(self, env, xp):
        f = self._FUNCS.get(self.name.lower())
        if f is None:
            raise PlanError(f"unknown function {self.name!r}")
        try:
            return f(xp, *[a.eval(env, xp) for a in self.args])
        except TypeError as e:
            # wrong arity / argument kinds surface as plan errors
            # (current_date(1), current_time(current_time()), …)
            raise PlanError(
                f"no function matches the given argument types: {e}")

    def columns(self):
        out = set()
        for a in self.args:
            out |= a.columns()
        return out

    def to_sql(self):
        if self.name == "__to_interval" and self.args:
            # analyzer-injected rendering marker: invisible in output
            # column names and EXPLAIN
            return self.args[0].to_sql()
        return f"{self.name}({', '.join(a.to_sql() for a in self.args)})"


def trunc_mod(a: int, b: int) -> int:
    """Rust/C truncating remainder (sign of the dividend) in exact int
    arithmetic — np.fmod on python scalars would round through float64."""
    r = a % b
    if r and (a < 0) != (b < 0):
        r -= b
    return r


def _interval_arg_ns(v) -> int:
    """Interval-typed argument value → ns (IntervalValue literal or int)."""
    if hasattr(v, "ns"):
        return int(v.ns)
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        return int(v)
    raise PlanError("time_window durations must be INTERVAL values")


def _time_window_scalar(t, window, *rest):
    """Tumbling window containing t (reference TIME_WINDOW with the slide
    defaulted to the window width; origin = epoch or the 4th argument)."""
    if t is None:
        return None
    if hasattr(t, "item"):
        t = t.item()
    w = _interval_arg_ns(window)
    slide = _interval_arg_ns(rest[0]) if rest else w
    origin = 0
    if len(rest) > 1:
        origin = rest[1]
        if isinstance(origin, str):
            from .parser import parse_timestamp_string

            origin = parse_timestamp_string(origin)
    if w <= 0 or slide <= 0:
        raise PlanError("time_window durations must be positive")
    t = int(t)
    # st_mod uses the WINDOW duration (transform_time_window.rs:270-274)
    st_mod = trunc_mod(int(origin), w)
    start = t - trunc_mod(t - st_mod + slide, slide)
    return {"kind": "window", "start": start, "end": start + w}


def _regexp_replace(v, pat, rep, flags=""):
    """DataFusion regexp_replace (Rust regex \\1 backrefs match python
    re.sub's); 'g' flag = replace all, else first occurrence; i/m/s/x
    map to the matching regex modes, anything else is an error (never
    silently dropped)."""
    import re as _re

    if isinstance(pat, (np.ndarray, DictArray)) \
            or isinstance(rep, (np.ndarray, DictArray)):
        raise PlanError("regexp_replace pattern must be a constant")
    count = 1
    fl = 0
    for ch in str(flags):
        if ch == "g":
            count = 0
        elif ch == "i":
            fl |= _re.IGNORECASE
        elif ch == "m":
            fl |= _re.MULTILINE
        elif ch == "s":
            fl |= _re.DOTALL
        elif ch == "x":
            fl |= _re.VERBOSE
        else:
            raise PlanError(
                f"regexp_replace() does not support the \"{ch}\" flag")
    rx = _re.compile(str(pat), fl)

    def one(x):
        return None if x is None else rx.sub(str(rep), str(x),
                                             count=count)

    if isinstance(v, DictArray):
        return v.map_values(one)
    if isinstance(v, np.ndarray):
        out = np.empty(len(v), dtype=object)
        out[:] = [one(x) for x in v]
        return out
    return one(v)


def _fn_nullif(a, b):
    """NULLIF(a, b): NULL where a == b, else a."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray) \
            or isinstance(a, DictArray) or isinstance(b, DictArray):
        n = next(len(x) for x in (a, b)
                 if isinstance(x, (np.ndarray, DictArray)))
        ar = _rows_of(a, n)
        br = _rows_of(b, n)
        out = np.empty(n, dtype=object)
        for i in range(n):
            x, y = ar[i], br[i]
            eq = (x is not None and y is not None and x == y)
            out[i] = None if eq else x
        return out
    if a is None:
        return None
    return None if (b is not None and a == b) else a


def _fn_date_bin(iv, ts, origin):
    """DATE_BIN(interval, ts[, origin]) → bucket start ns (floor toward
    -inf relative to origin, DataFusion semantics)."""
    if not _is_interval(iv):
        raise PlanError("date_bin's first argument must be an INTERVAL")
    step = int(iv.ns)
    if step <= 0:
        raise PlanError("date_bin interval must be positive")
    if isinstance(origin, str):
        from .parser import parse_timestamp_string

        origin = parse_timestamp_string(origin)
    o = int(origin) if origin is not None else 0
    if isinstance(ts, np.ndarray):
        t = ts.astype(np.int64)
        return o + ((t - o) // step) * step
    if ts is None:
        return None
    t = int(ts.item() if hasattr(ts, "item") else ts)
    return o + ((t - o) // step) * step


def _to_interval(a):
    from .tsfuncs import IntervalNs

    if isinstance(a, np.ndarray):
        out = np.empty(len(a), dtype=object)
        for i, v in enumerate(a):
            out[i] = None if v is None else IntervalNs(int(v))
        return out
    return None if a is None else IntervalNs(int(a))


def _str_func(fn, *, out=object, strict=True):
    """Lift a python string function elementwise over object columns
    (DataFusion-inherited string scalars in the reference). strict
    functions reject non-string inputs ('The function can only accept
    strings' — string_func/*.slt); ascii and the concat family coerce."""
    def run(xp, arr, *rest):
        import numpy as _np

        if strict:
            _require_string_input(arr)
        rest = tuple(r.materialize() if isinstance(r, DictArray) else r
                     for r in rest)
        arr_rest = [r for r in rest
                    if isinstance(r, _np.ndarray) and r.shape != ()]
        if arr_rest:
            # column-valued extra args (strpos(t0, t1)): elementwise zip
            if isinstance(arr, DictArray):
                arr = arr.materialize()
            n = len(arr) if isinstance(arr, _np.ndarray) \
                else len(arr_rest[0])
            cols = [arr if isinstance(arr, _np.ndarray) else [arr] * n]
            for r in rest:
                cols.append(r if isinstance(r, _np.ndarray)
                            and r.shape != ()
                            else [r.item() if hasattr(r, "item") else r]
                            * n)
            try:
                vals = [None if row[0] is None
                        or any(x is None for x in row[1:])
                        else fn(str(row[0]), *row[1:])
                        for row in zip(*cols)]
            except TypeError as exc:
                raise PlanError(
                    f"no function matches the given argument types: "
                    f"{exc}")
            if out is object:
                o = _np.empty(len(vals), dtype=object)
                o[:] = vals
                return o
            return _np.array([out() if v is None else v for v in vals],
                             dtype=out)
        rest = [r.item() if hasattr(r, "item") else r for r in rest]
        if any(r is None for r in rest):
            # a NULL argument makes every row NULL (strict scalar
            # semantics: replace(s, x, NULL) → NULL)
            if isinstance(arr, (DictArray, _np.ndarray)):
                n_ = len(arr)
                o = _np.empty(n_, dtype=object)
                o[:] = None
                return o
            return None
        if isinstance(arr, DictArray):
            return arr.map_values(lambda x: fn(str(x), *rest),
                                  out_dtype=out if out is not object
                                  else object)
        try:
            if isinstance(arr, _np.ndarray):
                vals = [None if x is None else fn(_str_coerce(x), *rest)
                        for x in arr]
                if out is object:
                    o = _np.empty(len(vals), dtype=object)
                    o[:] = vals
                    return o
                return _np.array([out() if v is None else v
                                  for v in vals], dtype=out)
            return None if arr is None else fn(_str_coerce(arr), *rest)
        except TypeError as exc:
            # mismatched argument types surface as plan errors, like the
            # reference's "No function matches the given name and
            # argument types"
            raise PlanError(
                f"no function matches the given argument types: {exc}")
    return run


def _str_coerce(x) -> str:
    """Implicit cast-to-string for the LENIENT string functions: bools
    render '1'/'0' (matching CAST(bool AS STRING) — ascii(f2) over a
    BOOLEAN column yields 49/48 in the reference)."""
    if isinstance(x, (bool, np.bool_)):
        return "1" if x else "0"
    if isinstance(x, (float, np.floating)):
        return repr(float(x))
    return str(x)


def _fn_substr(s, start, length=None):
    """SQL substr: 1-based; a start < 1 consumes the length window before
    position 1 (PostgreSQL/DataFusion semantics)."""
    start = _int_n(start, "substr")
    if length is None:
        return s[max(0, start - 1):]
    length = _int_n(length, "substr")
    if length < 0:
        raise PlanError("substr length must not be negative")
    end = start + length                     # exclusive 1-based end
    lo = max(1, start)
    if end <= lo:
        return ""
    return s[lo - 1:end - 1]


def _fn_lpad(s, n, p=" "):
    n = _int_n(n, "lpad")
    p = _str_coerce(p)            # numeric pad coerces (reference:
    if n <= len(s):               # rpad.slt pads with a bigint column)
        return s[:n]              # SQL lpad truncates to the target length
    if not p:
        return s
    return (p * n)[:n - len(s)] + s


def _fn_rpad(s, n, p=" "):
    n = _int_n(n, "rpad")
    p = _str_coerce(p)
    if n <= len(s):
        return s[:n]
    if not p:
        return s
    return s + (p * n)[:n - len(s)]


def _fn_concat_op(xp, a, b):
    """The || OPERATOR: NULL-propagating (unlike concat(), which skips
    NULL arguments — DataFusion distinguishes the two; sqlancer pins a
    NULL || x as NULL through CAST/SUM)."""
    import numpy as _np

    parts = [p.materialize() if isinstance(p, DictArray) else p
             for p in (a, b)]
    arrays = [p for p in parts if isinstance(p, _np.ndarray)]
    if not arrays:
        if a is None or b is None:
            return None
        return _cap_result(_str_coerce(a) + _str_coerce(b))
    n = len(arrays[0])
    cols = [p if isinstance(p, _np.ndarray) else [p] * n for p in parts]
    o = _np.empty(n, dtype=object)
    o[:] = [None if (x is None or y is None)
            else _cap_result(_str_coerce(x) + _str_coerce(y))
            for x, y in zip(*cols)]
    return o


def _fn_concat(xp, *parts):
    import numpy as _np

    if not parts:
        raise PlanError("concat takes at least one argument")

    parts = [p.materialize() if isinstance(p, DictArray) else p
             for p in parts]
    arrays = [p for p in parts if isinstance(p, _np.ndarray)]
    if not arrays:
        return _cap_result("".join("" if p is None else _str_coerce(p)
                                   for p in parts))
    n = len(arrays[0])
    cols = [p if isinstance(p, _np.ndarray) else [p] * n for p in parts]
    o = _np.empty(n, dtype=object)
    o[:] = [_cap_result("".join("" if v is None else _str_coerce(v)
                                for v in row))
            for row in zip(*cols)]
    return o


def _as_i64(xp, a):
    """gcd/lcm demand integer operands (DataFusion casts, erroring on
    fractional input); numpy would silently truncate floats."""
    arr = xp.asarray(a)
    if arr.dtype.kind == "f":
        if not bool(xp.all(arr == xp.floor(arr))):
            raise PlanError("gcd/lcm require integer arguments")
    return arr.astype(xp.int64) if hasattr(arr, "astype") else arr


def _require_string_input(arr):
    import numpy as _np

    bad = False
    if isinstance(arr, DictArray):
        return
    if isinstance(arr, _np.ndarray):
        if arr.dtype.kind in "iufb":
            bad = True
        elif arr.dtype == object:
            bad = any(isinstance(x, (int, float, _np.number, bool))
                      and not isinstance(x, str)
                      for x in arr if x is not None)
    elif isinstance(arr, (bool, int, float, _np.number)):
        bad = True
    if bad:
        raise PlanError("the function can only accept strings")


def _exact1(fn):
    def run(s, *rest):
        if rest:
            raise PlanError("function takes exactly one argument")
        return fn(s)
    return run


def _fn_ascii(s):
    return ord(s[0]) if s else 0


def _fn_initcap(s):
    """Uppercase the first alphanumeric of each word, lowercase the rest
    (word = alphanumeric run, PostgreSQL/DataFusion initcap)."""
    out = []
    new_word = True
    for ch in s:
        if ch.isalnum():
            out.append(ch.upper() if new_word else ch.lower())
            new_word = False
        else:
            out.append(ch)
            new_word = True
    return "".join(out)


def _fn_left(s, n):
    n = _int_n(n, "left")
    if n >= 0:
        return _cap_result(s[:n])
    return _cap_result(s[:max(0, len(s) + n)])


def _fn_right(s, n):
    n = _int_n(n, "right")
    if n >= 0:
        return _cap_result(s[max(0, len(s) - n):] if n else "")
    return _cap_result(s[-n:])


def _fn_split_part(s, delim, n):
    n = _int_n(n, "split_part")
    if n <= 0:
        # reference: field position must be greater than zero
        # (query_server/sqllogicaltests/cases/function/string_func/
        #  split_part.slt)
        raise PlanError("split_part field position must be greater "
                        "than zero")
    delim = _str_coerce(delim)      # int delimiter coerces ('123')
    if delim == "":
        return ""           # reference renders empty, not an error
    parts = s.split(delim)
    return parts[n - 1] if n <= len(parts) else ""


def _fn_translate(s, src, dst):
    src, dst = _str_coerce(src), _str_coerce(dst)
    table = {ord(c): (dst[i] if i < len(dst) else None)
             for i, c in enumerate(src)}
    return s.translate(table)


def _fn_md5(s):
    import hashlib

    return hashlib.md5(s.encode()).hexdigest()


def _fn_iso(x):
    from datetime import datetime, timezone

    ns = int(x)
    secs, frac = divmod(ns, 1_000_000_000)
    dt = datetime.fromtimestamp(secs, tz=timezone.utc)
    base = dt.strftime("%Y-%m-%dT%H:%M:%S")
    if frac:
        digits = f"{frac:09d}"
        # trim in 3-digit groups (ns -> us -> ms), matching arrow's
        # timestamp rendering ('.010', not '.01')
        while digits.endswith("000"):
            digits = digits[:-3]
        base += "." + digits
    return base


def _fn_chr(x):
    n = int(x)
    if n <= 0 or n > 0x10FFFF:
        raise PlanError(f"chr() argument out of range: {n}")
    return chr(n)


def _int_n(v, what: str) -> int:
    """Length/position arguments must be INTEGERS (reference errors on
    LEFT('Hello', 2.7)); bools reject too."""
    if isinstance(v, bool) or (isinstance(v, float) and v != int(v)):
        raise PlanError(f"{what} requires an integer argument, got {v!r}")
    return int(v)


def _fn_repeat(s, n):
    n = _int_n(n, "repeat")
    if n < 0:
        n = 0
    if len(s) * n > (1 << 28):
        raise PlanError("repeat result exceeds the 256MiB string limit")
    return s * n


def _cap_result(s: str) -> str:
    """left/right/concat outputs are bounded at 2^22 bytes (the
    reference errors on LEFT(huge, 10_000_000) and on a 4,294,305-char
    CONCAT but passes REPEAT alone)."""
    if len(s) > (1 << 22):
        raise PlanError("string result exceeds the 4MiB limit")
    return s


class TimeOfDayLit(Literal):
    """current_time(): a Time64 value carried as its 'HH:MM:SS.ffffff'
    rendering — lexical comparisons work, but string functions reject it
    (reference: length(current_time()) is a type error)."""


class DateLit(Literal):
    """DATE '2024-08-08': behaves as its ISO string everywhere except
    scalar signature checks (reference: substr(DATE …) is a type error —
    Date32 is not Utf8)."""


def _fn_to_hex(x):
    v = int(x)
    # DataFusion to_hex renders the two's-complement i64 bit pattern
    return format(v & 0xFFFFFFFFFFFFFFFF, "x") if v < 0 else format(v, "x")


def _to_hex_lift(xp, arr, *rest):
    """to_hex(Int64): a bare NULL literal is untypable upstream and
    errors; NULL column slots yield NULL."""
    if rest:
        raise PlanError("to_hex takes exactly one argument")
    if arr is None:
        raise PlanError("to_hex does not support a NULL literal")
    return _obj_func(_fn_to_hex, numeric=False)(xp, arr)


def _fn_concat_ws(xp, sep, *parts):
    import numpy as _np

    if not parts:
        raise PlanError("concat_ws takes a separator and at least one "
                        "argument")

    if isinstance(sep, DictArray):
        sep = sep.materialize()
    if isinstance(sep, _np.ndarray) and sep.shape != ():
        # column-valued separator: per-row join (reference:
        # concat_ws(f0, f0) joins each row with its own value)
        parts = [p.materialize() if isinstance(p, DictArray) else p
                 for p in parts]
        n = len(sep)
        cols = [p if isinstance(p, _np.ndarray) else [p] * n
                for p in parts]
        o = _np.empty(n, dtype=object)
        o[:] = [None if s is None else
                _cap_result(_str_coerce(s).join(
                    _str_coerce(v) for v in row if v is not None))
                for s, *row in zip(sep, *cols)]
        return o
    sep_v = sep.item() if hasattr(sep, "item") else sep
    if sep_v is None:
        # NULL separator → NULL result (PostgreSQL/DataFusion)
        arrs = [p for p in parts if isinstance(p, _np.ndarray)]
        if not arrs:
            return None
        o = _np.empty(len(arrs[0]), dtype=object)
        o[:] = None
        return o
    parts = [p.materialize() if isinstance(p, DictArray) else p
             for p in parts]
    arrays = [p for p in parts if isinstance(p, _np.ndarray)]
    if not arrays:
        vals = [_str_coerce(p) for p in parts if p is not None]
        return _cap_result(str(sep_v).join(vals))
    n = len(arrays[0])
    cols = [p if isinstance(p, _np.ndarray) else [p] * n for p in parts]
    o = _np.empty(n, dtype=object)
    o[:] = [_cap_result(str(sep_v).join(_str_coerce(v) for v in row
                                        if v is not None))
            for row in zip(*cols)]
    return o


# -- time scalars (int64 ns timestamps; reference renders these as arrow
#    timestamps — query_server scalar set inherited from DataFusion) ------

_NS = 1_000_000_000


def _ns_to_dt(ns: int):
    from datetime import datetime, timezone

    return datetime.fromtimestamp(int(ns) / 1e9, tz=timezone.utc)


def _fn_date_part(field, ns):
    from datetime import timezone

    dt = _ns_to_dt(ns)
    f = str(field).lower()
    if f in ("year", "years"):
        v = dt.year
    elif f in ("quarter",):
        v = (dt.month - 1) // 3 + 1
    elif f in ("month", "months"):
        v = dt.month
    elif f in ("week", "weeks"):
        v = dt.isocalendar()[1]
    elif f in ("day", "days"):
        v = dt.day
    elif f in ("doy",):
        v = dt.timetuple().tm_yday
    elif f in ("dow",):
        v = (dt.weekday() + 1) % 7   # Sunday = 0 (PostgreSQL dow)
    elif f in ("hour", "hours"):
        v = dt.hour
    elif f in ("minute", "minutes"):
        v = dt.minute
    elif f in ("second", "seconds"):
        v = dt.second + dt.microsecond / 1e6
    elif f in ("millisecond", "milliseconds"):
        v = (dt.second + dt.microsecond / 1e6) * 1e3
    elif f in ("microsecond", "microseconds"):
        v = (dt.second + dt.microsecond / 1e6) * 1e6
    elif f in ("nanosecond", "nanoseconds"):
        v = dt.second * 1e9 + (int(ns) % _NS)
    elif f in ("epoch",):
        v = int(ns) / 1e9
    else:
        raise PlanError(f"date_part: unknown field {field!r}")
    return float(v)


def _fn_date_trunc(granularity, ns):
    from datetime import datetime, timezone

    dt = _ns_to_dt(ns)
    g = str(granularity).lower()
    if g == "year":
        dt2 = datetime(dt.year, 1, 1, tzinfo=timezone.utc)
    elif g == "quarter":
        dt2 = datetime(dt.year, ((dt.month - 1) // 3) * 3 + 1, 1,
                       tzinfo=timezone.utc)
    elif g == "month":
        dt2 = datetime(dt.year, dt.month, 1, tzinfo=timezone.utc)
    elif g == "week":
        from datetime import timedelta

        d0 = datetime(dt.year, dt.month, dt.day, tzinfo=timezone.utc)
        dt2 = d0 - timedelta(days=dt.weekday())
    elif g == "day":
        dt2 = datetime(dt.year, dt.month, dt.day, tzinfo=timezone.utc)
    elif g == "hour":
        return (int(ns) // (3600 * _NS)) * 3600 * _NS
    elif g == "minute":
        return (int(ns) // (60 * _NS)) * 60 * _NS
    elif g == "second":
        return (int(ns) // _NS) * _NS
    elif g == "millisecond":
        return (int(ns) // 1_000_000) * 1_000_000
    elif g == "microsecond":
        return (int(ns) // 1_000) * 1_000
    else:
        raise PlanError(f"date_trunc: unknown granularity {granularity!r}")
    return int(dt2.timestamp()) * _NS


_DAY_NS = 86_400 * _NS


def _vec_date_part(field, ns):
    """Array form of _fn_date_part over int64 ns columns: datetime64
    calendar math instead of per-row datetime.fromtimestamp (the single
    hottest scalar loop in the relational path — ClickBench q19 spends
    seconds here). Integer arithmetic throughout, so results are exact
    where the float-seconds scalar path can round near bucket edges.
    Returns float64 (the scalar path always returns float) or None for
    unknown fields (caller's scalar loop raises the canonical error)."""
    import numpy as _np

    f = str(field).lower()
    ns = ns.astype(_np.int64, copy=False)
    if f in ("minute", "minutes"):
        return ((ns // (60 * _NS)) % 60).astype(_np.float64)
    if f in ("hour", "hours"):
        return ((ns // (3600 * _NS)) % 24).astype(_np.float64)
    if f in ("dow",):
        # epoch day 0 = Thursday; PostgreSQL dow has Sunday = 0
        return ((ns // _DAY_NS + 4) % 7).astype(_np.float64)
    if f in ("second", "seconds"):
        return (ns % (60 * _NS)) / 1e9
    if f in ("millisecond", "milliseconds"):
        return (ns % (60 * _NS)) / 1e6
    if f in ("microsecond", "microseconds"):
        return (ns % (60 * _NS)) / 1e3
    if f in ("nanosecond", "nanoseconds"):
        return (((ns // _NS) % 60) * _NS + ns % _NS).astype(_np.float64)
    if f in ("epoch",):
        return ns / 1e9
    d = ns.astype("datetime64[ns]")
    if f in ("year", "years"):
        return (d.astype("datetime64[Y]").astype(_np.int64)
                + 1970).astype(_np.float64)
    mo = d.astype("datetime64[M]").astype(_np.int64)
    if f in ("month", "months"):
        return (mo % 12 + 1).astype(_np.float64)
    if f in ("quarter",):
        return ((mo % 12) // 3 + 1).astype(_np.float64)
    days = d.astype("datetime64[D]")
    if f in ("day", "days"):
        return ((days - mo.astype("datetime64[M]").astype("datetime64[D]"))
                .astype(_np.int64) + 1).astype(_np.float64)
    if f in ("doy",):
        y0 = d.astype("datetime64[Y]").astype("datetime64[D]")
        return ((days - y0).astype(_np.int64) + 1).astype(_np.float64)
    if f in ("week", "weeks"):
        # ISO week = ordinal of this week's Thursday within ITS year
        epoch_days = days.astype(_np.int64)
        th = epoch_days - (epoch_days + 3) % 7 + 3
        thd = th.astype("datetime64[D]")
        ty0 = thd.astype("datetime64[Y]").astype("datetime64[D]")
        return (((thd - ty0).astype(_np.int64)) // 7 + 1).astype(_np.float64)
    return None


def _vec_date_trunc(granularity, ns):
    """Array form of _fn_date_trunc; int64 output matching the scalar
    path's all-int listcomp dtype. None for unknown granularities."""
    import numpy as _np

    g = str(granularity).lower()
    ns = ns.astype(_np.int64, copy=False)
    unit = {"hour": 3600 * _NS, "minute": 60 * _NS, "second": _NS,
            "millisecond": 1_000_000, "microsecond": 1_000}.get(g)
    if unit is not None:
        return (ns // unit) * unit
    if g == "day":
        return (ns // _DAY_NS) * _DAY_NS
    if g == "week":
        days = ns // _DAY_NS
        return (days - (days + 3) % 7) * _DAY_NS   # back to Monday
    d = ns.astype("datetime64[ns]")
    if g == "month":
        return d.astype("datetime64[M]").astype("datetime64[ns]") \
            .astype(_np.int64)
    if g == "quarter":
        mo = d.astype("datetime64[M]").astype(_np.int64)
        return ((mo // 3) * 3).astype("datetime64[M]") \
            .astype("datetime64[ns]").astype(_np.int64)
    if g == "year":
        return d.astype("datetime64[Y]").astype("datetime64[ns]") \
            .astype(_np.int64)
    return None


def _fn_from_unixtime(x):
    if isinstance(x, (float, np.floating)) or isinstance(x, str):
        # reference signature: from_unixtime(Int64) only
        raise PlanError(
            "from_unixtime does not support this input type (Int64 only)")
    return int(x) * _NS


def _fn_to_timestamp(x, scale_ns: int = 1):
    """String → ns (ISO-8601), or INTEGER scaled by the unit variant
    (to_timestamp=ns, _seconds/_millis/_micros — DataFusion signatures
    reject Float64)."""
    if isinstance(x, str):
        from .parser import parse_timestamp_string

        return parse_timestamp_string(x)
    if isinstance(x, (float, np.floating)):
        raise PlanError(
            "to_timestamp does not support Float64 input")
    return int(x) * scale_ns


def _register_time_scalars():
    import time as _time
    from datetime import datetime, timezone

    Func._FUNCS.update({
        "now": lambda xp: int(_time.time() * 1e9),

        "current_timestamp": lambda xp: int(_time.time() * 1e9),
        "current_date": lambda xp: datetime.now(timezone.utc)
        .strftime("%Y-%m-%d"),
        "current_time": lambda xp: datetime.now(timezone.utc)
        .strftime("%H:%M:%S.%f"),
        "date_part": _scalar_first_obj(_fn_date_part, vec=_vec_date_part),
        "datepart": _scalar_first_obj(_fn_date_part, vec=_vec_date_part),
        "date_trunc": _scalar_first_obj(_fn_date_trunc,
                                        vec=_vec_date_trunc),
        # relational-path DATE_BIN (the single-table path lowers it into
        # the bucket kernel; derived subqueries evaluate it row-wise —
        # tsbench avg_daily_driving_duration buckets inside a CTE)
        "date_bin": lambda xp, iv, ts, *origin: _fn_date_bin(
            iv, ts, origin[0] if origin else 0),
        "datetrunc": _scalar_first_obj(_fn_date_trunc,
                                       vec=_vec_date_trunc),
        "from_unixtime": _obj_func(_fn_from_unixtime),
        "to_timestamp": _obj_func(_fn_to_timestamp),
        "to_timestamp_seconds": _obj_func(
            lambda x: _fn_to_timestamp(x, _NS) if not isinstance(x, str)
            else (_fn_to_timestamp(x) // _NS) * _NS),
        "to_timestamp_millis": _obj_func(
            lambda x: _fn_to_timestamp(x, 1_000_000) if not isinstance(x, str)
            else (_fn_to_timestamp(x) // 1_000_000) * 1_000_000),
        "to_timestamp_micros": _obj_func(
            lambda x: _fn_to_timestamp(x, 1_000) if not isinstance(x, str)
            else (_fn_to_timestamp(x) // 1_000) * 1_000),
    })


def _scalar_first_obj(fn, vec=None):
    """Lift fn(scalar_opt, value) where the FIRST argument is a scalar
    option (field name / granularity) and the second is the column.
    `vec` is an optional whole-array fast path for integer columns (the
    timestamp case); it returns None to defer to the scalar loop."""
    def run(xp, opt, arr):
        import numpy as _np

        opt = opt.item() if hasattr(opt, "item") else opt
        if isinstance(arr, _np.ndarray):
            if vec is not None and arr.dtype.kind in "iu" and len(arr):
                out = vec(opt, arr)
                if out is not None:
                    return out
            vals = [None if x is None else fn(opt, x) for x in arr]
            if vals and all(isinstance(v, int) for v in vals):
                return _np.array(vals, dtype=_np.int64)
            if vals and all(v is None or isinstance(v, (int, float))
                            for v in vals):
                return _np.array([_np.nan if v is None else float(v)
                                  for v in vals])
            o = _np.empty(len(vals), dtype=object)
            o[:] = vals
            return o
        return None if arr is None else fn(opt, arr)
    return run


def _obj_func(fn, *, numeric: bool = True):
    """Lift a python function over object columns (gauge/state composites
    from sql.tsfuncs). Extra args arrive as evaluated scalars."""
    def run(xp, arr, *rest):
        import numpy as _np

        if isinstance(arr, DictArray):
            arr = arr.materialize()
        rest = [r.item() if hasattr(r, "item") else r for r in rest]
        if isinstance(arr, _np.ndarray):
            vals = [None if x is None else fn(x, *rest) for x in arr]
            if numeric:
                # exact type check: int SUBCLASSES (IntervalNs) must stay
                # objects so their interval rendering survives
                if all(v is None or type(v) in (int, float) for v in vals):
                    if any(v is None for v in vals):
                        return _np.array([_np.nan if v is None else float(v)
                                          for v in vals])
                    if all(isinstance(v, int) for v in vals):
                        return _np.array(vals, dtype=_np.int64)
                    return _np.array(vals, dtype=_np.float64)
            out = _np.empty(len(vals), dtype=object)
            out[:] = vals
            return out
        return None if arr is None else fn(arr, *rest)
    return run


def _binary_obj_func(fn):
    """Pairwise lift for two-geometry scalars (st_distance)."""
    def run(xp, a, b, *rest):
        import numpy as _np

        if isinstance(a, DictArray):
            a = a.materialize()
        if isinstance(b, DictArray):
            b = b.materialize()

        if isinstance(a, _np.ndarray) or isinstance(b, _np.ndarray):
            n = len(a) if isinstance(a, _np.ndarray) else len(b)
            aa = a if isinstance(a, _np.ndarray) else [a] * n
            bb = b if isinstance(b, _np.ndarray) else [b] * n
            return _np.array([
                _np.nan if (x is None or y is None) else fn(x, y, *rest)
                for x, y in zip(aa, bb)])
        if a is None or b is None:
            return None
        return fn(a, b, *rest)
    return run


def _binary_pred(fn):
    """Pairwise lift for boolean geometry predicates: NULL in → NULL
    out (object arrays keep None; _binary_obj_func's NaN would render
    'NaN')."""
    def run(xp, a, b, *rest):
        import numpy as _np

        if isinstance(a, DictArray):
            a = a.materialize()
        if isinstance(b, DictArray):
            b = b.materialize()
        if isinstance(a, _np.ndarray) or isinstance(b, _np.ndarray):
            n = len(a) if isinstance(a, _np.ndarray) else len(b)
            aa = a if isinstance(a, _np.ndarray) else [a] * n
            bb = b if isinstance(b, _np.ndarray) else [b] * n
            out = _np.empty(n, dtype=object)
            out[:] = [None if (x is None or y is None) else fn(x, y)
                      for x, y in zip(aa, bb)]
            return out
        if a is None or b is None:
            return None
        return fn(a, b)
    return run


def _register_tsfuncs():
    """Gauge/state accessors + GIS scalars (reference scalar_function/
    gauge/*.rs, duration_in.rs, state_at.rs, gis/*.rs). Registered lazily
    at module bottom to avoid an import cycle with sql.tsfuncs."""
    from . import gis as _gis
    from . import tsfuncs as tf

    Func._FUNCS.update({
        "delta": _obj_func(tf.gauge_delta),
        "time_delta": _obj_func(tf.gauge_time_delta),
        "rate": _obj_func(tf.gauge_rate),
        "first_val": _obj_func(lambda g: g["first"][1]),
        "last_val": _obj_func(lambda g: g["last"][1]),
        "first_time": _obj_func(lambda g: g["first"][0]),
        "last_time": _obj_func(lambda g: g["last"][0]),
        "idelta_left": _obj_func(tf.gauge_idelta_left),
        "idelta_right": _obj_func(tf.gauge_idelta_right),
        "num_elements": _obj_func(lambda g: g["num_elements"]),
        "duration_in": _obj_func(tf.duration_in),
        "state_at": _obj_func(tf.state_at, numeric=False),
        "st_distance": _binary_obj_func(tf.st_distance),
        "st_area": _obj_func(tf.st_area),
        "st_asbinary": _obj_func(_gis.st_asbinary, numeric=False),
        "st_geomfromwkb": _obj_func(_gis.st_geomfromwkb, numeric=False),
        "st_intersects": _binary_pred(_gis.st_intersects),
        "st_disjoint": _binary_pred(_gis.st_disjoint),
        "st_contains": _binary_pred(_gis.st_contains),
        "st_within": _binary_pred(_gis.st_within),
        "st_equals": _binary_pred(_gis.st_equals),
        # string scalars (DataFusion-inherited set in the reference)
        "upper": _str_func(str.upper),
        "lower": _str_func(str.lower),
        "length": _str_func(len, out=np.int64),
        "regexp_replace": lambda xp, v, pat, rep, *flags: _regexp_replace(
            v, pat, rep, flags[0] if flags else ""),
        "char_length": _str_func(len, out=np.int64),
        # TRIM takes exactly one argument (the charset form is btrim /
        # TRIM(BOTH..FROM)); ltrim/rtrim accept an optional charset
        # (reference ltrim.slt: ltrim('   sdf', ' s') works)
        "trim": _str_func(_exact1(str.strip)),
        "ltrim": _str_func(lambda s, *c: s.lstrip(*[str(x) for x in c])),
        "rtrim": _str_func(lambda s, *c: s.rstrip(*[str(x) for x in c])),
        "reverse": _str_func(lambda s: s[::-1]),
        "substr": _str_func(_fn_substr),
        "substring": _str_func(_fn_substr),
        "replace": _str_func(
            lambda s, a, b: s.replace(_str_coerce(a), _str_coerce(b))),
        # starts/ends_with coerce non-strings (reference:
        # starts_with(123, 'hello') → false)
        "starts_with": _str_func(
            lambda s, p: s.startswith(_str_coerce(p)), out=np.bool_,
            strict=False),
        "ends_with": _str_func(
            lambda s, p: s.endswith(_str_coerce(p)), out=np.bool_,
            strict=False),
        "concat": _fn_concat,
        "__concat_op": _fn_concat_op,
        "strpos": _str_func(lambda s, sub: s.find(_str_coerce(sub)) + 1,
                            out=np.int64),
        "repeat": _str_func(_fn_repeat),
        "lpad": _str_func(_fn_lpad),
        "rpad": _str_func(_fn_rpad),
        "ascii": _str_func(_fn_ascii, out=np.int64, strict=False),
        # internal: timestamp → ISO string (analyzer wraps time args of
        # lenient string functions so ascii(time) sees '1999-…' like the
        # reference's implicit timestamp→utf8 cast)
        "__iso__": _obj_func(_fn_iso, numeric=False),
        "chr": _obj_func(_fn_chr, numeric=False),
        "bit_length": _str_func(lambda s: len(s.encode()) * 8,
                                out=np.int64),
        "octet_length": _str_func(lambda s: len(s.encode()), out=np.int64),
        "character_length": _str_func(len, out=np.int64),
        "btrim": _str_func(lambda s, *c: s.strip(*c)),
        "ltrim_chars": _str_func(lambda s, c: s.lstrip(c)),
        "rtrim_chars": _str_func(lambda s, c: s.rstrip(c)),
        "initcap": _str_func(_fn_initcap),
        "left": _str_func(_fn_left),
        "right": _str_func(_fn_right),
        "split_part": _str_func(_fn_split_part),
        "translate": _str_func(_fn_translate),
        "md5": _str_func(_fn_md5),
        "to_hex": _to_hex_lift,
        "concat_ws": _fn_concat_ws,
    })
    _register_time_scalars()


def _parse_bool_str(s: str) -> bool:
    low = str(s).strip().lower()
    if low in ("t", "true", "1", "yes"):
        return True
    if low in ("f", "false", "0", "no"):
        return False
    raise ValueError(f"invalid boolean string {s!r}")


def _cast_scalar(x, kind: str):
    """One value → cast target kind (i/u/f/s/b/t/v). Raises ValueError/
    OverflowError on impossible casts (DataFusion-style strict CAST)."""
    if kind == "v":   # INTERVAL: '3 day' → ns span (arrow-rendered)
        from .parser import parse_interval_string
        from .tsfuncs import IntervalNs

        if isinstance(x, str):
            return IntervalNs(parse_interval_string(x))
        raise ValueError(f"cannot cast {x!r} to INTERVAL")
    if kind == "t" and isinstance(x, str):
        # arrow parses string→timestamp as RFC3339 text, never as an
        # integer ("Error parsing timestamp from '0'" — sqlancer pins it)
        s = x.strip()
        if "-" not in s[1:] and ":" not in s:
            raise ValueError(f"Error parsing timestamp from {s!r}")
        from .parser import parse_timestamp_string

        return parse_timestamp_string(s)
    if kind in ("i", "t", "u"):
        if isinstance(x, str):
            out = int(x.strip())
        elif isinstance(x, (float, np.floating)):
            if np.isnan(x) or np.isinf(x):
                raise ValueError(f"cannot cast {x} to integer")
            out = int(x)          # truncation toward zero
        else:
            out = int(x)
        if kind == "u" and out < 0:
            raise ValueError(f"cannot cast negative {out} to unsigned")
        return out
    if kind == "f":
        return float(x.strip()) if isinstance(x, str) else float(x)
    if kind == "s":
        if isinstance(x, (bool, np.bool_)):
            # the reference renders CAST(bool AS STRING) as '0'/'1'
            # (data_type/type_conversion/between.slt pins it)
            return "1" if x else "0"
        if isinstance(x, (float, np.floating)):
            return repr(float(x))
        if isinstance(x, (int, np.integer)):
            return str(int(x))
        return str(x)
    if kind == "b":
        if isinstance(x, str):
            return _parse_bool_str(x)
        return bool(x != 0) if not isinstance(x, (bool, np.bool_)) else bool(x)
    raise ValueError(f"unknown cast kind {kind}")


_CAST_KINDS = {"BIGINT": "i", "INT": "i", "INTEGER": "i",
               "BIGINT UNSIGNED": "u", "UNSIGNED": "u",
               "DOUBLE": "f", "FLOAT": "f",
               "STRING": "s", "VARCHAR": "s", "TEXT": "s",
               "BOOLEAN": "b", "BOOL": "b", "TIMESTAMP": "t",
               "CHAR": "s", "INTERVAL": "v"}


def iter_child_exprs(e):
    """Every direct child Expr of a node — the ONE traversal helper all
    tree walks share (attr children, Func args, CASE arms)."""
    for attr in ("left", "right", "operand", "expr", "low", "high",
                 "else_", "pattern"):
        c = getattr(e, attr, None)
        if isinstance(c, Expr):
            yield c
    for a in getattr(e, "args", None) or []:
        if isinstance(a, Expr):
            yield a
    for c, r in getattr(e, "whens", None) or []:
        if isinstance(c, Expr):
            yield c
        if isinstance(r, Expr):
            yield r


def _column_null_mask(col: str, env: dict, xp):
    """Per-row NULL mask for a column in env, from its validity mask when
    present, else from the value representation (None in object arrays,
    NaN in float columns). None when the column can't be resolved."""
    key = f"__valid__:{col}"
    if key in env:
        return ~np.asarray(env[key], dtype=bool)
    v = env.get(col)
    if v is None:
        return None
    if isinstance(v, DictArray):
        return None   # dictionary columns have no NULL holes
    dt = getattr(v, "dtype", None)
    if dt is None:
        return None
    if dt == object:
        return np.array([x is None or (isinstance(x, float) and x != x)
                         for x in v], dtype=bool)
    if dt.kind == "f":
        return xp.isnan(v)
    return None


def propagating_columns(e) -> set:
    """Columns whose NULLs propagate to this expression's result — i.e.
    every referenced column EXCEPT those only seen inside NULL-aware nodes
    (IS NULL, CASE), which define their own NULL behavior. The executor's
    blanket NULL-out mask uses this instead of columns() so
    `CASE WHEN i IS NULL THEN -1 ...` can map NULL to a value."""
    if isinstance(e, (IsNull, Case, IsDistinct, IsBool, KeyInSet,
                      CorrExists)):
        # NULL-defining nodes: their result is never NULL regardless of
        # input NULLs
        return set()
    if not isinstance(e, Expr):
        return set()
    out = set()
    if isinstance(e, Column):
        out.add(e.name)
    for attr in ("left", "right", "operand", "expr", "low", "high"):
        child = getattr(e, attr, None)
        if isinstance(child, Expr):
            out |= propagating_columns(child)
    for a in getattr(e, "args", None) or []:
        out |= propagating_columns(a)
    return out


@dataclass(repr=False)
class Case(Expr):
    """CASE [operand] WHEN cond/value THEN result ... [ELSE d] END
    (reference: DataFusion Expr::Case). First matching arm wins; no match
    and no ELSE → NULL. NULL conditions/operands never match (3VL)."""

    operand: Expr | None
    whens: list            # [(cond_or_value, result_expr)]
    else_: Expr | None = None

    @staticmethod
    def _env_invalid(e, env, n):
        """Rows where any NULL-propagating column of `e` is invalid."""
        invalid = None
        for c in propagating_columns(e):
            key = f"__valid__:{c}"
            if key in env and len(env[key]) == n:
                bad = ~np.asarray(env[key], dtype=bool)
                invalid = bad if invalid is None else (invalid | bad)
        return invalid

    def _conds(self, env, xp, n):
        base = self.operand.eval(env, xp) if self.operand is not None \
            else None
        base_bad = (self._env_invalid(self.operand, env, n)
                    if self.operand is not None else None)
        for cond, _ in self.whens:
            if self.operand is not None:
                m = _eq(xp, base, cond.eval(env, xp))
                cond_bad = self._env_invalid(cond, env, n)
            else:
                m = cond.eval(env, xp)
                cond_bad = self._env_invalid(cond, env, n)
            m = np.asarray(m)
            if m.dtype == object:
                m = np.array([bool(x) if x is not None else False
                              for x in m], dtype=bool)
            elif m.dtype.kind == "f":
                m = ~np.isnan(m) & (m != 0)
            else:
                m = m.astype(bool)
            if not m.shape:
                m = np.full(n, bool(m))
            # 3VL: a NULL operand or NULL in the condition's propagating
            # columns never matches (typed NULL slots carry garbage)
            for bad in (base_bad, cond_bad):
                if bad is not None:
                    m = m & ~bad
            yield m

    def _arm_values(self, e, env, xp, n, pick):
        """Values of one arm for the picked rows. Full-vector eval when it
        succeeds; an arm that errors on rows its WHEN excludes (CAST over
        a guarded Inf row) re-evaluates on the picked subset only."""
        def vec(e_, env_, n_):
            if e_ is None:
                return np.full(n_, None, dtype=object)
            v = e_.eval(env_, xp)
            v = np.asarray(v.materialize() if hasattr(v, "materialize")
                           else v)
            if not v.shape:
                v = np.full(n_, v[()])
            if v.dtype != object:
                v = v.astype(object)
            vf = v.copy()
            nanm = [isinstance(x, float) and x != x for x in vf]
            if any(nanm):
                vf[nanm] = None
            bad = self._env_invalid(e_, env_, n_)
            if bad is not None and bad.any():
                vf[bad] = None
            return vf

        try:
            return vec(e, env, n)[pick]
        except Exception:
            if pick.all():
                raise
            k = int(pick.sum())
            sub = {key: (v[pick] if hasattr(v, "__len__")
                         and not isinstance(v, (str, bytes))
                         and len(v) == n else v)
                   for key, v in env.items()}
            return vec(e, sub, k)

    def eval(self, env, xp):
        # row count from any column in scope (scalar-only CASE gets n=1)
        n = 1
        for vv in env.values():
            if hasattr(vv, "__len__") and not isinstance(vv, (str, bytes)):
                n = len(vv)
                break
        result = np.full(n, None, dtype=object)
        taken = np.zeros(n, dtype=bool)
        for m, (_, res) in zip(self._conds(env, xp, n), self.whens):
            pick = m & ~taken
            taken |= m
            if pick.any():
                result[pick] = self._arm_values(res, env, xp, n, pick)
        rest = ~taken
        if self.else_ is not None and rest.any():
            result[rest] = self._arm_values(self.else_, env, xp, n, rest)
        # downcast homogeneous results so renders stay native (5 not 5.0)
        vals = [x for x in result if x is not None]
        if vals and len(vals) == n:
            if all(isinstance(x, (bool, np.bool_)) for x in vals):
                return np.array([bool(x) for x in result])
            if all(isinstance(x, (int, np.integer))
                   and not isinstance(x, (bool, np.bool_)) for x in vals):
                return np.array([int(x) for x in result], dtype=np.int64)
            if all(isinstance(x, (float, np.floating)) for x in vals):
                return np.array([float(x) for x in result])
        return result

    def columns(self):
        out = set()
        if self.operand is not None:
            out |= self.operand.columns()
        for c, r in self.whens:
            out |= c.columns() | r.columns()
        if self.else_ is not None:
            out |= self.else_.columns()
        return out

    def to_sql(self):
        parts = ["CASE"]
        if self.operand is not None:
            parts.append(self.operand.to_sql())
        for c, r in self.whens:
            parts.append(f"WHEN {c.to_sql()} THEN {r.to_sql()}")
        if self.else_ is not None:
            parts.append(f"ELSE {self.else_.to_sql()}")
        parts.append("END")
        return " ".join(parts)


@dataclass(repr=False)
class Cast(Expr):
    """CAST(expr AS type) / TRY_CAST (NULL instead of error) — reference
    inherits DataFusion's cast kernels; semantics here follow them:
    float→int truncates toward zero, NaN/Inf→int errors, bool→'true'."""

    expr: Expr
    target: str
    safe: bool = False

    def eval(self, env, xp):
        kind = _CAST_KINDS.get(self.target.upper())
        if kind is None:
            raise PlanError(f"unknown CAST target {self.target!r}")
        v = self.expr.eval(env, xp)
        if v is None:
            return None
        if isinstance(v, DictArray):
            def cast_u(x):
                try:
                    return _cast_scalar(x, kind)
                except (ValueError, OverflowError) as e:
                    if self.safe:
                        return None
                    raise PlanError(f"CAST failed: {e}")
            return v.map_values(cast_u)
        if isinstance(v, np.ndarray) and v.dtype != object:
            # NULL slots of a typed column carry garbage values — they
            # must neither abort a strict CAST nor poison TRY_CAST
            vm = None
            if isinstance(self.expr, Column):
                vm = env.get(f"__valid__:{self.expr.name}")
            if kind in ("i", "t", "u"):
                bad = (~np.isfinite(v) if v.dtype.kind == "f"
                       else np.zeros(len(v), dtype=bool))
                if kind == "u":
                    bad = bad | (np.asarray(v, dtype=np.float64) < 0)
                relevant = bad if vm is None else (bad & vm)
                if relevant.any() and not self.safe:
                    raise PlanError(
                        "CAST failed: NaN/Inf/negative to integer")
                vsafe = np.where(bad, 0, v)
                tgt = np.uint64 if kind == "u" else np.int64
                out_i = (np.trunc(vsafe) if v.dtype.kind == "f"
                         else vsafe).astype(tgt)
                if relevant.any():
                    # TRY_CAST is per-element: only failed slots go NULL
                    out = out_i.astype(object)
                    out[relevant] = None
                    return out
                return out_i
            if kind == "f":
                return v.astype(np.float64)
            if kind == "b":
                return v != 0
            out = np.empty(len(v), dtype=object)
            out[:] = [_cast_scalar(x, "s") for x in v.tolist()]
            return out
        if isinstance(v, np.ndarray):   # object (string) column
            out = np.empty(len(v), dtype=object)
            vals = []
            for x in v:
                if x is None:
                    vals.append(None)
                    continue
                try:
                    vals.append(_cast_scalar(x, kind))
                except (ValueError, OverflowError) as e:
                    if self.safe:
                        vals.append(None)
                    else:
                        raise PlanError(f"CAST failed: {e}")
            out[:] = vals
            return out
        try:
            return _cast_scalar(v, kind)
        except (ValueError, OverflowError) as e:
            if self.safe:
                return None
            raise PlanError(f"CAST failed: {e}")

    def columns(self):
        return self.expr.columns()

    def to_sql(self):
        fn = "TRY_CAST" if self.safe else "CAST"
        return f"{fn}({self.expr.to_sql()} AS {self.target})"


@dataclass(repr=False)
class Subquery(Expr):
    """Uncorrelated scalar subquery — the executor resolves it to a Literal
    before evaluation (reference gets these via DataFusion's subquery
    decorrelation; we support the uncorrelated forms)."""

    select: object   # ast.SelectStmt | ast.UnionStmt

    def eval(self, env, xp):
        raise PlanError("unresolved scalar subquery (executor must resolve)")

    def columns(self):
        return set()

    def to_sql(self):
        return "(<subquery>)"


@dataclass(repr=False)
class InSubquery(Expr):
    """expr [NOT] IN (SELECT ...) — resolved to an InList by the executor."""

    expr: Expr
    select: object
    negated: bool = False

    def eval(self, env, xp):
        raise PlanError("unresolved IN subquery (executor must resolve)")

    def columns(self):
        return self.expr.columns()

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        return f"({self.expr.to_sql()}{neg} IN (<subquery>))"


@dataclass(repr=False)
class Exists(Expr):
    """[NOT] EXISTS (SELECT ...) — resolved to a boolean literal by the
    executor (uncorrelated, like InSubquery; reference: DataFusion's
    scalar-subquery decorrelation handles the same class)."""

    select: object
    negated: bool = False

    def eval(self, env, xp):
        raise PlanError("unresolved EXISTS subquery (executor must resolve)")

    def columns(self):
        return set()

    def to_sql(self):
        neg = "NOT " if self.negated else ""
        return f"({neg}EXISTS (<subquery>))"


def _rows_of(v, n):
    """Per-row python values for an eval() result: scalars broadcast,
    np scalars unwrap (so tuple hashing matches the python values the
    inner query produced), NaN normalizes to None (NULL semantics)."""
    if isinstance(v, DictArray):
        v = v.materialize()
    if isinstance(v, np.ndarray):
        out = []
        for x in v.tolist() if v.dtype != object else v:
            if isinstance(x, float) and x != x:
                out.append(None)
            elif isinstance(x, np.generic):
                out.append(x.item())
            else:
                out.append(x)
        return out
    if isinstance(v, np.generic):
        v = v.item()
    if isinstance(v, float) and v != v:
        v = None
    return [v] * n


def _env_rows(env: dict) -> int:
    for val in env.values():
        if isinstance(val, (np.ndarray, DictArray)):
            return len(val)
    return 1


_SCALAR_DUP = object()   # sentinel: correlation key had >1 inner row


@dataclass(repr=False)
class CorrLookup(Expr):
    """Decorrelated correlated SCALAR subquery: per row, the correlation
    key exprs (`args`) evaluate and the tuple maps through `mapping`
    (built by grouping the inner query by its correlation columns);
    missing keys — including NULL key components, which can never equal
    anything — yield `default` (0 for COUNT bodies, else NULL).
    Reference surface: DataFusion's scalar_subquery_to_join
    (query_server/query/src/sql/logical/optimizer.rs:66-108)."""

    args: list
    mapping: dict
    default: object = None

    def eval(self, env, xp):
        n = _env_rows(env)
        cols = [_rows_of(a.eval(env, xp), n) for a in self.args]
        out = np.empty(n, dtype=object)
        for i in range(n):
            key = tuple(c[i] for c in cols)
            if any(k is None for k in key):
                out[i] = self.default
                continue
            v = self.mapping.get(key, self.default)
            if v is _SCALAR_DUP:
                raise PlanError(
                    "scalar subquery must return a single value")
            out[i] = v
        return out

    def columns(self):
        s = set()
        for a in self.args:
            s |= a.columns()
        return s

    def to_sql(self):
        return "(<correlated scalar subquery>)"


@dataclass(repr=False)
class CorrIn(Expr):
    """Decorrelated correlated IN subquery: `probe [NOT] IN (SELECT v
    FROM .. WHERE inner_k = outer_k ..)`. args = [probe, *outer_keys];
    `pairs` holds (key.., v) tuples from the inner query, `keyed` the
    correlation keys with any row, `null_keys` those whose value set
    contained NULL. Three-valued logic folds to a filter mask: UNKNOWN
    rows (NULL probe against a non-empty set, or a miss against a set
    containing NULL) never match, for IN and NOT IN alike."""

    args: list
    pairs: set
    keyed: set
    null_keys: set
    negated: bool = False

    def eval(self, env, xp):
        n = _env_rows(env)
        cols = [_rows_of(a.eval(env, xp), n) for a in self.args]
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            probe = cols[0][i]
            key = tuple(c[i] for c in cols[1:])
            if any(k is None for k in key) or key not in self.keyed:
                res = False          # empty set: IN false, NOT IN true
            elif probe is None:
                res = None
            elif key + (probe,) in self.pairs:
                res = True
            elif key in self.null_keys:
                res = None
            else:
                res = False
            if res is None:
                out[i] = False       # UNKNOWN excludes under both forms
            else:
                out[i] = (not res) if self.negated else res
        return out

    def columns(self):
        s = set()
        for a in self.args:
            s |= a.columns()
        return s

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        return f"({self.args[0].to_sql()}{neg} IN (<correlated subquery>))"


def _tri_rows(e, env, xp, n):
    """Row values of an expression with 3VL NULL recovered for PREDICATE
    subtrees: a boolean expr is NULL where neither its true mask nor its
    definite-false mask holds (x NOT IN (...) over NULL x is NULL — both
    IS DISTINCT FROM and IS TRUE/FALSE observe that)."""
    v = e.eval(env, xp)
    rows = _rows_of(v, n)
    is_boolish = (isinstance(v, np.ndarray) and v.dtype == bool) \
        or isinstance(v, (bool, np.bool_))
    if is_boolish and xp is np:
        f = _eval_false_mask(e, env, xp)
        if isinstance(f, np.ndarray):
            fr = _rows_of(f, n)
            rows = [None if (not t) and (not fl) else t
                    for t, fl in zip(rows, fr)]
    return rows


@dataclass(repr=False)
class IsDistinct(Expr):
    """x IS [NOT] DISTINCT FROM y — NULL-safe comparison (two NULLs are
    NOT distinct; a NULL vs a value is)."""

    left: Expr
    right: Expr
    negated: bool = False   # negated == IS NOT DISTINCT FROM

    def eval(self, env, xp):
        n = _env_rows(env)
        ar = _tri_rows(self.left, env, xp, n)
        br = _tri_rows(self.right, env, xp, n)
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            x, y = ar[i], br[i]
            if x is None or y is None:
                distinct = (x is None) != (y is None)
            else:
                try:
                    distinct = not (x == y)
                except TypeError:
                    distinct = True
            out[i] = (not distinct) if self.negated else distinct
        return out

    def columns(self):
        return self.left.columns() | self.right.columns()

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        return (f"({self.left.to_sql()} IS{neg} DISTINCT FROM "
                f"{self.right.to_sql()})")


@dataclass(repr=False)
class IsBool(Expr):
    """x IS [NOT] TRUE/FALSE (sqlancer): NULL inputs are not the target
    (so IS NOT TRUE keeps NULL rows)."""

    expr: Expr
    target: bool
    negated: bool = False

    def eval(self, env, xp):
        n = _env_rows(env)
        rows = _tri_rows(self.expr, env, xp, n)
        out = np.zeros(n, dtype=bool)
        for i, x in enumerate(rows):
            m = (x is not None) and bool(x) == self.target
            out[i] = (not m) if self.negated else m
        return out

    def columns(self):
        return self.expr.columns()

    def to_sql(self):
        neg = " NOT" if self.negated else ""
        t = "TRUE" if self.target else "FALSE"
        return f"({self.expr.to_sql()} IS{neg} {t})"


@dataclass(repr=False)
class CorrExists(Expr):
    """Generalized decorrelated EXISTS: equality conjuncts hash-partition
    the inner rows; remaining cross-correlation conjuncts (inner col vs
    outer col, e.g. tpch q21's l2.l_suppkey <> l1.l_suppkey) evaluate
    per (outer row, inner candidate). args = eq outer key exprs followed
    by the outer column exprs the cross conjuncts reference."""

    args: list
    n_eq: int
    outer_names: list        # env names for args[n_eq:] in cross conjs
    inner_rows: dict         # eq key tuple → list of {inner name: value}
    cross: list              # conjunct Exprs over inner + outer names
    negated: bool = False

    def eval(self, env, xp):
        n = _env_rows(env)
        cols = [_rows_of(a.eval(env, xp), n) for a in self.args]
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            key = tuple(c[i] for c in cols[:self.n_eq])
            found = False
            if not any(k is None for k in key):
                outer_env = {nm: cols[self.n_eq + j][i]
                             for j, nm in enumerate(self.outer_names)}
                for aux in self.inner_rows.get(key, ()):
                    cenv = {**aux, **outer_env}
                    ok = True
                    for cj in self.cross:
                        r = cj.eval(cenv, np)
                        if isinstance(r, np.ndarray):
                            r = bool(r.all()) if r.size else False
                        if not bool(r):
                            ok = False
                            break
                    if ok:
                        found = True
                        break
            out[i] = (not found) if self.negated else found
        return out

    def columns(self):
        s = set()
        for a in self.args:
            s |= a.columns()
        return s

    def to_sql(self):
        neg = "NOT " if self.negated else ""
        return f"({neg}EXISTS (<correlated subquery>))"


@dataclass(repr=False)
class KeyInSet(Expr):
    """Decorrelated multi-conjunct EXISTS: membership of the outer
    correlation key tuple in the inner key set. A NULL key component
    matches nothing (EXISTS false → NOT EXISTS keeps the row, the
    anti-join rule)."""

    args: list
    keys: set
    negated: bool = False

    def eval(self, env, xp):
        n = _env_rows(env)
        cols = [_rows_of(a.eval(env, xp), n) for a in self.args]
        out = np.zeros(n, dtype=bool)
        for i in range(n):
            key = tuple(c[i] for c in cols)
            m = (not any(k is None for k in key)) and key in self.keys
            out[i] = (not m) if self.negated else m
        return out

    def columns(self):
        s = set()
        for a in self.args:
            s |= a.columns()
        return s

    def to_sql(self):
        neg = "NOT " if self.negated else ""
        return f"({neg}EXISTS (<correlated subquery>))"


@dataclass(repr=False)
class WindowFunc(Expr):
    """fn(args) OVER (PARTITION BY ... ORDER BY ...) — evaluated by the
    relational executor over the post-filter row set; generic eval is
    invalid because window semantics need whole-partition context."""

    name: str
    args: list
    partition_by: list = None    # list[Expr]
    order_by: list = None        # list[(Expr, asc)]
    # frame: None (default: cumulative when ordered, whole partition
    # otherwise) | 'full' | 'cum' | 'rev' (CURRENT ROW → UNBOUNDED
    # FOLLOWING) — the ROWS BETWEEN shapes the reference corpus uses
    frame: str | None = None

    def eval(self, env, xp):
        raise PlanError(
            f"window function {self.name} outside relational context")

    def columns(self):
        out = set()
        for a in self.args:
            out |= a.columns()
        for e in (self.partition_by or []):
            out |= e.columns()
        for e, _ in (self.order_by or []):
            out |= e.columns()
        return out

    def to_sql(self):
        parts = []
        if self.partition_by:
            parts.append("PARTITION BY "
                         + ", ".join(e.to_sql() for e in self.partition_by))
        if self.order_by:
            parts.append("ORDER BY " + ", ".join(
                e.to_sql() + ("" if asc else " DESC")
                for e, asc in self.order_by))
        return (f"{self.name}({', '.join(a.to_sql() for a in self.args)}) "
                f"OVER ({' '.join(parts)})")


# ---------------------------------------------------------------------------
# domain extraction (predicate pushdown)
# ---------------------------------------------------------------------------
def extract_domains(expr: Expr | None, columns: set[str]) -> ColumnDomains:
    """Sound over-approximation of `expr` restricted to `columns` — used to
    push tag/time constraints into the index and file pruning (reference
    predicate::domain push_down_filter). Rows outside the returned domains
    can never satisfy expr; the full expr is still re-checked at execution.
    """
    if expr is None:
        return ColumnDomains.all()
    return _extract(expr, columns)


def _extract(e: Expr, cols: set[str]) -> ColumnDomains:
    if isinstance(e, BinOp):
        if e.op == "and":
            return _extract(e.left, cols).intersect(_extract(e.right, cols))
        if e.op == "or":
            return _extract(e.left, cols).union(_extract(e.right, cols))
        if e.op in ("=", "<", "<=", ">", ">="):
            col, lit, op = _col_lit(e)
            if col is not None and col in cols:
                dom = {
                    "=": lambda v: SetDomain([v]),
                    "<": RangeDomain.lt, "<=": RangeDomain.le,
                    ">": RangeDomain.gt, ">=": RangeDomain.ge,
                }[op](lit)
                return ColumnDomains.of(col, dom)
        return ColumnDomains.all()
    if isinstance(e, InList) and not e.negated and isinstance(e.expr, Column):
        if e.expr.name in cols:
            return ColumnDomains.of(e.expr.name, SetDomain(e.values))
        return ColumnDomains.all()
    if isinstance(e, Between) and not e.negated and isinstance(e.expr, Column):
        if (e.expr.name in cols and isinstance(e.low, Literal)
                and isinstance(e.high, Literal)):
            return ColumnDomains.of(
                e.expr.name,
                RangeDomain.of(low=e.low.value, high=e.high.value))
        return ColumnDomains.all()
    if (isinstance(e, Like) and not e.negated
            and isinstance(e.expr, Column) and isinstance(e.pattern, str)
            and e.expr.name in cols):
        if "%" not in e.pattern and "_" not in e.pattern:
            # wildcard-free LIKE is equality — plus the $-accepts-a-
            # trailing-newline quirk of the host automaton
            return ColumnDomains.of(
                e.expr.name, SetDomain([e.pattern, e.pattern + "\n"]))
        return ColumnDomains.of(e.expr.name, LikeDomain(e.pattern))
    return ColumnDomains.all()


def _col_lit(e: BinOp):
    """Normalize col-op-literal / literal-op-col → (col, lit, op)."""
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    if isinstance(e.left, Column) and isinstance(e.right, Literal):
        return e.left.name, e.right.value, e.op
    if isinstance(e.left, Literal) and isinstance(e.right, Column):
        return e.right.name, e.left.value, flip[e.op]
    return None, None, None


_register_tsfuncs()
