"""GIS scalar functions: WKT/WKB codecs + planar predicates.

Role-parity with the reference's gis scalar set
(query_server/query/src/extension/expr/scalar_function/gis/:
st_asbinary.rs, st_geomfromwkb.rs, st_binary_op.rs wrapping the geo
crate). Geometries are WKT strings in the engine (GEOMETRY columns store
WKT); st_AsBinary produces standard little-endian WKB bytes, rendered as
lowercase hex; ST_GeomFromWKB parses WKB back to CANONICAL WKT (no space
after the tag, comma-separated coordinates — the geo-types Display the
reference shows in st_geomfromwkb.slt).

Predicates (contains/within/intersects/disjoint/equals) are exact planar
computational geometry over point/linestring/polygon and the multi
variants: point-in-polygon by ray casting (concave rings supported),
segment-pair intersection tests, containment = all-points-inside with no
boundary crossings.
"""
from __future__ import annotations

import re
import struct

from ..errors import PlanError

_TYPES = ("POINT", "LINESTRING", "POLYGON", "MULTIPOINT",
          "MULTILINESTRING", "MULTIPOLYGON", "GEOMETRYCOLLECTION")
_WKB_CODE = {t: i + 1 for i, t in enumerate(_TYPES)}
_WKB_TYPE = {v: k for k, v in _WKB_CODE.items()}


# ---------------------------------------------------------------- WKT
class Geom:
    """(kind, data): POINT → (x, y) | None for EMPTY;
    LINESTRING → [pts]; POLYGON → [rings][pts];
    MULTIPOINT → [pts]; MULTILINESTRING → [[pts]];
    MULTIPOLYGON → [[[pts]]]; GEOMETRYCOLLECTION → [Geom]."""

    __slots__ = ("kind", "data")

    def __init__(self, kind, data):
        self.kind = kind
        self.data = data


def parse_wkt(s: str) -> Geom:
    if not isinstance(s, str):
        raise PlanError("GIS functions take WKT strings")
    text = s.strip()
    g, rest = _parse_geom(text)
    if rest.strip():
        raise PlanError(f"trailing WKT content: {rest[:20]!r}")
    return g


def _parse_geom(text: str):
    m = re.match(r"\s*([A-Za-z]+)\s*", text)
    if not m or m.group(1).upper() not in _TYPES:
        raise PlanError(f"bad WKT: {text[:30]!r}")
    kind = m.group(1).upper()
    rest = text[m.end():]
    if rest.upper().startswith("EMPTY"):
        empty = {"POINT": None, "LINESTRING": [], "POLYGON": [],
                 "MULTIPOINT": [], "MULTILINESTRING": [],
                 "MULTIPOLYGON": [], "GEOMETRYCOLLECTION": []}[kind]
        return Geom(kind, empty), rest[5:]
    body, rest = _take_parens(rest)
    if kind == "POINT":
        return Geom(kind, _coord(body)), rest
    if kind == "LINESTRING":
        return Geom(kind, _coords(body)), rest
    if kind == "POLYGON":
        return Geom(kind, [_coords(r) for r in _split_groups(body)]), rest
    if kind == "MULTIPOINT":
        # both MULTIPOINT((1 2),(3 4)) and MULTIPOINT(1 2, 3 4)
        groups = _split_top(body)
        pts = []
        for gtxt in groups:
            gtxt = gtxt.strip()
            if gtxt.startswith("("):
                inner, _ = _take_parens(gtxt)
                pts.append(_coord(inner))
            else:
                pts.append(_coord(gtxt))
        return Geom(kind, pts), rest
    if kind == "MULTILINESTRING":
        return Geom(kind, [_coords(g) for g in _split_groups(body)]), rest
    if kind == "MULTIPOLYGON":
        polys = []
        for gtxt in _split_top(body):
            inner, _ = _take_parens(gtxt.strip())
            polys.append([_coords(r) for r in _split_groups(inner)])
        return Geom(kind, polys), rest
    # GEOMETRYCOLLECTION
    out = []
    txt = body
    while txt.strip():
        g, txt = _parse_geom(txt)
        out.append(g)
        txt = txt.lstrip()
        if txt.startswith(","):
            txt = txt[1:]
    return Geom(kind, out), rest


def _take_parens(text: str):
    text = text.lstrip()
    if not text.startswith("("):
        raise PlanError(f"bad WKT near {text[:20]!r}")
    depth = 0
    for i, ch in enumerate(text):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return text[1:i], text[i + 1:]
    raise PlanError("unbalanced WKT parentheses")


def _split_top(body: str) -> list[str]:
    out, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _split_groups(body: str) -> list[str]:
    return [_take_parens(g.strip())[0] for g in _split_top(body)]


def _coord(txt: str):
    parts = txt.split()
    if len(parts) < 2:
        raise PlanError(f"bad WKT coordinate {txt!r}")
    return (float(parts[0]), float(parts[1]))


def _coords(txt: str):
    return [_coord(c) for c in _split_top(txt)]


def _num(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def to_wkt(g: Geom) -> str:
    """Canonical rendering (geo-types Display: no space after the tag)."""
    k, d = g.kind, g.data
    if k == "POINT":
        if d is None:
            return "POINT EMPTY"
        return f"POINT({_num(d[0])} {_num(d[1])})"
    if d in ([], None):
        return f"{k} EMPTY"
    if k == "LINESTRING":
        return "LINESTRING(" + _pts(d) + ")"
    if k == "POLYGON":
        return "POLYGON(" + ",".join(f"({_pts(r)})" for r in d) + ")"
    if k == "MULTIPOINT":
        return "MULTIPOINT(" + _pts(d) + ")"
    if k == "MULTILINESTRING":
        return "MULTILINESTRING(" + ",".join(
            f"({_pts(ln)})" for ln in d) + ")"
    if k == "MULTIPOLYGON":
        return "MULTIPOLYGON(" + ",".join(
            "(" + ",".join(f"({_pts(r)})" for r in poly) + ")"
            for poly in d) + ")"
    return "GEOMETRYCOLLECTION(" + ",".join(to_wkt(x) for x in d) + ")"


def _pts(pts) -> str:
    return ",".join(f"{_num(x)} {_num(y)}" for x, y in pts)


# ---------------------------------------------------------------- WKB
def _wkb_geom(g: Geom) -> bytes:
    code = _WKB_CODE[g.kind]
    head = struct.pack("<BI", 1, code)
    k, d = g.kind, g.data
    if k == "POINT":
        if d is None:
            return head + struct.pack("<dd", float("nan"), float("nan"))
        return head + struct.pack("<dd", *d)
    if k == "LINESTRING":
        return head + _wkb_ring(d)
    if k == "POLYGON":
        return head + struct.pack("<I", len(d)) + b"".join(
            _wkb_ring(r) for r in d)
    if k == "MULTIPOINT":
        return head + struct.pack("<I", len(d)) + b"".join(
            _wkb_geom(Geom("POINT", p)) for p in d)
    if k == "MULTILINESTRING":
        return head + struct.pack("<I", len(d)) + b"".join(
            _wkb_geom(Geom("LINESTRING", ln)) for ln in d)
    if k == "MULTIPOLYGON":
        return head + struct.pack("<I", len(d)) + b"".join(
            _wkb_geom(Geom("POLYGON", poly)) for poly in d)
    return head + struct.pack("<I", len(d)) + b"".join(
        _wkb_geom(x) for x in d)


def _wkb_ring(pts) -> bytes:
    return struct.pack("<I", len(pts)) + b"".join(
        struct.pack("<dd", x, y) for x, y in pts)


def _read_geom(buf: bytes, off: int):
    if off + 5 > len(buf):
        raise PlanError("truncated WKB")
    order = buf[off]
    fmt = "<" if order == 1 else ">"
    code, = struct.unpack_from(fmt + "I", buf, off + 1)
    kind = _WKB_TYPE.get(code)
    if kind is None:
        raise PlanError(f"unknown WKB geometry code {code}")
    off += 5

    def read_pt(o):
        x, y = struct.unpack_from(fmt + "dd", buf, o)
        return (x, y), o + 16

    def read_count(o):
        n, = struct.unpack_from(fmt + "I", buf, o)
        return n, o + 4

    if kind == "POINT":
        p, off = read_pt(off)
        if p[0] != p[0]:
            return Geom(kind, None), off
        return Geom(kind, p), off
    if kind == "LINESTRING":
        n, off = read_count(off)
        pts = []
        for _ in range(n):
            p, off = read_pt(off)
            pts.append(p)
        return Geom(kind, pts), off
    if kind == "POLYGON":
        n, off = read_count(off)
        rings = []
        for _ in range(n):
            m, off = read_count(off)
            pts = []
            for _ in range(m):
                p, off = read_pt(off)
                pts.append(p)
            rings.append(pts)
        return Geom(kind, rings), off
    n, off = read_count(off)
    subs = []
    for _ in range(n):
        sub, off = _read_geom(buf, off)
        subs.append(sub)
    if kind == "MULTIPOINT":
        return Geom(kind, [s.data for s in subs]), off
    if kind == "MULTILINESTRING":
        return Geom(kind, [s.data for s in subs]), off
    if kind == "MULTIPOLYGON":
        return Geom(kind, [s.data for s in subs]), off
    return Geom(kind, subs), off


def st_asbinary(wkt) -> bytes | None:
    """Unparseable input yields NULL, not an error (reference
    st_asbinary.slt: st_AsBinary('POINT(0, 0)') → NULL)."""
    if wkt is None:
        return None
    try:
        return _wkb_geom(parse_wkt(str(wkt)))
    except Exception:
        return None


def st_geomfromwkb(data) -> str | None:
    if data is None:
        return None
    if not isinstance(data, (bytes, bytearray)):
        raise PlanError(
            "st_GeomFromWKB expects Binary input (st_AsBinary output)")
    g, off = _read_geom(bytes(data), 0)
    if off != len(data):
        raise PlanError("trailing WKB bytes")
    return to_wkt(g)


def _ring_area(pts) -> float:
    if len(pts) < 3:
        return 0.0
    s = 0.0
    for i in range(len(pts)):
        x1, y1 = pts[i]
        x2, y2 = pts[(i + 1) % len(pts)]
        s += x1 * y2 - x2 * y1
    return abs(s) / 2.0


def st_area_geom(g: Geom) -> float:
    """Unsigned planar area (geo crate unsigned_area): outer rings minus
    holes, multipolygons summed; 0 for points/lines. An EMPTY POINT is an
    error (geo: 'The input was an empty Point, but the output doesn't
    support empty Points')."""
    if g.kind == "POINT" and g.data is None:
        raise PlanError("the input was an empty Point")
    total = 0.0
    for rings in _polys(g):
        if rings:
            total += _ring_area(rings[0])
            for hole in rings[1:]:
                total -= _ring_area(hole)
    return total


# ------------------------------------------------------ predicates
def _seg_intersect(p1, p2, p3, p4) -> bool:
    """Closed-segment intersection (touching counts)."""
    def orient(a, b, c):
        v = (b[0] - a[0]) * (c[1] - a[1]) - (b[1] - a[1]) * (c[0] - a[0])
        return 0 if v == 0 else (1 if v > 0 else -1)

    def on_seg(a, b, c):
        return (min(a[0], b[0]) <= c[0] <= max(a[0], b[0])
                and min(a[1], b[1]) <= c[1] <= max(a[1], b[1]))

    o1, o2 = orient(p1, p2, p3), orient(p1, p2, p4)
    o3, o4 = orient(p3, p4, p1), orient(p3, p4, p2)
    if o1 != o2 and o3 != o4:
        return True
    if o1 == 0 and on_seg(p1, p2, p3):
        return True
    if o2 == 0 and on_seg(p1, p2, p4):
        return True
    if o3 == 0 and on_seg(p3, p4, p1):
        return True
    return o4 == 0 and on_seg(p3, p4, p2)


def _pt_on_seg(p, a, b) -> bool:
    cross = (b[0] - a[0]) * (p[1] - a[1]) - (b[1] - a[1]) * (p[0] - a[0])
    if cross != 0:
        return False
    return (min(a[0], b[0]) <= p[0] <= max(a[0], b[0])
            and min(a[1], b[1]) <= p[1] <= max(a[1], b[1]))


def _pt_in_ring(p, ring) -> int:
    """2 = interior, 1 = boundary, 0 = outside (ray cast; concave ok)."""
    n = len(ring)
    if n == 0:
        return 0
    inside = False
    for i in range(n):
        a, b = ring[i], ring[(i + 1) % n]
        if _pt_on_seg(p, a, b):
            return 1
        if (a[1] > p[1]) != (b[1] > p[1]):
            xin = a[0] + (p[1] - a[1]) * (b[0] - a[0]) / (b[1] - a[1])
            if xin > p[0]:
                inside = not inside
    return 2 if inside else 0


def _pt_in_poly(p, rings) -> int:
    """2/1/0 against a polygon with holes."""
    if not rings:
        return 0
    r0 = _pt_in_ring(p, rings[0])
    if r0 != 2:
        return r0
    for hole in rings[1:]:
        h = _pt_in_ring(p, hole)
        if h == 2:
            return 0
        if h == 1:
            return 1
    return 2


def _segments(g: Geom):
    k, d = g.kind, g.data
    if k == "LINESTRING":
        yield from zip(d, d[1:])
    elif k == "POLYGON":
        for r in d:
            yield from zip(r, r[1:] + r[:1])
    elif k == "MULTILINESTRING":
        for ln in d:
            yield from zip(ln, ln[1:])
    elif k == "MULTIPOLYGON":
        for poly in d:
            for r in poly:
                yield from zip(r, r[1:] + r[:1])
    elif k == "GEOMETRYCOLLECTION":
        for sub in d:
            yield from _segments(sub)


def _points(g: Geom):
    k, d = g.kind, g.data
    if k == "POINT":
        if d is not None:
            yield d
    elif k in ("LINESTRING", "MULTIPOINT"):
        yield from d
    elif k in ("POLYGON", "MULTILINESTRING"):
        for part in d:
            yield from part
    elif k == "MULTIPOLYGON":
        for poly in d:
            for r in poly:
                yield from r
    else:
        for sub in d:
            yield from _points(sub)


def _polys(g: Geom):
    if g.kind == "POLYGON":
        yield g.data
    elif g.kind == "MULTIPOLYGON":
        yield from g.data
    elif g.kind == "GEOMETRYCOLLECTION":
        for sub in g.data:
            yield from _polys(sub)


def _pt_in_geom(p, g: Geom) -> int:
    """2 interior / 1 boundary / 0 outside for area geometries; for
    line/point geometries 1 = on, 0 = off."""
    best = 0
    for poly in _polys(g):
        best = max(best, _pt_in_poly(p, poly))
        if best == 2:
            return 2
    if g.kind in ("LINESTRING", "MULTILINESTRING",
                  "GEOMETRYCOLLECTION"):
        for a, b in _segments(g):
            if _pt_on_seg(p, a, b):
                return max(best, 1)
    if g.kind in ("POINT", "MULTIPOINT"):
        for q in _points(g):
            if q == p:
                return max(best, 1)
    return best


def st_intersects(w1, w2):
    if w1 is None or w2 is None:
        return None
    g1, g2 = parse_wkt(w1), parse_wkt(w2)
    if _is_empty(g1) or _is_empty(g2):
        return False
    for s1 in _segments(g1):
        for s2 in _segments(g2):
            if _seg_intersect(*s1, *s2):
                return True
    # containment without edge crossings (one inside the other), and
    # point-vs-geometry cases
    for p in _points(g1):
        if _pt_in_geom(p, g2):
            return True
    for p in _points(g2):
        if _pt_in_geom(p, g1):
            return True
    return False


def st_disjoint(w1, w2):
    r = st_intersects(w1, w2)
    return None if r is None else (not r)


def _is_empty(g: Geom) -> bool:
    if g.kind == "POINT":
        return g.data is None
    if g.kind == "GEOMETRYCOLLECTION":
        return all(_is_empty(x) for x in g.data) if g.data else True
    return not g.data


def _contains(outer: Geom, inner: Geom) -> bool:
    """Every point of `inner` inside `outer` (boundary allowed), and no
    inner edge crossing outer's boundary into the exterior (geo crate
    Contains: an EMPTY geometry is contained in nothing)."""
    if _is_empty(outer) or _is_empty(inner):
        return False
    pts = list(_points(inner))
    if not pts:
        return False
    interior_seen = False
    for p in pts:
        loc = _pt_in_geom(p, outer)
        if loc == 0:
            return False
        if loc == 2:
            interior_seen = True
    # midpoints guard concave boundaries: a segment between two inside
    # vertices can leave the polygon
    for a, b in _segments(inner):
        mid = ((a[0] + b[0]) / 2, (a[1] + b[1]) / 2)
        if _pt_in_geom(mid, outer) == 0:
            return False
        if _pt_in_geom(mid, outer) == 2:
            interior_seen = True
    outer_has_area = next(iter(_polys(outer)), None) is not None
    if not outer_has_area:
        # line outer: its BOUNDARY is the endpoint set (geo Contains
        # excludes it — a line does not contain its own endpoints)
        ends = _line_endpoints(outer)
        if inner.kind in ("POINT", "MULTIPOINT"):
            return all(p not in ends for p in pts)
        mids = [((a[0] + b[0]) / 2, (a[1] + b[1]) / 2)
                for a, b in _segments(inner)]
        return any(p not in ends for p in pts + mids)
    if inner.kind in ("POINT", "MULTIPOINT"):
        return interior_seen or all(
            _pt_in_geom(p, outer) >= 1 for p in pts)
    if not interior_seen:
        # boundary-coincident shapes (a polygon vs itself): test a
        # representative INTERIOR point of each inner polygon
        for poly in _polys(inner):
            rp = _rep_point(poly)
            if rp is not None:
                loc = _pt_in_geom(rp, outer)
                if loc == 0:
                    return False
                if loc == 2:
                    interior_seen = True
    return interior_seen


def _line_endpoints(g: Geom) -> set:
    """Boundary points of a line geometry: endpoints of each open
    linestring (closed rings have none)."""
    out = set()

    def add(pts):
        if len(pts) >= 2 and pts[0] != pts[-1]:
            out.add(pts[0])
            out.add(pts[-1])

    if g.kind == "LINESTRING":
        add(g.data)
    elif g.kind == "MULTILINESTRING":
        for ln in g.data:
            add(ln)
    elif g.kind == "GEOMETRYCOLLECTION":
        for sub in g.data:
            out |= _line_endpoints(sub)
    return out


def _rep_point(rings):
    """A point strictly inside a polygon (concave/holes tolerated by
    retrying candidate midpoints)."""
    ring = rings[0] if rings else []
    n = len(ring)
    if n == 0:
        return None
    cx = sum(p[0] for p in ring) / n
    cy = sum(p[1] for p in ring) / n
    if _pt_in_poly((cx, cy), rings) == 2:
        return (cx, cy)
    for i in range(n):
        for j in range(i + 2, n):
            mid = ((ring[i][0] + ring[j][0]) / 2,
                   (ring[i][1] + ring[j][1]) / 2)
            if _pt_in_poly(mid, rings) == 2:
                return mid
    return None


def st_contains(w1, w2):
    if w1 is None or w2 is None:
        return None
    return _contains(parse_wkt(w1), parse_wkt(w2))


def st_within(w1, w2):
    if w1 is None or w2 is None:
        return None
    return _contains(parse_wkt(w2), parse_wkt(w1))


def st_equals(w1, w2):
    """Topological equality approximated as mutual containment."""
    if w1 is None or w2 is None:
        return None
    g1, g2 = parse_wkt(w1), parse_wkt(w2)
    if _is_empty(g1) and _is_empty(g2):
        return True
    return _contains(g1, g2) and _contains(g2, g1)
