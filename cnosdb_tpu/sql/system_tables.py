"""System schemas: information_schema / cluster_schema / usage_schema.

Role-parity with the reference's metadata providers
(query_server/query/src/metadata/: information_schema_provider,
cluster_schema_provider, usage_schema_provider): virtual tables backed by
the meta store and engine stats, addressable as
`SELECT ... FROM information_schema.<table>`.
"""
from __future__ import annotations

import numpy as np

from ..errors import TableNotFound


def is_system_db_for(db: str, session) -> bool:
    """cluster_schema is only registered under the system default
    tenant — another tenant may own a REAL database of that name
    (dcl_tenant.slt: create database cluster_schema under tenant001)."""
    from ..parallel.meta import DEFAULT_TENANT

    if db == "cluster_schema":
        return session.tenant == DEFAULT_TENANT
    return db in ("information_schema", "usage_schema")


def _is_owner_view(meta, session) -> bool:
    """Instance admins and tenant owners see full catalog tables; plain
    members get filtered views (roles.slt, database_privileges.slt)."""
    u = meta.users.get(session.user)
    return (u is None or bool(u.get("admin"))
            or meta.check_db_privilege(session.user, session.tenant,
                                       "", "all"))


def system_table(executor, db: str, table: str, session) -> tuple[list[str], list]:
    meta = executor.meta
    engine = executor.coord.engine
    t = table.lower()
    if db == "information_schema":
        if t == "databases":
            rows = []
            for name in meta.list_databases(session.tenant):
                o = meta.database(session.tenant, name).options
                cfg = o.config
                rows.append((
                    session.tenant, name, o.ttl.humantime(), o.shard_num,
                    o.vnode_duration.humantime(), o.replica,
                    o.precision.name,
                    _size_str(cfg.get("max_memcache_size", "128 MiB")),
                    cfg.get("memcache_partitions", 16),
                    _size_str(cfg.get("wal_max_file_size", "128 MiB")),
                    bool(cfg.get("wal_sync", False)),
                    bool(cfg.get("strict_write", False)),
                    cfg.get("max_cache_readers", 32)))
            return _cols(["tenant_name", "database_name", "ttl", "shard",
                          "vnode_duration", "replica", "precision",
                          "max_memcache_size", "memcache_partitions",
                          "wal_max_file_size", "wal_sync", "strict_write",
                          "max_cache_readers"], rows)
        if t == "tables":
            # column set and values follow the reference
            # (information_schema_provider/builder/tables.rs: table_type
            # TABLE, engine TSKV/EXTERNAL/STREAM) — except table_options,
            # where the reference emits the literal 'TODO'; here each
            # engine's stored spec is rendered for real
            rows = []
            for dbn in meta.list_databases(session.tenant):
                owner = f"{session.tenant}.{dbn}"
                o = meta.database(session.tenant, dbn).options
                tskv_opts = _render_options({
                    "ttl": o.ttl.humantime(), "shard": o.shard_num,
                    "vnode_duration": o.vnode_duration.humantime(),
                    "replica": o.replica, "precision": o.precision.name})
                # tskv tables only — externals are listed below with
                # their own engine tag (list_tables merges both for
                # SHOW TABLES, which would double-list here)
                for tn in sorted(meta.tables.get(owner, {})):
                    rows.append((session.tenant, dbn, tn, "TABLE", "TSKV",
                                 tskv_opts))
                for tn, spec in sorted(getattr(meta, "externals", {})
                                       .get(owner, {}).items()):
                    rows.append((session.tenant, dbn, tn, "TABLE",
                                 "EXTERNAL", _render_options({
                                     "path": spec.get("path", ""),
                                     "format": spec.get("fmt", "csv"),
                                     "header": spec.get("header", True),
                                     **spec.get("options", {})})))
            for key, st in sorted(getattr(meta, "stream_tables",
                                          {}).items()):
                tenant, dbn, name = key.split(".", 2)
                if tenant != session.tenant:
                    continue
                rows.append((tenant, dbn, name, "TABLE", "STREAM",
                             _render_options({
                                 "db": st.get("db", ""),
                                 "table": st.get("table", ""),
                                 "event_time_column":
                                     st.get("event_time_column", "")})))
            return _cols(["table_tenant", "table_database", "table_name",
                          "table_type", "table_engine", "table_options"],
                         rows)
        if t == "columns":
            # reference column set (information_schema_provider/builder/
            # columns.rs): ordinal position, nullability, DESCRIBE-style
            # codec rendering (explicit NULL codec → SQL NULL)
            rows = []
            for dbn in meta.list_databases(session.tenant):
                for tn in sorted(meta.tables.get(
                        f"{session.tenant}.{dbn}", {})):
                    schema = meta.table(session.tenant, dbn, tn)
                    for pos, c in enumerate(schema.columns):
                        ct = c.column_type
                        kind = ("TIME" if ct.is_time else
                                "TAG" if ct.is_tag else "FIELD")
                        dtype = ("TIMESTAMP(NANOSECOND)" if ct.is_time
                                 else "STRING" if ct.is_tag
                                 else ct.value_type.sql_name())
                        codec = (None if c.encoding.name == "NULL"
                                 else (c.encoding.name
                                       if c.explicit_codec else "DEFAULT"))
                        rows.append((session.tenant, dbn, tn, c.name,
                                     kind, pos, None, not ct.is_time,
                                     dtype, codec))
            return _cols(["tenant_name", "database_name", "table_name",
                          "column_name", "column_type",
                          "ordinal_position", "column_default",
                          "is_nullable", "data_type",
                          "compression_codec"], rows)
        if t == "tenants":
            return _tenants_table(meta)
        if t == "users":
            return _users_table(meta)
        if t == "roles":
            # reference information_schema ROLES: per-tenant roles incl.
            # the system roles (role_name, role_type, inherit_role) —
            # visible only to instance admins and tenant OWNERS; other
            # members read it empty (dcl_role.slt, roles.slt)
            rows = []
            if _is_owner_view(meta, session):
                for name, spec in sorted(
                        meta.list_roles(session.tenant).items()):
                    system = name in ("owner", "member")
                    rows.append((name,
                                 "system" if system else "custom",
                                 None if system else spec.get("inherit")))
            return _cols(["role_name", "role_type", "inherit_role"], rows)
        if t == "members":
            rows = [(user, role) for user, role in sorted(
                meta.members.get(session.tenant, {}).items())]
            return _cols(["user_name", "role_name"], rows)
        if t == "queries":
            # live registry incl. the asking query itself (reference
            # QueryTracker view; query_type is 'batch' for SQL)
            import time as _t

            rows = []
            for qid, q in executor.tracker.snapshot():
                txt = q["sql"].strip()
                if not txt.endswith(";"):
                    txt += ";"
                rows.append((str(qid), "batch", txt, q["user"],
                             q.get("tenant", ""), q.get("db", ""),
                             "SCHEDULING",
                             round(_t.time() - q["start"], 6)))
            return _cols(["query_id", "query_type", "query_text",
                          "user_name", "tenant_name", "database_name",
                          "state", "duration"], rows)
        if t == "enabled_roles":
            # roles of the CURRENT session user in the current tenant
            role = meta.members.get(session.tenant,
                                    {}).get(session.user)
            rows = [(role,)] if role else []
            return _cols(["role_name"], rows)
        if t == "resource_status":
            # pending/applied resource ops from the recycle bin
            # (reference ResourceManager persists ops in meta;
            # resource_status.slt pins DropDatabase entries)
            rows = []
            for key in meta.trash.get("db", {}):
                tenant, dbn = key.split(".", 1)
                rows.append((0, f"{tenant}-{dbn}", "DropDatabase", 0,
                             "Successed", ""))
            for key in meta.trash.get("table", {}):
                parts = key.split(".", 2)
                rows.append((0, "-".join(parts), "DropTable", 0,
                             "Successed", ""))
            for name in meta.trash.get("tenant", {}):
                rows.append((0, name, "DropTenant", 0, "Successed", ""))
            return _cols(["time", "name", "action", "try_count",
                          "status", "comment"], rows)
        if t == "database_privileges":
            # admins and tenant owners see every grant; a plain member
            # sees only their OWN role's grants
            # (database_privileges.slt)
            rows = []
            owner_view = _is_owner_view(meta, session)
            own_role = meta.members.get(session.tenant,
                                        {}).get(session.user)
            for role, spec in meta.roles.get(session.tenant, {}).items():
                if not owner_view and role != own_role:
                    continue
                for dbn, lvl in (spec.get("privileges") or {}).items():
                    rows.append((session.tenant, dbn,
                                 lvl.capitalize(), role))
            return _cols(["tenant_name", "database_name",
                          "privilege_type", "role_name"], rows)
    if db == "cluster_schema":
        # the reference serves users/tenants from CLUSTER_SCHEMA
        # (metadata/cluster_schema_provider); keep them reachable from the
        # information_schema spelling too. The schema is only registered
        # under the system default tenant — any other tenant sees
        # table-not-found, and non-admin sessions read users EMPTY
        # (sys_table/cluster_schema/users.slt)
        if session.tenant != "cnosdb":
            raise TableNotFound(f"{db}.{table}")
        if t == "users":
            u = meta.users.get(session.user)
            if u is not None and not u.get("admin"):
                return _cols(["user_name", "is_admin", "user_options"], [])
            return _users_table(meta)
        if t == "tenants":
            u = meta.users.get(session.user)
            if u is not None and not u.get("admin"):
                # tenant catalog is admin-only; members read it empty
                # (cluster_schema/tenants.slt)
                return _cols(["tenant_name", "tenant_options"], [])
            return _tenants_table(meta)
        if t == "nodes":
            rows = [(n.id, n.http_addr, n.grpc_addr, "running")
                    for n in meta.nodes.values()]
            return _cols(["node_id", "http_addr", "grpc_addr", "status"], rows)
        if t == "vnodes":
            rows = []
            for owner, buckets in meta.buckets.items():
                for b in buckets:
                    for rs in b.shard_group:
                        for v in rs.vnodes:
                            rows.append((v.id, owner, b.id, rs.id, v.node_id,
                                         v.status.name))
            return _cols(["vnode_id", "owner", "bucket_id", "replica_set_id",
                          "node_id", "status"], rows)
    if db == "usage_schema":
        if t == "disk_usage":
            rows = []
            for (owner, vid), v in engine.vnodes.items():
                rows.append((owner, vid, v.disk_size(), v.series_count()))
            return _cols(["owner", "vnode_id", "disk_bytes", "series_count"], rows)
        if t == "wal_usage":
            rows = []
            for (owner, vid), v in engine.vnodes.items():
                rows.append((owner, vid, v.wal.total_size()))
            return _cols(["owner", "vnode_id", "wal_bytes"], rows)
    raise TableNotFound(f"{db}.{table}")


def _render_options(opts: dict) -> str:
    """Deterministic `k=v,...` rendering (sorted keys, SQL-style bools)
    for the table_options column."""
    def val(v):
        if isinstance(v, bool):
            return "true" if v else "false"
        return v

    return ",".join(f"{k}={val(v)}" for k, v in sorted(opts.items()))


def _size_str(v) -> str:
    from .executor import _size_display

    return _size_display(v)


def _users_table(meta):
    import json

    def opts_json(u):
        # reference user_options JSON: keys appear only when SET, in
        # hash_password → must_change_password → comment order
        # (dcl/alter_user.slt pins the shapes); the hash never leaks
        out = {"hash_password": "*****"}
        if "must_change_password" in u and u["must_change_password"] \
                is not None:
            out["must_change_password"] = bool(u["must_change_password"])
        if u.get("comment"):
            out["comment"] = u["comment"]
        if "granted_admin" in u and u["granted_admin"] is not None:
            out["granted_admin"] = bool(u["granted_admin"])
        return json.dumps(out, separators=(",", ":"),
                          ensure_ascii=False)

    rows = [(name, bool(u.get("admin")), opts_json(u))
            for name, u in meta.users.items()]
    return _cols(["user_name", "is_admin", "user_options"], rows)


def _tenants_table(meta):
    import json

    def opts_json(o):
        da = None
        if o.drop_after is not None:
            # reference serde of Duration: {"duration":{"secs","nanos"},
            # "is_inf"} (cluster_schema/tenants.slt)
            da = {"duration": {"secs": o.drop_after.ns // 10 ** 9,
                               "nanos": o.drop_after.ns % 10 ** 9},
                  "is_inf": o.drop_after.is_inf}
        return json.dumps(
            {"comment": o.comment or None, "limiter_config": o.limiter,
             "drop_after": da, "tenant_is_hidden": False},
            separators=(",", ":"), ensure_ascii=False)

    rows = [(name, opts_json(opts)) for name, opts in meta.tenants.items()]
    return _cols(["tenant_name", "tenant_options"], rows)


def _cols(names: list[str], rows: list[tuple]):
    if not rows:
        return names, [np.empty(0, dtype=object) for _ in names]
    cols = []
    for i in range(len(names)):
        vals = [r[i] for r in rows]
        if all(isinstance(v, bool) for v in vals):
            cols.append(np.array(vals))
        elif all(isinstance(v, (int, np.integer)) and not isinstance(v, bool)
                 for v in vals):
            cols.append(np.array(vals, dtype=np.int64))
        else:
            cols.append(np.array(vals, dtype=object))
    return names, cols
