"""System schemas: information_schema / cluster_schema / usage_schema.

Role-parity with the reference's metadata providers
(query_server/query/src/metadata/: information_schema_provider,
cluster_schema_provider, usage_schema_provider): virtual tables backed by
the meta store and engine stats, addressable as
`SELECT ... FROM information_schema.<table>`.
"""
from __future__ import annotations

import numpy as np

from ..errors import TableNotFound


def is_system_db(db: str) -> bool:
    return db in ("information_schema", "cluster_schema", "usage_schema")


def system_table(executor, db: str, table: str, session) -> tuple[list[str], list]:
    meta = executor.meta
    engine = executor.coord.engine
    t = table.lower()
    if db == "information_schema":
        if t == "databases":
            rows = []
            for name in meta.list_databases(session.tenant):
                o = meta.database(session.tenant, name).options
                rows.append((session.tenant, name, str(o.ttl), o.shard_num,
                             str(o.vnode_duration), o.replica, o.precision.name))
            return _cols(["tenant_name", "database_name", "ttl", "shard",
                          "vnode_duration", "replica", "precision"], rows)
        if t == "tables":
            # column set and values follow the reference
            # (information_schema_provider/builder/tables.rs: table_type
            # TABLE, engine TSKV/EXTERNAL/STREAM, options 'TODO')
            rows = []
            for dbn in meta.list_databases(session.tenant):
                for tn in meta.list_tables(session.tenant, dbn):
                    rows.append((session.tenant, dbn, tn, "TABLE", "TSKV",
                                 "TODO"))
                owner = f"{session.tenant}.{dbn}"
                for tn in sorted(getattr(meta, "externals", {})
                                 .get(owner, {})):
                    rows.append((session.tenant, dbn, tn, "TABLE",
                                 "EXTERNAL", "TODO"))
            for key, st in sorted(getattr(meta, "stream_tables",
                                          {}).items()):
                tenant, dbn, name = key.split(".", 2)
                if tenant != session.tenant:
                    continue
                rows.append((tenant, dbn, name, "TABLE", "STREAM", "TODO"))
            return _cols(["table_tenant", "table_database", "table_name",
                          "table_type", "table_engine", "table_options"],
                         rows)
        if t == "columns":
            rows = []
            for dbn in meta.list_databases(session.tenant):
                for tn in meta.list_tables(session.tenant, dbn):
                    schema = meta.table(session.tenant, dbn, tn)
                    for c in schema.columns:
                        ct = c.column_type
                        kind = ("TIME" if ct.is_time else
                                "TAG" if ct.is_tag else "FIELD")
                        dtype = ("TIMESTAMP" if ct.is_time else "STRING"
                                 if ct.is_tag else ct.value_type.sql_name())
                        rows.append((session.tenant, dbn, tn, c.name, kind,
                                     dtype, c.encoding.name))
            return _cols(["table_tenant", "table_database", "table_name",
                          "column_name", "column_type", "data_type",
                          "compression_codec"], rows)
        if t == "tenants":
            return _tenants_table(meta)
        if t == "users":
            return _users_table(meta)
        if t == "queries":
            return _cols(["query_id", "query_text", "user_name", "tenant_name",
                          "state", "duration"], [])
    if db == "cluster_schema":
        # the reference serves users/tenants from CLUSTER_SCHEMA
        # (metadata/cluster_schema_provider); keep them reachable from the
        # information_schema spelling too
        if t == "users":
            return _users_table(meta)
        if t == "tenants":
            return _tenants_table(meta)
        if t == "nodes":
            rows = [(n.id, n.http_addr, n.grpc_addr, "running")
                    for n in meta.nodes.values()]
            return _cols(["node_id", "http_addr", "grpc_addr", "status"], rows)
        if t == "vnodes":
            rows = []
            for owner, buckets in meta.buckets.items():
                for b in buckets:
                    for rs in b.shard_group:
                        for v in rs.vnodes:
                            rows.append((v.id, owner, b.id, rs.id, v.node_id,
                                         v.status.name))
            return _cols(["vnode_id", "owner", "bucket_id", "replica_set_id",
                          "node_id", "status"], rows)
    if db == "usage_schema":
        if t == "disk_usage":
            rows = []
            for (owner, vid), v in engine.vnodes.items():
                rows.append((owner, vid, v.disk_size(), v.series_count()))
            return _cols(["owner", "vnode_id", "disk_bytes", "series_count"], rows)
        if t == "wal_usage":
            rows = []
            for (owner, vid), v in engine.vnodes.items():
                rows.append((owner, vid, v.wal.total_size()))
            return _cols(["owner", "vnode_id", "wal_bytes"], rows)
    raise TableNotFound(f"{db}.{table}")


def _users_table(meta):
    rows = [(name, bool(u.get("admin")), u.get("comment", ""))
            for name, u in meta.users.items()]
    return _cols(["user_name", "is_admin", "comment"], rows)


def _tenants_table(meta):
    rows = [(name, opts.comment) for name, opts in meta.tenants.items()]
    return _cols(["tenant_name", "tenant_options"], rows)


def _cols(names: list[str], rows: list[tuple]):
    if not rows:
        return names, [np.empty(0, dtype=object) for _ in names]
    cols = []
    for i in range(len(names)):
        vals = [r[i] for r in rows]
        if all(isinstance(v, bool) for v in vals):
            cols.append(np.array(vals))
        elif all(isinstance(v, (int, np.integer)) and not isinstance(v, bool)
                 for v in vals):
            cols.append(np.array(vals, dtype=np.int64))
        else:
            cols.append(np.array(vals, dtype=object))
    return names, cols
