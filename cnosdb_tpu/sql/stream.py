"""Micro-batch stream engine.

Role-parity with the reference's stream subsystem (query_server/query/src/
execution/stream/mod.rs:43-120 MicroBatchStreamExecution + trigger/,
watermark_tracker.rs, offset_tracker): a registered stream query re-plans
a bounded time slice of its source table on every trigger tick, feeds the
aggregated result into a sink (another table or a callback), and tracks
the event-time watermark durably so restarts resume where they left off.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ..errors import QueryError
from .executor import QueryExecutor, ResultSet, Session
from ..utils import lockwatch


@dataclass
class StreamQuery:
    name: str
    sql: str = ""                 # text form with $START/$END placeholders
    interval_s: float = 10.0      # trigger cadence
    delay_ns: int = 0             # watermark delay (late data allowance)
    session: Session = field(default_factory=Session)
    sink: object = None           # callable(ResultSet) | ("table", name)
    stmt: object = None           # parsed SelectStmt template (SQL DDL path)


class WatermarkTracker:
    """Durable per-stream watermark (reference watermark_tracker.rs)."""

    def __init__(self, path: str):
        self.path = path
        self.watermarks: dict[str, int] = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self.watermarks = {k: int(v) for k, v in json.load(f).items()}
            except Exception:
                self.watermarks = {}

    def get(self, name: str, default: int) -> int:
        return self.watermarks.get(name, default)

    def set(self, name: str, value: int):
        self.watermarks[name] = value
        self._persist()

    def remove(self, name: str):
        if self.watermarks.pop(name, None) is not None:
            self._persist()

    def _persist(self):
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self.watermarks, f)
            # fsync BEFORE the rename: os.replace is only atomic for
            # data already on disk — a power loss after the rename but
            # before writeback would otherwise leave an empty/torn file
            # where a valid watermark used to be
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)


def _window_stmt(stmt, start: int, end: int):
    """Template SelectStmt → copy with WHERE ∧ start ≤ time < end."""
    import dataclasses

    from .expr import BinOp, Column, Literal

    window = BinOp("and",
                   BinOp(">=", Column("time"), Literal(int(start))),
                   BinOp("<", Column("time"), Literal(int(end))))
    where = window if stmt.where is None else BinOp("and", stmt.where, window)
    return dataclasses.replace(stmt, where=where)


class OffsetTracker:
    """Per-source processed/available offsets (reference
    stream/offset_tracker/mod.rs). For tskv sources the offset is the max
    ingested timestamp: a trigger only processes up to what the source has
    actually made AVAILABLE, so a lagging ingest pipeline cannot make the
    watermark skip past data that is still arriving in order."""

    def __init__(self):
        self._lock = lockwatch.Lock("stream.offsets")
        self._processed: dict[str, int] = {}
        self._available: dict[str, int] = {}

    def update_available(self, source: str, offset: int):
        with self._lock:
            cur = self._processed.get(source)
            if cur is None or offset > cur:
                self._available[source] = max(
                    self._available.get(source, offset), offset)

    def has_available(self) -> bool:
        with self._lock:
            return bool(self._available)

    def available_range(self, source: str):
        """→ (processed | None, available | None) for one source."""
        with self._lock:
            return (self._processed.get(source),
                    self._available.get(source))

    def commit(self, source: str, offset: int):
        """Mark everything ≤ offset processed; drops the available entry
        when fully consumed (reference update_processed_offset)."""
        with self._lock:
            self._processed[source] = offset
            if self._available.get(source, -1) <= offset:
                self._available.pop(source, None)


class MemoryStateStore:
    """Commit/expire/state over row batches, uniquely identified by
    (query_id, partition_id, operator_id) — reference
    stream/state_store/memory.rs. Batches are ResultSet-shaped
    (names, columns); puts stage into the uncommitted set, commit()
    publishes them and returns the new version, expire(predicate)
    removes matching rows from the committed state and returns them."""

    def __init__(self):
        self._lock = lockwatch.Lock("stream.state_store")
        self._committed: list[ResultSet] = []
        self._uncommitted: list[ResultSet] = []
        self._version = 0

    def put(self, batch: ResultSet):
        with self._lock:
            # copy: callers may reuse/mutate their arrays
            self._uncommitted.append(ResultSet(
                list(batch.names), [np.array(c) for c in batch.columns]))

    def commit(self) -> int:
        with self._lock:
            self._committed.extend(self._uncommitted)
            self._uncommitted = []
            self._version += 1
            return self._version

    def expire(self, predicate) -> list[ResultSet]:
        """predicate: sql.expr.Expr over the batch columns; matching rows
        are REMOVED and returned (reference expire())."""
        removed = []
        with self._lock:
            kept = []
            for rs in self._committed:
                env = {n: c for n, c in zip(rs.names, rs.columns)}
                m = np.asarray(predicate.eval(env, np))
                if not m.shape:
                    m = np.full(rs.n_rows, bool(m))
                m = m.astype(bool)
                if m.any():
                    removed.append(ResultSet(
                        list(rs.names), [c[m] for c in rs.columns]))
                if not m.all():
                    kept.append(ResultSet(
                        list(rs.names), [c[~m] for c in rs.columns]))
            self._committed = kept
        return removed

    def state(self) -> list[ResultSet]:
        with self._lock:
            return list(self._committed)


class StateStoreFactory:
    """get_or_default keyed by (query_id, partition_id, operator_id)
    (reference MemoryStateStoreFactory)."""

    def __init__(self):
        self._lock = lockwatch.Lock("stream.state_factory")
        self._stores: dict[tuple, MemoryStateStore] = {}

    def get_or_default(self, query_id: str, partition_id: int = 0,
                       operator_id: int = 0) -> MemoryStateStore:
        key = (query_id, partition_id, operator_id)
        with self._lock:
            store = self._stores.get(key)
            if store is None:
                store = self._stores[key] = MemoryStateStore()
            return store

    def drop_query(self, query_id: str):
        with self._lock:
            for key in [k for k in self._stores if k[0] == query_id]:
                self._stores.pop(key)


class StreamEngine:
    def __init__(self, executor: QueryExecutor, state_dir: str):
        self.executor = executor
        self.tracker = WatermarkTracker(os.path.join(state_dir, "watermarks.json"))
        self.offsets = OffsetTracker()
        self.state_stores = StateStoreFactory()
        self.streams: dict[str, StreamQuery] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._stop = threading.Event()

    def register(self, sq: StreamQuery, start_ns: int | None = None):
        if sq.stmt is None and ("$START" not in sq.sql or "$END" not in sq.sql):
            raise QueryError("stream SQL must contain $START and $END placeholders")
        if sq.name in self.streams:
            # replace: stop the old trigger thread first, or two loops would
            # race the watermark and double-write the sink
            self.drop(sq.name)
        self.streams[sq.name] = sq
        if start_ns is not None and sq.name not in self.tracker.watermarks:
            self.tracker.set(sq.name, start_ns)
        stop_evt = threading.Event()
        t = threading.Thread(target=self._run_stream, args=(sq, stop_evt),
                             daemon=True)
        self._threads[sq.name] = (t, stop_evt)
        t.start()

    def drop(self, name: str, keep_watermark: bool = False):
        self.state_stores.drop_query(name)
        self.streams.pop(name, None)
        entry = self._threads.pop(name, None)
        if entry is not None:
            t, stop_evt = entry
            stop_evt.set()
            if t is not threading.current_thread():
                t.join(timeout=2)
        if not keep_watermark:
            # a re-created stream with the same name must start fresh, not
            # resume from the dropped stream's watermark
            self.tracker.remove(name)

    def stop(self):
        self._stop.set()
        for t, stop_evt in self._threads.values():
            stop_evt.set()
            t.join(timeout=2)

    # ------------------------------------------------------------ execution
    def trigger_once(self, name: str, now_ns: int | None = None) -> ResultSet | None:
        """One micro-batch: [watermark, now - delay) → sink; advances the
        watermark only after the sink accepted the batch."""
        sq = self.streams.get(name)
        if sq is None:
            raise QueryError(f"unknown stream {name!r}")
        now = now_ns if now_ns is not None else int(time.time() * 1e9)
        start = self.tracker.get(name, 0)
        end = now - sq.delay_ns
        # the offset tracker caps the batch at what the SOURCE has made
        # available (max ingested ts + 1): a lagging ingest must not be
        # skipped over by a wall-clock watermark
        source = getattr(sq.stmt, "table", None) if sq.stmt is not None \
            else None
        if source:
            self._refresh_available(sq, source)
            _proc, avail = self.offsets.available_range(
                f"{sq.name}:{source}")
            if avail is not None:
                end = min(end, avail + 1)
        if end <= start:
            return None
        if sq.stmt is not None:
            rs = self.executor.execute_statement(
                _window_stmt(sq.stmt, start, end), sq.session)
        else:
            sql = sq.sql.replace("$START", str(start)).replace("$END", str(end))
            rs = self.executor.execute_one(sql, sq.session)
        self._emit(sq, rs)
        # stage + commit this batch's state, then advance offsets and the
        # durable watermark (reference order: sink → offsets → watermark)
        if rs.n_rows:
            store = self.state_stores.get_or_default(sq.name)
            store.put(rs)
            store.commit()
        if source:
            self.offsets.commit(f"{sq.name}:{source}", end - 1)
        self.tracker.set(name, end)
        return rs

    def _refresh_available(self, sq: StreamQuery, source: str):
        """Publish the source table's max ingested timestamp as its
        available offset."""
        try:
            rs = self.executor.execute_one(
                f"SELECT max(time) AS m FROM {source}", sq.session)
            if rs.n_rows and rs.columns[0][0] is not None:
                v = rs.columns[0][0]
                if not (isinstance(v, float) and v != v):
                    self.offsets.update_available(
                        f"{sq.name}:{source}", int(v))
        except Exception:
            pass   # source may not exist yet; triggers retry

    def _emit(self, sq: StreamQuery, rs: ResultSet):
        if rs.n_rows == 0 or sq.sink is None:
            return
        if callable(sq.sink):
            sq.sink(rs)
            return
        kind, target = sq.sink
        if kind == "table":
            self._insert_into(sq.session, target, rs)

    def _insert_into(self, session: Session, table: str, rs: ResultSet):
        """Write an aggregated batch into a sink table (stream → table)."""
        from ..models.points import WriteBatch
        from ..models.schema import ValueType

        schema = self.executor.meta.table_opt(session.tenant, session.database,
                                              table)
        cols = rs.to_dict()
        if "time" not in cols:
            raise QueryError("stream sink requires a 'time' output column")
        tag_names = [n for n in rs.names
                     if schema is not None and schema.contains_column(n)
                     and schema.column(n).column_type.is_tag]
        if schema is None:
            # auto-create: non-time object columns → tags, numeric → fields
            tag_names = [n for n in rs.names if n != "time"
                         and cols[n].dtype == object]
        field_types = {}
        for n in rs.names:
            if n == "time" or n in tag_names:
                continue
            col = cols[n]
            if np.issubdtype(col.dtype, np.integer):
                field_types[n] = ValueType.INTEGER
            elif np.issubdtype(col.dtype, np.bool_):
                field_types[n] = ValueType.BOOLEAN
            elif col.dtype == object:
                field_types[n] = ValueType.STRING
            else:
                field_types[n] = ValueType.FLOAT
        rows = []
        for i in range(rs.n_rows):
            row = {"time": int(cols["time"][i])}
            for t in tag_names:
                row[t] = cols[t][i]
            for f in field_types:
                v = cols[f][i]
                if isinstance(v, float) and np.isnan(v):
                    v = None
                row[f] = v
            rows.append(row)
        wb = WriteBatch.from_rows(table, rows, tag_names, field_types)
        self.executor.coord.write_points(session.tenant, session.database, wb)

    def _run_stream(self, sq: StreamQuery, stop_evt: threading.Event):
        import logging

        # cadence-aligned: first trigger one interval after registration
        # (also keeps manual triggering in tests deterministic)
        while not stop_evt.wait(sq.interval_s) and not self._stop.is_set():
            if self.streams.get(sq.name) is not sq:
                return
            try:
                self.trigger_once(sq.name)
            except Exception:
                # transient errors must not kill the trigger loop, but they
                # must be visible
                logging.getLogger("cnosdb.stream").exception(
                    "stream %s trigger failed", sq.name)
