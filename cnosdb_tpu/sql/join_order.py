"""Cost-based inner-join ordering.

The reference inherits DataFusion's join planning; this engine materializes
relations eagerly, which allows something better than estimates: EXACT
cardinalities. A maximal tree of INNER joins is flattened to (leaves,
conjuncts); leaves materialize first, single-leaf conjuncts filter early,
then a greedy order joins the smallest estimated intermediate next
(|L|·|R| / max(ndv(keys)) with exact distinct counts on the key columns).

Row and column order stay EXACTLY as the written-order plan would produce
them: each leaf carries a hidden row-index column through the joins, and
the final result is lexsorted by the written-order index tuple (a left-deep
chain of the hash joins in sql/relational.py emits rows lexicographically
ordered by leaf row indices, and filters only remove rows — so the sort
reconstructs the written order bit for bit). The optimizer is therefore
invisible except in time: any structural case it does not prove safe
(outer joins, leaves without a unique qualifier) falls back to written
order.
"""
from __future__ import annotations

import numpy as np

from ..models.strcol import DictArray
from . import ast
from .relational import Scope, _split_conjuncts, hash_join
from .expr import BinOp, Expr

_HIDDEN = "__jridx"


def flatten_inner(item) -> tuple[list, list] | None:
    """Maximal INNER-join region rooted at `item` → (leaves, conjuncts).
    A non-inner join is NOT flattened through — it becomes a leaf whose
    subtree keeps its own (order-pinning) structure; the caller
    materializes it via the ordinary join path, so inner regions AROUND
    outer joins still reorder (round-3 verdict item 8)."""
    if isinstance(item, ast.Join) and item.kind == "inner":
        l = flatten_inner(item.left)
        r = flatten_inner(item.right)
        return l[0] + r[0], l[1] + r[1] + _split_conjuncts(item.on)
    return [item], []


def _ndv(arr) -> int:
    """Exact distinct count of a key column (NDV); 1 on anything exotic —
    a conservative default that only makes the optimizer less eager."""
    try:
        if isinstance(arr, DictArray):
            return max(len(np.unique(arr.codes)), 1)
        a = np.asarray(arr)
        if a.dtype == object:
            return max(len({x for x in a.tolist()}), 1)
        return max(len(np.unique(a)), 1)
    except Exception:
        return 1


def _conjunct_sides(c: Expr):
    """Equi conjunct → (left_expr, right_expr, left_cols, right_cols)."""
    if isinstance(c, BinOp) and c.op == "=":
        lc, rc = c.left.columns(), c.right.columns()
        if lc and rc:
            return c.left, c.right, lc, rc
    return None


def _conjoin(cs: list[Expr]) -> Expr | None:
    out = None
    for c in cs:
        out = c if out is None else BinOp("and", out, c)
    return out


def order_and_join(leaves: list[Scope], conjuncts: list[Expr]) -> Scope:
    """Join materialized leaf scopes in a greedy cost order; returns a scope
    whose rows/columns match the written-order left-deep join exactly.
    Leaves may carry multiple qualifiers (a materialized outer-join
    subtree is one leaf): display columns are addressed by hidden
    per-position keys, so reordering never depends on name resolution."""
    k = len(leaves)
    # hidden written-order row index per leaf, riding the env through
    # joins + a unique address per display column (position-stable even
    # when a leaf has colliding or multi-qualifier names)
    for i, s in enumerate(leaves):
        s.env[f"{_HIDDEN}{i}"] = np.arange(s.n, dtype=np.int64)
        for pos, col in enumerate(s.cols):
            s.env[f"__leafcol{i}_{pos}"] = col

    # single-leaf conjuncts filter at the source (same rows the written
    # plan would drop post-join; relative row order is unchanged)
    leaf_cols = [set(s.env) for s in leaves]
    remaining: list[Expr] = []
    for c in conjuncts:
        cols = c.columns()
        hit = [i for i in range(k) if cols <= leaf_cols[i]]
        if hit:
            i = hit[0]
            m = np.asarray(c.eval(leaves[i].env, np))
            if not m.shape:
                m = np.full(leaves[i].n, bool(m))
            leaves[i] = leaves[i].filter(m.astype(bool))
        else:
            remaining.append(c)

    unused = set(range(k))
    start = min(unused, key=lambda i: leaves[i].n)
    cur = leaves[start]
    unused.discard(start)
    pending = list(remaining)
    leaf_ndv: dict[tuple[int, str], int] = {}   # loop-invariant, cached

    while unused:
        best, best_cost, best_connected = None, None, False
        cur_cols = set(cur.env)
        cur_ndv: dict[str, int] = {}            # valid for this round only
        for j in unused:
            cost = float(cur.n) * float(leaves[j].n)
            connected = False
            combined = cur_cols | leaf_cols[j]
            for c in pending:
                sides = _conjunct_sides(c)
                if sides is None or not (c.columns() <= combined):
                    continue
                le, re_, lc, rc = sides
                for a, b, ae, be in ((lc, rc, le, re_), (rc, lc, re_, le)):
                    if a <= cur_cols and b <= leaf_cols[j]:
                        connected = True
                        ck = str(ae)
                        if ck not in cur_ndv:
                            cur_ndv[ck] = _ndv(ae.eval(cur.env, np))
                        lk = (j, str(be))
                        if lk not in leaf_ndv:
                            leaf_ndv[lk] = _ndv(be.eval(leaves[j].env, np))
                        nd = max(cur_ndv[ck], leaf_ndv[lk])
                        cost = min(cost,
                                   float(cur.n) * float(leaves[j].n) / nd)
                        break
            # cross products only when nothing is connected
            if best is None or (connected, ) > (best_connected, ) or (
                    connected == best_connected and cost < best_cost):
                best, best_cost, best_connected = j, cost, connected
        j = best
        unused.discard(j)
        combined = set(cur.env) | leaf_cols[j]
        applicable = [c for c in pending if c.columns() <= combined]
        pending = [c for c in pending if c not in applicable]
        kind = "inner" if applicable else "cross"
        cur = hash_join(cur, leaves[j], kind, _conjoin(applicable))

    if pending:   # conjuncts referencing columns no leaf provides
        m = np.ones(cur.n, dtype=bool)
        for c in pending:
            mm = np.asarray(c.eval(cur.env, np))
            m &= mm.astype(bool) if mm.shape else bool(mm)
        cur = cur.filter(m)

    # restore written-order rows: lexsort by (ridx_0, ..., ridx_{k-1});
    # np.lexsort sorts by the LAST key primarily
    ridx = [np.asarray(cur.env[f"{_HIDDEN}{i}"], dtype=np.int64)
            for i in range(k)]
    order = np.lexsort(ridx[::-1])
    cur = cur.take(order)

    # restore written-order columns and bare-name resolution via the
    # hidden per-position addresses
    names, cols, env = [], [], {}
    for i, leaf in enumerate(leaves):
        for pos, n_ in enumerate(leaf.names):
            col = cur.env[f"__leafcol{i}_{pos}"]
            names.append(n_)
            cols.append(col)
        for q in leaf.quals:
            for n_ in leaf.names:
                key = f"{q}.{n_}"
                if key in cur.env:
                    env[key] = cur.env[key]
    for i in range(k - 1, -1, -1):   # earliest-written leaf wins bare names
        for pos, n_ in enumerate(leaves[i].names):
            env[n_] = cur.env[f"__leafcol{i}_{pos}"]
    out = Scope(names, cols, env)
    out.quals = set().union(*(s.quals for s in leaves))
    return out


def reorderable(leaves: list[Scope], conjuncts: list[Expr]) -> bool:
    """Safe to reorder: ≥3 leaves, disjoint qualifier sets (display
    columns are addressed positionally, so multi-qualifier leaves —
    materialized outer-join subtrees — are fine), and no conjunct
    referencing a name visible in more than one leaf (written-order
    bare-name resolution depends on join position; rather than emulate
    it mid-reorder, bail out)."""
    if len(leaves) < 3:
        return False
    seen: set[str] = set()
    for s in leaves:
        if not s.quals:
            return False
        for q in s.quals:
            if q in seen:
                return False
            seen.add(q)
    for c in conjuncts:
        for col in c.columns():
            if sum(1 for s in leaves if col in s.env) > 1:
                return False
    return True
