"""Deterministic fault-injection plane.

The distributed machinery (multi-raft replication, leader-retry writes,
broken-replica failover, WAL torn-tail recovery) promises invariants that
only show up under partial failure. This module makes those failures
*injectable, deterministic and inheritable*: named fault points threaded
through the RPC plane, the WAL/record-file layer, flush/compaction and the
meta service fire according to a seeded schedule parsed from the
``CNOSDB_FAULTS`` environment variable — so the multi-process cluster
harness (tests/cluster_harness.py) arms every spawned node just by setting
the env, and the same spec + seed reproduces the same firing sequence.

Zero overhead when disabled: ``CNOSDB_FAULTS`` unset leaves the
module-level ``ENABLED`` bool False, and every hook site guards with a
single ``if faults.ENABLED:`` check before calling :func:`fire`.

Schedule grammar (rules separated by ``;``)::

    CNOSDB_FAULTS = "seed=<int>" | <rule> { ";" <rule> }
    rule          = <point> ":" <action> [ ":" <sched> ]
    action        = fail | delay(<ms>) | drop | torn[(<bytes>)]
                  | corrupt[(<nbytes>)] | enospc | io_error | crash | noop
    sched         = <k>=<v> { "," <k>=<v> }     # all optional, AND-ed
                      nth=<k>     fire only on the k-th matching hit
                      after=<k>   fire on every hit after the k-th
                      times=<k>   fire at most k times
                      once        fire at most once (= times=1)
                      prob=<p>    fire with probability p (seeded RNG)
                      if=<substr> hit counts only when <substr> appears in
                                  the hook call's context values (method
                                  name, peer address, path ...)

Example::

    CNOSDB_FAULTS="seed=7;rpc.send:fail:if=127.0.0.1:9402;\
wal.append:torn(4):nth=11;rpc.reply:drop:nth=1,if=write_replica"

Actions ``fail`` / ``enospc`` / ``io_error`` raise (:class:`FaultInjected`
is an ``OSError`` so existing network/disk error handling takes the same
path a real fault would), ``delay`` sleeps, ``crash`` calls ``os._exit``.
``torn``, ``drop`` and ``corrupt`` are *site-implemented*: :func:`fire`
returns the ``(action, arg)`` tuple and the hook site performs the partial
write / reply drop / on-disk bit flip itself. ``corrupt(<nbytes>)`` flips
bytes of an already-durable file (default 1) at a deterministic offset —
the silent-corruption model the integrity plane (storage/scrub.py) exists
to catch. ``noop`` fires (lands in the fired log, advances hit counters)
but does nothing — the chaos sweep's probe pass arms it at every point to
learn how many times each site is crossed by a workload.

Every fire() site self-registers in :data:`FAULT_POINTS` via
:func:`register_point` at module import — the registry the crash-point
sweep (cnosdb_tpu/chaos/sweep.py) enumerates and the `fault-site-coverage`
lint rule enforces. The authoritative point table lives in ARCHITECTURE.md
"Fault model"; at runtime, ``control({"points": True})`` returns it.
"""
from __future__ import annotations

import errno as _errno
import os
import random
import threading
import time
import zlib
from .utils import lockwatch


class FaultInjected(OSError):
    """An injected failure. Subclasses OSError so hook sites' existing
    connection/disk error handling treats it exactly like the real thing."""


# Single module-level guard — hook sites check `faults.ENABLED` (one
# attribute load + bool test) before paying for the fire() call.
ENABLED = False

# Runtime control surface (`_faults` RPC method) is armed iff CNOSDB_FAULTS
# is present in the environment — harness-spawned processes inherit it, and
# production processes (env unset) expose nothing.
CTL_ARMED = "CNOSDB_FAULTS" in os.environ

_lock = lockwatch.RLock("faults.registry")
_rules: dict[str, list["_Rule"]] = {}
_fired: list[tuple[str, str, int]] = []   # (point, action, hit#) sequence
_seed = 0

_SITE_ACTIONS = frozenset({"torn", "drop", "corrupt"})
_KNOWN_ACTIONS = _SITE_ACTIONS | {"fail", "delay", "enospc", "io_error",
                                  "crash", "noop"}


class FaultPoint:
    """One registered fire() site — the unit the crash-point sweep
    enumerates. `scope` is "node" when the point is reachable from the
    single-process canonical workload (chaos/workload.py) and therefore
    swept crash-by-crash, or "cluster" when it only fires across
    processes (RPC plane, meta raft) and is exercised by the nemesis
    suite in tests/test_chaos_cluster.py instead."""

    __slots__ = ("name", "module", "scope", "desc")

    def __init__(self, name: str, module: str, scope: str, desc: str):
        self.name = name
        self.module = module
        self.scope = scope
        self.desc = desc

    def as_row(self) -> list[str]:
        return [self.name, self.module, self.scope, self.desc]


# point name -> FaultPoint; populated by register_point() calls that sit
# next to each fire() site (enforced by the fault-site-coverage lint rule)
FAULT_POINTS: dict[str, FaultPoint] = {}


def register_point(name: str, module: str, scope: str = "node",
                   desc: str = "") -> None:
    """Self-registration for a fire() site, called at import of the module
    that hosts the hook. Idempotent (module reload overwrites)."""
    if scope not in ("node", "cluster"):
        raise ValueError(f"fault point {name!r}: scope must be node|cluster")
    with _lock:
        FAULT_POINTS[name] = FaultPoint(name, module, scope, desc)


def registered_points(scope: str | None = None) -> dict[str, FaultPoint]:
    """Snapshot of the registry, optionally filtered to one scope."""
    with _lock:
        return {n: p for n, p in FAULT_POINTS.items()
                if scope is None or p.scope == scope}


class _Rule:
    __slots__ = ("point", "action", "arg", "when", "hits", "fired", "rng")

    def __init__(self, point: str, action: str, arg: str | None,
                 when: dict, seed: int):
        self.point = point
        self.action = action
        self.arg = arg
        self.when = when
        self.hits = 0
        self.fired = 0
        # per-rule RNG seeded from the global seed and a *stable* hash of
        # the rule text (hash() is salted per process; crc32 is not), so
        # prob schedules replay identically across processes and runs
        key = zlib.crc32(f"{point}:{action}:{arg}".encode())
        self.rng = random.Random((seed << 32) ^ key)

    def check(self, ctx: dict) -> bool:
        """Advance this rule's hit counter for a matching call and decide
        whether it fires. Caller holds _lock (determinism under threads)."""
        w = self.when
        cond = w.get("if")
        if cond is not None:
            hay = " ".join(str(v) for v in ctx.values())
            if cond not in hay:
                return False
        self.hits += 1
        if "nth" in w and self.hits != w["nth"]:
            return False
        if "after" in w and self.hits <= w["after"]:
            return False
        if "times" in w and self.fired >= w["times"]:
            return False
        if "prob" in w and self.rng.random() >= w["prob"]:
            return False
        self.fired += 1
        return True


def _parse_rule(text: str, seed: int) -> _Rule:
    parts = text.split(":", 1)
    if len(parts) != 2 or not parts[0]:
        raise ValueError(f"bad fault rule {text!r} (want point:action[:sched])")
    point = parts[0].strip()
    rest = parts[1]
    # action may carry "(arg)"; the schedule follows the NEXT ":" — but an
    # "if=" value can itself contain ":" (host:port), so split the schedule
    # off first on the ":" that is outside parentheses
    depth = 0
    split_at = -1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == ":" and depth == 0:
            split_at = i
            break
    act_text = rest if split_at < 0 else rest[:split_at]
    sched_text = "" if split_at < 0 else rest[split_at + 1:]
    act_text = act_text.strip()
    arg = None
    if "(" in act_text:
        if not act_text.endswith(")"):
            raise ValueError(f"bad fault action {act_text!r}")
        act_text, arg = act_text[:-1].split("(", 1)
    action = act_text.strip()
    if action not in _KNOWN_ACTIONS:
        raise ValueError(f"unknown fault action {action!r} in {text!r}")
    when: dict = {}
    if sched_text:
        for kv in sched_text.split(","):
            kv = kv.strip()
            if not kv:
                continue
            if kv == "once":
                when["times"] = 1
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            if k == "if":
                when["if"] = v.strip()
            elif k == "prob":
                when["prob"] = float(v)
            elif k in ("nth", "after", "times"):
                when[k] = int(v)
            else:
                raise ValueError(f"unknown fault schedule key {k!r} in {text!r}")
    return _Rule(point, action, arg, when, seed)


def configure(spec: str | None) -> None:
    """(Re)install the fault schedule from a spec string ("" disables).

    Raises ValueError on a malformed spec — a chaos run silently running
    with no faults armed would report false-green invariants."""
    global ENABLED, _seed
    rules: dict[str, list[_Rule]] = {}
    seed = 0
    texts = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            seed = int(part[5:])
        else:
            texts.append(part)
    with _lock:
        _seed = seed
        for t in texts:
            r = _parse_rule(t, seed)
            rules.setdefault(r.point, []).append(r)
        _rules.clear()
        _rules.update(rules)
        _fired.clear()
        ENABLED = bool(rules)


def reset() -> None:
    """Disable injection and clear rules + the fired log."""
    configure("")


def fire(point: str, **ctx) -> tuple[str, str | None] | None:
    """Hook entry: evaluate `point`'s rules against this call.

    Raising actions (fail/enospc/io_error) raise FaultInjected/OSError,
    delay sleeps, crash exits the process. Site-implemented actions
    (torn/drop) return ``(action, arg)`` for the caller to perform;
    returns None when nothing fires."""
    if not ENABLED:
        return None
    with _lock:
        rules = _rules.get(point)
        if not rules:
            return None
        hit = None
        for r in rules:
            if r.check(ctx):
                hit = r
                _fired.append((point, r.action, r.hits))
                break
        if hit is None:
            return None
        action, arg = hit.action, hit.arg
    # execute OUTSIDE the lock: delay must not serialize unrelated points
    if action == "noop":
        return None   # fired log + hit counters advanced; nothing injected
    if action == "fail":
        raise FaultInjected(f"injected fail at {point}")
    if action == "enospc":
        raise FaultInjected(_errno.ENOSPC, f"injected ENOSPC at {point}")
    if action == "io_error":
        raise FaultInjected(_errno.EIO, f"injected EIO at {point}")
    if action == "delay":
        time.sleep(float(arg or 10) / 1e3)
        return None
    if action == "crash":
        os._exit(137)
    return (action, arg)


def corrupt_file(path: str, nbytes: int = 1,
                 lo: int = 0, hi: int | None = None) -> int:
    """Site helper for the ``corrupt`` action: XOR-flip `nbytes` bytes of
    `path` inside the [lo, hi) window at an offset derived from the file
    name (stable hash, no RNG — replayable). Returns the flip offset.

    The flip targets already-durable bytes, modeling bit rot / a bad
    sector underneath a sealed file — invisible until a CRC check
    (scan-time page read or the background scrubber) walks over it."""
    size = os.path.getsize(path)
    hi = size if hi is None else min(int(hi), size)
    lo = max(0, int(lo))
    nbytes = max(1, int(nbytes))
    span = hi - lo - nbytes
    if span <= 0:   # window too small: fall back to anywhere in the file
        lo, span = 0, max(1, size - nbytes)
    off = lo + zlib.crc32(os.path.basename(path).encode()) % span
    with open(path, "r+b") as f:
        f.seek(off)
        orig = f.read(nbytes)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in orig))
        f.flush()
        os.fsync(f.fileno())
    return off


def fired_log() -> list[tuple[str, str, int]]:
    """The (point, action, hit#) sequence fired so far — the determinism
    witness: same spec + same workload ⇒ same log."""
    with _lock:
        return list(_fired)


def control(payload: dict) -> dict:
    """Runtime control handler behind the `_faults` RPC method (armed only
    when CNOSDB_FAULTS is present in the process environment):

      {"spec": "<schedule>"}  reconfigure ("" disables)
      {"log": true}           return the fired log
      {"points": true}        return the FAULT_POINTS registry rows
    """
    out: dict = {"ok": True}
    if "spec" in payload:
        configure(payload["spec"] or "")
        out["enabled"] = ENABLED
    if payload.get("log"):
        out["log"] = [list(t) for t in fired_log()]
    if payload.get("points"):
        out["points"] = [p.as_row() for _, p in
                         sorted(registered_points().items())]
    return out


# Arm from the environment at import: harness-spawned subprocesses inherit
# the parent's CNOSDB_FAULTS and come up with the same schedule.
if CTL_ARMED:
    configure(os.environ.get("CNOSDB_FAULTS", ""))
