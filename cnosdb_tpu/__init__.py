"""cnosdb_tpu — a TPU-native distributed time-series database.

A ground-up rebuild of the capability surface of CnosDB (reference:
/root/reference, Rust, v2.4.3) designed TPU-first:

- Host side (Python + C++ codecs): columnar TSM storage (pages/chunks/
  footer+bloom), WAL, memcache, flush, leveled compaction, series index,
  meta/coordinator/sharding.
- Device side (JAX/XLA): the scan data plane — predicate filters,
  time-bucketed GROUP BY and the aggregate set (count/sum/mean/min/max/
  first/last) run as jit/shard_map programs with segment reductions and
  ICI psum partial-aggregate combining.

Layer map mirrors reference SURVEY.md §1 (services → query → coordinator →
meta → replication → storage) but is architected around XLA's compilation
model: static padded block shapes, segment ids for (series × time-bucket)
grouping, collectives over a jax.sharding.Mesh instead of NCCL/gRPC fanout
on the hot path.

This top-level import is intentionally light (models/storage only need
numpy); jax loads — and x64 is enabled, timestamps are i64 ns — when the
device-side `cnosdb_tpu.ops` / `cnosdb_tpu.parallel` modules import.
"""

__version__ = "0.1.0"
