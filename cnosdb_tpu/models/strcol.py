"""Dictionary-encoded string columns.

The TPU-first answer to the reference's string columns (tskv/src/tsm/
codec/string.rs stores raw compressed blocks; DataFusion aggregates on
materialized Utf8 arrays): strings never travel the hot path as Python
objects. A column is a pair (codes int32 [N], values object [U]) where
`values` is the lexicographically-sorted unique dictionary — so every
comparison, min/max, group-by and filter on the column is an integer
kernel over `codes`, and code order IS string order. Python-object work is
O(U) (decode the dictionary) instead of O(N) (decode every row).

Invariants:
- `values` is sorted ascending, unique, non-empty whenever `codes` is
  non-empty (an all-null column carries a single "" entry so code 0 is
  always addressable; validity lives in the caller's mask, not here).
- `codes[i]` is an index into `values`; rows the caller marks invalid may
  carry any code (conventionally 0).
"""
from __future__ import annotations

import numpy as np

try:  # pyarrow rides the Arrow IPC plane already; use its C++ hash table
    import pyarrow as pa
    import pyarrow.compute as pc
except Exception:  # pragma: no cover - arrow is a hard dep elsewhere
    pa = None


class DictArray:
    __slots__ = ("codes", "values")

    def __init__(self, codes: np.ndarray, values: np.ndarray):
        self.codes = codes
        self.values = values

    # -- ndarray-ish surface used by the scan/merge paths ------------------
    def __len__(self) -> int:
        return len(self.codes)

    def __getitem__(self, idx) -> "DictArray":
        return DictArray(self.codes[idx], self.values)

    @property
    def dtype(self):
        return np.dtype(object)

    @property
    def shape(self):
        return self.codes.shape

    def map_values(self, fn, out_dtype=object) -> np.ndarray:
        """Apply a python fn once per UNIQUE, gather to rows. The workhorse
        for string scalars (upper/substr/LIKE/CAST…): O(U) Python instead
        of O(N)."""
        per_u = [fn(x) for x in self.values]
        if out_dtype is object:
            arr = np.empty(len(per_u), dtype=object)
            arr[:] = per_u
        else:
            arr = np.array(per_u, dtype=out_dtype)
        return arr[self.codes]

    def materialize(self) -> np.ndarray:
        """→ object ndarray (vectorized pointer gather, no per-row Python)."""
        if len(self.codes) == 0:
            return np.empty(0, dtype=object)
        return self.values[self.codes]

    def tolist(self) -> list:
        return self.materialize().tolist()

    # dict-aware comparisons: predicate evaluated once per UNIQUE, then a
    # C gather broadcasts it to rows — `col = 'x'` on 10M rows costs O(U)
    # Python compares + one int gather instead of 10M object compares.
    def _cmp(self, op, other) -> np.ndarray:
        per_unique = op(self.values, other)
        return per_unique[self.codes]

    def __eq__(self, other):  # type: ignore[override]
        if isinstance(other, DictArray):
            other = other.materialize()
        if isinstance(other, np.ndarray):
            return self.materialize() == other
        return self._cmp(np.equal, other)

    def __ne__(self, other):  # type: ignore[override]
        if isinstance(other, DictArray):
            other = other.materialize()
        if isinstance(other, np.ndarray):
            return self.materialize() != other
        return self._cmp(np.not_equal, other)

    def __lt__(self, other):
        return self._cmp(np.less, other)

    def __le__(self, other):
        return self._cmp(np.less_equal, other)

    def __gt__(self, other):
        return self._cmp(np.greater, other)

    def __ge__(self, other):
        return self._cmp(np.greater_equal, other)

    def __hash__(self):  # __eq__ override kills the default
        return id(self)

    def isin(self, choices) -> np.ndarray:
        per_unique = np.isin(self.values, list(choices))
        return per_unique[self.codes]

    # ---------------------------------------------------------------------
    @classmethod
    def empty(cls) -> "DictArray":
        return cls(np.empty(0, dtype=np.int32), np.empty(0, dtype=object))

    @classmethod
    def from_objects(cls, arr) -> "DictArray":
        """Factorize an object/str sequence. pyarrow's C++ hash when the
        values are clean utf-8 str; a Python dict otherwise. None → code 0
        (callers track validity separately)."""
        if isinstance(arr, DictArray):
            return arr
        n = len(arr)
        if n == 0:
            return cls.empty()
        if pa is not None:
            try:
                a = pa.array(arr, type=pa.large_utf8(), from_pandas=True)
                d = a.dictionary_encode()
                idx = d.indices
                if idx.null_count:
                    idx = idx.fill_null(0)
                codes = np.asarray(idx.to_numpy(zero_copy_only=False),
                                   dtype=np.int64)
                values = np.array(d.dictionary.to_pylist(), dtype=object)
                return cls._normalize(codes, values)
            except (pa.ArrowInvalid, pa.ArrowTypeError):
                pass
        return cls._from_objects_py(arr)

    @classmethod
    def _from_objects_py(cls, arr) -> "DictArray":
        table: dict = {}
        codes = np.empty(len(arr), dtype=np.int64)
        for i, v in enumerate(arr):
            if v is None:
                v = ""
            elif isinstance(v, (bytes, bytearray)):
                v = bytes(v).decode("utf-8", "replace")
            c = table.get(v)
            if c is None:
                c = table[v] = len(table)
            codes[i] = c
        values = np.array(list(table.keys()), dtype=object)
        return cls._normalize(codes, values)

    @classmethod
    def _normalize(cls, codes: np.ndarray, values: np.ndarray) -> "DictArray":
        """Sort the dictionary (code order == string order) and remap."""
        if len(values) == 0:
            values = np.array([""], dtype=object)
            codes = np.zeros(len(codes), dtype=np.int64)
        order = np.argsort(values)  # O(U log U) Python compares — U small
        rank = np.empty(len(values), dtype=np.int64)
        rank[order] = np.arange(len(values))
        return cls(rank[codes].astype(np.int32), values[order])

    @classmethod
    def concat(cls, parts) -> "DictArray":
        """Concatenate parts (DictArray or object arrays) under one union
        dictionary. Codes remap through searchsorted — vectorized."""
        das = [p if isinstance(p, DictArray) else cls.from_objects(p)
               for p in parts]
        das = [d for d in das if len(d)]
        if not das:
            return cls.empty()
        if len(das) == 1:
            return das[0]
        union = unify_dictionaries(das)
        return cls(np.concatenate([d.remap_to(union) for d in das]), union)

    def remap_to(self, union_values: np.ndarray) -> np.ndarray:
        """codes re-expressed against a superset dictionary (sorted)."""
        if self.values is union_values:
            return self.codes
        mapping = np.searchsorted(union_values, self.values)
        return mapping[self.codes].astype(np.int32)


def unify_dictionaries(das: list) -> np.ndarray:
    """→ the sorted union dictionary over all parts. Non-mutating (decoded
    DictArrays can be shared through reader caches across concurrent
    scans); callers re-express codes via `d.remap_to(union)`.

    One hash-based dedup over Σ|U_i| then one sort of |U_union| — the
    previous np.unique(concatenate) sorted the full Σ|U_i| with Python
    compares, which dominated factorize_ms on multi-page assemblies.
    Parts sharing a dictionary object (scan-cache reuse) dedupe by id
    first so their uniques hash once."""
    vals = []
    seen_ids = set()
    for d in das:
        v = d.values
        if len(v) and id(v) not in seen_ids:
            seen_ids.add(id(v))
            vals.append(v)
    if not vals:
        return np.array([""], dtype=object)
    if len(vals) == 1:
        return vals[0]
    cat = np.concatenate(vals)
    if pa is not None:
        try:
            uniq = pa.array(cat, type=pa.large_utf8(),
                            from_pandas=False).unique().to_pylist()
            uniq.sort()
            out = np.empty(len(uniq), dtype=object)
            out[:] = uniq
            return out
        except Exception:
            pass  # non-str entries → the object-compare path below
    return np.unique(cat)


def dict_encode_strict(arr: np.ndarray) -> "DictArray | None":
    """Hash-encode an all-string object array through arrow (no null or
    non-str coercion — None on anything that isn't pure str, so callers
    keep their exact legacy semantics for mixed columns). Used by
    relational.group_indices to factorize string keys without the
    astype("U") copy + O(N log N) Python-compare sort."""
    if pa is None or not isinstance(arr, np.ndarray) or arr.dtype != object:
        return None
    try:
        pa_arr = pa.array(arr, type=pa.large_utf8(), from_pandas=False)
    except Exception:
        return None
    if pa_arr.null_count:
        return None
    enc = pa_arr.dictionary_encode()
    codes = enc.indices.to_numpy(zero_copy_only=False).astype(np.int64)
    values = np.array(enc.dictionary.to_pylist(), dtype=object)
    return DictArray._normalize(codes, values)


def as_object_array(vals) -> np.ndarray:
    """Materialize DictArray → object ndarray; pass plain arrays through."""
    if isinstance(vals, DictArray):
        return vals.materialize()
    return vals


def as_dict_part(vals) -> DictArray:
    """Coerce one merge part to a DictArray. Non-object numeric arrays are
    schema-evolution all-null placeholders (their valid mask is all False)."""
    if isinstance(vals, DictArray):
        return vals
    if isinstance(vals, np.ndarray) and vals.dtype != object:
        return DictArray(np.zeros(len(vals), dtype=np.int32),
                         np.array([""], dtype=object))
    return DictArray.from_objects(vals)
