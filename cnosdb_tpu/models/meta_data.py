"""Cluster placement metadata.

Mirrors common/models/src/meta_data.rs:73-157: a database's data is split
into time Buckets; each bucket has `shard_num` ReplicationSets (one raft
group each); each replica is a Vnode pinned to a node. Placement for a write
is (bucket by timestamp) → (shard by series hash_id % shard_count).
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field


class VnodeStatus(enum.IntEnum):
    RUNNING = 0
    COPYING = 1
    BROKEN = 2


@dataclass
class NodeInfo:
    id: int
    grpc_addr: str = ""
    http_addr: str = ""
    attributes: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"id": self.id, "grpc_addr": self.grpc_addr,
                "http_addr": self.http_addr, "attributes": self.attributes}

    @classmethod
    def from_dict(cls, d: dict) -> "NodeInfo":
        return cls(d["id"], d.get("grpc_addr", ""), d.get("http_addr", ""),
                   d.get("attributes", {}))


@dataclass
class VnodeInfo:
    id: int
    node_id: int
    status: VnodeStatus = VnodeStatus.RUNNING

    def to_dict(self) -> dict:
        return {"id": self.id, "node_id": self.node_id, "status": int(self.status)}

    @classmethod
    def from_dict(cls, d: dict) -> "VnodeInfo":
        return cls(d["id"], d["node_id"], VnodeStatus(d.get("status", 0)))


@dataclass
class ReplicationSet:
    id: int
    leader_node_id: int = 0
    leader_vnode_id: int = 0
    vnodes: list[VnodeInfo] = field(default_factory=list)

    def vnode(self, vnode_id: int) -> VnodeInfo | None:
        for v in self.vnodes:
            if v.id == vnode_id:
                return v
        return None

    def by_node(self, node_id: int) -> VnodeInfo | None:
        for v in self.vnodes:
            if v.node_id == node_id:
                return v
        return None

    def to_dict(self) -> dict:
        return {"id": self.id, "leader_node_id": self.leader_node_id,
                "leader_vnode_id": self.leader_vnode_id,
                "vnodes": [v.to_dict() for v in self.vnodes]}

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicationSet":
        return cls(d["id"], d.get("leader_node_id", 0), d.get("leader_vnode_id", 0),
                   [VnodeInfo.from_dict(v) for v in d.get("vnodes", [])])


@dataclass
class BucketInfo:
    id: int
    start_time: int  # ns, inclusive
    end_time: int    # ns, exclusive
    shard_group: list[ReplicationSet] = field(default_factory=list)

    def vnode_for(self, series_hash: int) -> ReplicationSet:
        """shard = hash % shard_count (reference meta_data.rs:81-85)."""
        return self.shard_group[series_hash % len(self.shard_group)]

    def contains(self, ts: int) -> bool:
        return self.start_time <= ts < self.end_time

    def to_dict(self) -> dict:
        return {"id": self.id, "start_time": self.start_time, "end_time": self.end_time,
                "shard_group": [r.to_dict() for r in self.shard_group]}

    @classmethod
    def from_dict(cls, d: dict) -> "BucketInfo":
        return cls(d["id"], d["start_time"], d["end_time"],
                   [ReplicationSet.from_dict(r) for r in d.get("shard_group", [])])
