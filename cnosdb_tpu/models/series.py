"""Series keys.

A series = measurement(table) + sorted tag set. Mirrors the reference's
SeriesKey (common/models/src/series_info.rs): stable binary encoding used as
the index key, and a BKDR hash for shard placement
(coordinator/src/service.rs:604-610 hashes table+tags to pick the shard).
"""
from __future__ import annotations

from dataclasses import dataclass

from ..utils.hash import bkdr_hash


@dataclass(frozen=True, order=True)
class Tag:
    key: str
    value: str


class SeriesKey:
    __slots__ = ("table", "tags", "_encoded", "_hash")

    def __init__(self, table: str, tags: list[Tag] | list[tuple[str, str]] | dict):
        if isinstance(tags, dict):
            tags = [Tag(k, v) for k, v in tags.items()]
        else:
            tags = [t if isinstance(t, Tag) else Tag(t[0], t[1]) for t in tags]
        tags = sorted(tags)
        self.table = table
        self.tags = tuple(tags)
        self._encoded: bytes | None = None
        self._hash: int | None = None

    # -- encoding --------------------------------------------------------
    def encode(self) -> bytes:
        """Stable binary encoding: len-prefixed table then k/v pairs."""
        if self._encoded is None:
            tb = self.table.encode()
            parts = [len(tb).to_bytes(2, "little"), tb]
            parts.append(len(self.tags).to_bytes(2, "little"))
            for t in self.tags:
                kb, vb = t.key.encode(), t.value.encode()
                parts += [len(kb).to_bytes(2, "little"), kb,
                          len(vb).to_bytes(4, "little"), vb]
            self._encoded = b"".join(parts)
        return self._encoded

    @classmethod
    def decode(cls, data: bytes) -> "SeriesKey":
        off = 0
        tl = int.from_bytes(data[off:off + 2], "little"); off += 2
        table = data[off:off + tl].decode(); off += tl
        n = int.from_bytes(data[off:off + 2], "little"); off += 2
        tags = []
        for _ in range(n):
            kl = int.from_bytes(data[off:off + 2], "little"); off += 2
            k = data[off:off + kl].decode(); off += kl
            vl = int.from_bytes(data[off:off + 4], "little"); off += 4
            v = data[off:off + vl].decode(); off += vl
            tags.append(Tag(k, v))
        return cls(table, tags)

    # -- identity --------------------------------------------------------
    def hash_id(self) -> int:
        """BKDR u64 used for shard placement (BucketInfo.vnode_for)."""
        if self._hash is None:
            self._hash = bkdr_hash(self.encode())
        return self._hash

    def tag_value(self, key: str) -> str | None:
        for t in self.tags:
            if t.key == key:
                return t.value
        return None

    def tag_dict(self) -> dict[str, str]:
        return {t.key: t.value for t in self.tags}

    def __eq__(self, other) -> bool:
        return (isinstance(other, SeriesKey)
                and self.table == other.table and self.tags == other.tags)

    def __hash__(self) -> int:
        return hash((self.table, self.tags))

    def __repr__(self) -> str:
        ts = ",".join(f"{t.key}={t.value}" for t in self.tags)
        return f"{self.table},{ts}"
