"""Write batches — the wire/WAL representation of point writes.

Role-parity with the reference's flatbuffers Points (common/protos/
proto/models.fbs, built by protocol_parser lines_convert.rs:20,197): rows
grouped per table and per series, columnar within a series. Grouping by
series at the parse edge keeps the vnode apply path allocation-free and
lets memcache append whole arrays.

Serialized with msgpack (C-speed) for WAL + RPC. Field values ride as
(value_type, values list) with None for missing-at-that-row.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import msgpack
import numpy as np

from .schema import ValueType
from .series import SeriesKey, Tag


@dataclass
class SeriesRows:
    """Rows of one series: parallel arrays, may be unsorted in time."""

    key: SeriesKey
    timestamps: list[int]
    fields: dict[str, tuple[int, list]]  # name → (ValueType, values; None=missing)

    def n_rows(self) -> int:
        return len(self.timestamps)


@dataclass
class WriteBatch:
    """table → list[SeriesRows]."""

    tables: dict[str, list[SeriesRows]] = field(default_factory=dict)

    def add_series(self, table: str, sr: SeriesRows):
        self.tables.setdefault(table, []).append(sr)

    def n_rows(self) -> int:
        return sum(sr.n_rows() for srs in self.tables.values() for sr in srs)

    # -- serde -----------------------------------------------------------
    def encode(self) -> bytes:
        obj = {}
        for table, srs in self.tables.items():
            obj[table] = [
                [sr.key.encode(), sr.timestamps,
                 {k: [vt, vals] for k, (vt, vals) in sr.fields.items()}]
                for sr in srs
            ]
        return msgpack.packb(obj, use_bin_type=True)

    @classmethod
    def decode(cls, data: bytes) -> "WriteBatch":
        obj = msgpack.unpackb(data, raw=False, strict_map_key=False)
        wb = cls()
        for table, srs in obj.items():
            for key_b, ts, fields in srs:
                wb.add_series(table, SeriesRows(
                    SeriesKey.decode(key_b), list(ts),
                    {k: (int(v[0]), list(v[1])) for k, v in fields.items()}))
        return wb

    # -- convenience builder (tests, SQL INSERT path) --------------------
    @classmethod
    def from_rows(cls, table: str, rows: list[dict], tag_names: list[str],
                  field_types: dict[str, ValueType]) -> "WriteBatch":
        """rows: [{'time': i64, <tag>: str, <field>: value}]"""
        groups: dict[SeriesKey, list[dict]] = {}
        for r in rows:
            key = SeriesKey(table, [Tag(t, str(r[t])) for t in tag_names if r.get(t) is not None])
            groups.setdefault(key, []).append(r)
        wb = cls()
        for key, rs in groups.items():
            ts = [int(r["time"]) for r in rs]
            fields = {}
            for fname, vt in field_types.items():
                vals = [r.get(fname) for r in rs]
                if any(v is not None for v in vals):
                    fields[fname] = (int(vt), vals)
            wb.add_series(table, SeriesRows(key, ts, fields))
        return wb
