"""Write batches — the wire/WAL representation of point writes.

Role-parity with the reference's flatbuffers Points (common/protos/
proto/models.fbs, built by protocol_parser lines_convert.rs:20,197): rows
grouped per table and per series, columnar within a series. Grouping by
series at the parse edge keeps the vnode apply path allocation-free and
lets memcache append whole arrays.

Serialized with msgpack (C-speed) for WAL + RPC. Field values ride as
(value_type, values list) with None for missing-at-that-row.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import msgpack
import numpy as np

from .schema import ValueType
from .series import SeriesKey, Tag


@dataclass
class SeriesRows:
    """Rows of one series: parallel arrays, may be unsorted in time.

    `timestamps` is a list[int] OR an np.int64 array; each field's values
    are a list (None = missing at that row) OR a typed numpy array, which
    asserts every row is present. Array form is the fast ingest path —
    it stays zero-copy through WAL encode (raw bytes) and memcache."""

    key: SeriesKey
    timestamps: list[int] | np.ndarray
    fields: dict[str, tuple[int, list | np.ndarray]]  # name → (ValueType, values)

    def n_rows(self) -> int:
        return len(self.timestamps)


def _check_field_value(vt: ValueType, v, fname: str):
    """Reject values a field type cannot hold at WRITE time (the
    reference fails the cast during planning: 'Can't cast value -3 to
    type UInt64'); deferring to flush would corrupt the memcache."""
    import numbers

    from .schema import SchemaError

    if v is None:
        return
    if vt == ValueType.UNSIGNED:
        if isinstance(v, bool) or not isinstance(
                v, (int, np.integer)) or int(v) < 0:
            raise SchemaError(
                f"can't cast value {v!r} to BIGINT UNSIGNED for {fname!r}")
    elif vt == ValueType.INTEGER:
        if isinstance(v, bool) or not isinstance(v, numbers.Real):
            raise SchemaError(
                f"can't cast value {v!r} to BIGINT for {fname!r}")

    elif vt == ValueType.FLOAT:
        if isinstance(v, bool) or not isinstance(v, numbers.Real):
            raise SchemaError(
                f"can't cast value {v!r} to DOUBLE for {fname!r}")
    elif vt == ValueType.BOOLEAN:
        # integers cast by truthiness and 'true'/'false' strings parse
        # (reference: update_field.slt sets f2_boolean = 3 and 'False')
        if isinstance(v, (bool, np.bool_, int, np.integer)):
            return
        if isinstance(v, str) and v.strip().lower() in (
                "true", "false", "t", "f", "yes", "no"):
            return
        raise SchemaError(
            f"can't cast value {v!r} to BOOLEAN for {fname!r}")
    elif vt in (ValueType.STRING, ValueType.GEOMETRY):
        if not isinstance(v, str):
            raise SchemaError(
                f"can't cast value {v!r} to STRING for {fname!r}")


def _time_ns(v) -> int:
    """Coerce a time cell to i64 ns: ints pass through; arrow/pandas
    Timestamp, datetime and datetime64 (COPY FROM csv/parquet type
    inference) convert exactly."""
    import datetime as _dt

    if isinstance(v, np.datetime64):
        return int(v.astype("datetime64[ns]").astype(np.int64))
    value = getattr(v, "value", None)   # pandas Timestamp: ns since epoch
    if value is not None and type(v).__name__ == "Timestamp":
        return int(value)
    if isinstance(v, _dt.datetime):
        if v.tzinfo is None:
            v = v.replace(tzinfo=_dt.timezone.utc)
        delta = v - _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
        secs = delta.days * 86400 + delta.seconds
        return secs * 1_000_000_000 + delta.microseconds * 1_000
    return int(v)


def ts_bounds(col) -> tuple[int, int]:
    """(min, max) of a timestamp column in either accepted representation
    (list[int] or np.int64 array); callers must ensure it is non-empty."""
    if isinstance(col, np.ndarray):
        return int(col.min()), int(col.max())
    return min(col), max(col)


def _enc_col(vals):
    """msgpack form of a column: numeric ndarray → tagged raw bytes
    (C-speed both ways), anything else → list."""
    if isinstance(vals, np.ndarray) and vals.dtype != object:
        return {"__nd__": vals.dtype.str, "b": vals.tobytes()}
    if isinstance(vals, np.ndarray):
        return vals.tolist()
    return vals


def _dec_col(v):
    if isinstance(v, dict):
        return np.frombuffer(v["b"], dtype=np.dtype(v["__nd__"]))
    return list(v)


# Reserved key carrying the schema stamp inside an encoded WriteBatch.
# "\x00" can never start a table name (idents are [A-Za-z_][A-Za-z0-9_]*),
# so the stamp cannot collide with user data; decoders that predate it
# would have treated it as a (never-matching) table entry.
META_KEY = "\x00meta"


@dataclass
class WriteBatch:
    """table → list[SeriesRows]."""

    tables: dict[str, list[SeriesRows]] = field(default_factory=dict)
    # schema stamp: table → {"sv": schema_version, "cols": {name: col_id}}
    # written by the vnode write path at WAL-append time; post-crash replay
    # uses it to re-key field names by column id when the live schema moved
    # (RENAME/DROP between the write and the crash).
    meta: dict = field(default_factory=dict)

    def add_series(self, table: str, sr: SeriesRows):
        self.tables.setdefault(table, []).append(sr)

    def n_rows(self) -> int:
        return sum(sr.n_rows() for srs in self.tables.values() for sr in srs)

    # -- serde -----------------------------------------------------------
    def encode(self) -> bytes:
        obj = {}
        for table, srs in self.tables.items():
            obj[table] = [
                [sr.key.encode(), _enc_col(sr.timestamps),
                 {k: [vt, _enc_col(vals)] for k, (vt, vals) in sr.fields.items()}]
                for sr in srs
            ]
        if self.meta:
            obj[META_KEY] = self.meta
        return msgpack.packb(obj, use_bin_type=True)

    @classmethod
    def decode(cls, data: bytes) -> "WriteBatch":
        obj = msgpack.unpackb(data, raw=False, strict_map_key=False)
        wb = cls()
        wb.meta = obj.pop(META_KEY, None) or {}
        for table, srs in obj.items():
            for key_b, ts, fields in srs:
                wb.add_series(table, SeriesRows(
                    SeriesKey.decode(key_b), _dec_col(ts),
                    {k: (int(v[0]), _dec_col(v[1])) for k, v in fields.items()}))
        return wb

    # -- schema stamp ----------------------------------------------------
    def stamp_schema(self, schemas: dict) -> None:
        """Record each written table's schema_version + the column ids of
        the written field names into `self.meta` (WAL-durable via encode).
        Post-crash replay compares the stamp against the live schema and
        re-keys fields by id, so rows written before a RENAME/DROP land
        under the column they were written to even when the old name was
        reused. Tables without a known schema stay unstamped (replay then
        keeps today's name-keyed behavior)."""
        for table, srs in self.tables.items():
            schema = schemas.get(table)
            if schema is None or table in self.meta:
                continue
            names = {n for sr in srs for n in sr.fields}
            cols = {n: schema.column(n).id for n in names
                    if schema.contains_column(n)}
            self.meta[table] = {"sv": schema.schema_version, "cols": cols}

    def replay_remap(self, table: str, schema) -> dict | None:
        """→ {written_name: current_name | None(dropped)} when this batch's
        stamp disagrees with the live schema; None when no re-keying is
        needed (no stamp, same version, or schema unknown)."""
        stamp = self.meta.get(table) if self.meta else None
        if not stamp or schema is None \
                or schema.schema_version == stamp.get("sv"):
            return None
        remap = {}
        changed = False
        for name, cid in (stamp.get("cols") or {}).items():
            col = schema.column_by_id(cid)
            remap[name] = None if col is None else col.name
            if col is None or col.name != name:
                changed = True
        return remap if changed else None

    # -- convenience builder (tests, SQL INSERT path) --------------------
    @classmethod
    def from_rows(cls, table: str, rows: list[dict], tag_names: list[str],
                  field_types: dict[str, ValueType]) -> "WriteBatch":
        """rows: [{'time': i64, <tag>: str, <field>: value}]"""
        groups: dict[SeriesKey, list[dict]] = {}
        for r in rows:
            key = SeriesKey(table, [Tag(t, str(r[t])) for t in tag_names if r.get(t) is not None])
            groups.setdefault(key, []).append(r)
        from .schema import SchemaError

        wb = cls()
        for key, rs in groups.items():
            ts = [_time_ns(r["time"]) for r in rs]
            fields = {}
            for fname, vt in field_types.items():
                vals = [r.get(fname) for r in rs]
                if any(v is not None for v in vals):
                    for v in vals:
                        _check_field_value(vt, v, fname)
                    # boolean columns cast ints (truthiness) and
                    # 'true'/'false' strings
                    if vt == ValueType.BOOLEAN:
                        vals = [None if v is None
                                else (v.strip().lower() in
                                      ("true", "t", "yes")
                                      if isinstance(v, str) else bool(v))
                                for v in vals]
                    if vt == ValueType.INTEGER:
                        # float literals cast by truncation toward zero
                        # (reference: INSERT 23.456 into BIGINT → 23);
                        # NaN/Inf cannot truncate
                        for v in vals:
                            if isinstance(v, float) and (
                                    v != v or v in (float("inf"),
                                                    float("-inf"))):
                                raise SchemaError(
                                    f"can't cast value {v!r} to BIGINT "
                                    f"for {fname!r}")
                        vals = [None if v is None else int(v)
                                for v in vals]
                    fields[fname] = (int(vt), vals)
            wb.add_series(table, SeriesRows(key, ts, fields))
        return wb
