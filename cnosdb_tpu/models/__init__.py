from .codec import Encoding, codecs_for
from .schema import (
    ValueType,
    ColumnType,
    TableColumn,
    TskvTableSchema,
    DatabaseSchema,
    DatabaseOptions,
    Precision,
    TenantOptions,
    Duration,
)
from .series import Tag, SeriesKey
from .predicate import (
    TimeRange,
    TimeRanges,
    Domain,
    RangeDomain,
    SetDomain,
    AllDomain,
    NoneDomain,
    ColumnDomains,
)
from .meta_data import NodeInfo, VnodeInfo, ReplicationSet, BucketInfo, VnodeStatus

__all__ = [
    "Encoding", "codecs_for",
    "ValueType", "ColumnType", "TableColumn", "TskvTableSchema",
    "DatabaseSchema", "DatabaseOptions", "Precision", "TenantOptions", "Duration",
    "Tag", "SeriesKey",
    "TimeRange", "TimeRanges", "Domain", "RangeDomain", "SetDomain",
    "AllDomain", "NoneDomain", "ColumnDomains",
    "NodeInfo", "VnodeInfo", "ReplicationSet", "BucketInfo", "VnodeStatus",
]
