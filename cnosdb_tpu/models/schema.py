"""Schemas: columns, tables, databases, tenants.

Mirrors the reference's schema model
(common/models/src/schema/{tskv_table_schema,database_schema,tenant}.rs):
- a table = one TIME column + tag columns + typed field columns, each with
  a column id and a codec;
- a database = owner(tenant) + options (ttl, shard, vnode_duration, replica,
  precision);
- tenants carry options/limiters.

TPU-first notes: every field type maps to a fixed-width device dtype
(STRING fields are dictionary-encoded to i32 codes before device transfer),
and the schema knows each column's numpy/jax dtype so scan batches can be
assembled without per-row branching.
"""
from __future__ import annotations

import enum
import json
import re
from dataclasses import dataclass, field as dc_field

import numpy as np

from ..errors import SchemaError, ColumnNotFound
from .codec import Encoding

TIME_FIELD_NAME = "time"


class Precision(enum.IntEnum):
    """Timestamp precision of a database (reference common/utils/src/precision.rs)."""

    MS = 0
    US = 1
    NS = 2

    def to_ns_factor(self) -> int:
        return {Precision.MS: 1_000_000, Precision.US: 1_000, Precision.NS: 1}[self]

    @classmethod
    def parse(cls, s: str) -> "Precision":
        return cls[s.strip().upper()]


class ValueType(enum.IntEnum):
    """Field value types (reference ValueType in tskv_table_schema.rs)."""

    UNKNOWN = 0
    FLOAT = 1      # f64
    INTEGER = 2    # i64
    UNSIGNED = 3   # u64
    BOOLEAN = 4
    STRING = 5
    GEOMETRY = 6

    def numpy_dtype(self):
        return {
            ValueType.FLOAT: np.float64,
            ValueType.INTEGER: np.int64,
            ValueType.UNSIGNED: np.uint64,
            ValueType.BOOLEAN: np.bool_,
            ValueType.STRING: object,
            ValueType.GEOMETRY: object,
        }[self]

    def device_dtype(self):
        """dtype as staged onto TPU; strings ride as dictionary codes."""
        return {
            ValueType.FLOAT: np.float64,
            ValueType.INTEGER: np.int64,
            ValueType.UNSIGNED: np.uint64,
            ValueType.BOOLEAN: np.bool_,
            ValueType.STRING: np.int32,
            ValueType.GEOMETRY: np.int32,
        }[self]

    @classmethod
    def parse(cls, s: str) -> "ValueType":
        m = {
            "DOUBLE": cls.FLOAT, "FLOAT": cls.FLOAT,
            "BIGINT": cls.INTEGER, "INTEGER": cls.INTEGER, "INT": cls.INTEGER,
            "BIGINT UNSIGNED": cls.UNSIGNED, "UNSIGNED": cls.UNSIGNED,
            "BOOLEAN": cls.BOOLEAN, "BOOL": cls.BOOLEAN,
            "STRING": cls.STRING, "TEXT": cls.STRING, "VARCHAR": cls.STRING,
            "GEOMETRY": cls.GEOMETRY,
        }
        key = s.strip().upper()
        if key.startswith("GEOMETRY("):
            return cls.GEOMETRY   # GEOMETRY(subtype, srid) — WKT strings
        if key not in m:
            raise SchemaError(f"unknown value type {s!r}")
        return m[key]

    def sql_name(self) -> str:
        return {
            ValueType.FLOAT: "DOUBLE",
            ValueType.INTEGER: "BIGINT",
            ValueType.UNSIGNED: "BIGINT UNSIGNED",
            ValueType.BOOLEAN: "BOOLEAN",
            ValueType.STRING: "STRING",
            ValueType.GEOMETRY: "GEOMETRY",
            ValueType.UNKNOWN: "UNKNOWN",
        }[self]


class ColumnKind(enum.IntEnum):
    TIME = 0
    TAG = 1
    FIELD = 2


@dataclass(frozen=True)
class ColumnType:
    kind: ColumnKind
    value_type: ValueType = ValueType.UNKNOWN
    precision: Precision = Precision.NS

    @classmethod
    def time(cls, precision: Precision = Precision.NS) -> "ColumnType":
        return cls(ColumnKind.TIME, ValueType.INTEGER, precision)

    @classmethod
    def tag(cls) -> "ColumnType":
        return cls(ColumnKind.TAG, ValueType.STRING)

    @classmethod
    def field(cls, vt: ValueType) -> "ColumnType":
        return cls(ColumnKind.FIELD, vt)

    @property
    def is_time(self) -> bool:
        return self.kind == ColumnKind.TIME

    @property
    def is_tag(self) -> bool:
        return self.kind == ColumnKind.TAG

    @property
    def is_field(self) -> bool:
        return self.kind == ColumnKind.FIELD


_IDENT_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")


@dataclass
class TableColumn:
    id: int
    name: str
    column_type: ColumnType
    encoding: Encoding = Encoding.DEFAULT
    # DDL gave an explicit CODEC(); DESCRIBE renders DEFAULT otherwise
    # (reference keeps Encoding::Default distinct from the resolved codec)
    explicit_codec: bool = False
    # GEOMETRY(subtype, srid): writes must match the declared subtype
    # (reference GeometryType in tskv_table_schema.rs)
    geom_subtype: str | None = None
    # previous names after ALTER ... RENAME COLUMN: storage chunks wrote
    # under these (the reference tracks columns by id; here names carry
    # the lineage so scans keep reading old files)
    prior_names: list = dc_field(default_factory=list)

    def default_encoding(self) -> Encoding:
        ct = self.column_type
        if ct.is_time:
            return Encoding.DELTA_TS
        if ct.is_tag:
            return Encoding.ZSTD
        return {
            ValueType.FLOAT: Encoding.GORILLA,
            ValueType.INTEGER: Encoding.DELTA,
            ValueType.UNSIGNED: Encoding.DELTA,
            ValueType.BOOLEAN: Encoding.BITPACK,
            ValueType.STRING: Encoding.ZSTD,
            ValueType.GEOMETRY: Encoding.ZSTD,
        }.get(ct.value_type, Encoding.DEFAULT)

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "name": self.name,
            "kind": int(self.column_type.kind),
            "value_type": int(self.column_type.value_type),
            "precision": int(self.column_type.precision),
            "encoding": int(self.encoding),
            "explicit_codec": self.explicit_codec,
            "geom_subtype": self.geom_subtype,
            "prior_names": self.prior_names,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TableColumn":
        return cls(
            id=d["id"],
            name=d["name"],
            column_type=ColumnType(
                ColumnKind(d["kind"]), ValueType(d["value_type"]), Precision(d["precision"])
            ),
            encoding=Encoding(d["encoding"]),
            explicit_codec=bool(d.get("explicit_codec", False)),
            geom_subtype=d.get("geom_subtype"),
            prior_names=list(d.get("prior_names") or []),
        )


class TskvTableSchema:
    """Table schema: time + tags + fields, each with stable column ids.

    Mirrors reference TskvTableSchema (tskv_table_schema.rs): schema_version
    bumps on ALTER, column ids never reused, field ids are the per-series
    column identity inside TSM chunks.
    """

    def __init__(self, tenant: str, db: str, name: str, columns: list[TableColumn],
                 schema_version: int = 0, next_column_id: int | None = None):
        self.tenant = tenant
        self.db = db
        self.name = name
        self.schema_version = schema_version
        self.columns: list[TableColumn] = []
        self._by_name: dict[str, TableColumn] = {}
        self._next_id = 0
        for c in columns:
            self._add(c)
        # Column ids are never reused, even across drop + serde round-trips,
        # so TSM chunks written under a dropped id can't be misread as a new
        # column. Persisted in to_dict/from_dict.
        if next_column_id is not None:
            self._next_id = max(self._next_id, next_column_id)

    # -- construction ----------------------------------------------------
    def _add(self, c: TableColumn) -> None:
        if c.name in self._by_name:
            raise SchemaError(f"duplicate column {c.name!r} in {self.name}")
        if not _IDENT_RE.match(c.name):
            raise SchemaError(f"invalid column name {c.name!r}")
        self.columns.append(c)
        self._by_name[c.name] = c
        self._next_id = max(self._next_id, c.id + 1)

    def add_column(self, name: str, column_type: ColumnType,
                   encoding: Encoding | None = None,
                   sorted_insert: bool = False) -> TableColumn:
        """`sorted_insert` keeps same-kind columns name-ordered — the
        line-protocol schema-inference path uses it (the reference's
        inferred schemas are BTreeMap-backed, so SELECT * over an
        lp-evolved table lists fields alphabetically); explicit ALTER ADD
        appends."""
        col = TableColumn(self._next_id, name, column_type,
                          encoding if encoding is not None else Encoding.DEFAULT)
        if encoding is None:
            col.encoding = col.default_encoding()
        # reusing a renamed-away name cuts the old column's lineage to it
        # (scans must never conflate the new column with historic chunks)
        for c in self.columns:
            if name in getattr(c, "prior_names", ()):
                c.prior_names = [x for x in c.prior_names if x != name]
        if sorted_insert:
            if col.name in self._by_name:
                raise SchemaError(
                    f"duplicate column {col.name!r} in {self.name}")
            if not _IDENT_RE.match(col.name):
                raise SchemaError(f"invalid column name {col.name!r}")
            pos = len(self.columns)
            for i, c in enumerate(self.columns):
                if c.column_type.is_time:
                    continue
                same_kind = c.column_type.is_tag == column_type.is_tag
                if same_kind and c.name > name:
                    pos = i
                    break
                if column_type.is_tag and not c.column_type.is_tag:
                    pos = i   # tags precede fields in the layout
                    break
            self.columns.insert(pos, col)
            self._by_name[col.name] = col
            self._next_id = max(self._next_id, col.id + 1)
        else:
            self._add(col)
        self.schema_version += 1
        return col

    def rename_column(self, old: str, new: str) -> TableColumn:
        """RENAME COLUMN: the column keeps its id — TSM chunks resolve
        fields by id (storage/scan.py), so historic data follows the
        rename even if `new` is later reused. `old` joins prior_names
        for the name-keyed surfaces (memcache rows, id-less chunks);
        reusing a renamed-away name cuts the other column's lineage to
        it, mirroring add_column."""
        col = self._by_name.get(old)
        if col is None:
            raise ColumnNotFound(f"{self.name}.{old}")
        if col.column_type.is_time:
            raise SchemaError("cannot rename the time column")
        if new in self._by_name:
            raise SchemaError(f"duplicate column {new!r} in {self.name}")
        if not _IDENT_RE.match(new):
            raise SchemaError(f"invalid column name {new!r}")
        for c in self.columns:
            if c is not col and new in getattr(c, "prior_names", ()):
                c.prior_names = [x for x in c.prior_names if x != new]
        del self._by_name[old]
        col.prior_names = [old] + [x for x in col.prior_names if x != old]
        col.name = new
        self._by_name[new] = col
        self.schema_version += 1
        return col

    def drop_column(self, name: str) -> TableColumn:
        col = self._by_name.get(name)
        if col is None:
            raise ColumnNotFound(f"{self.name}.{name}")
        if col.column_type.is_time:
            # validate BEFORE mutating: a failed drop must not remove the
            # name from the index (ALTER ... ADD FIELD time would then
            # slip past the duplicate check — alter_table.slt)
            raise SchemaError("cannot drop time column")
        self._by_name.pop(name)
        self.columns.remove(col)
        self.schema_version += 1
        return col

    # -- lookups ---------------------------------------------------------
    def column(self, name: str) -> TableColumn:
        c = self._by_name.get(name)
        if c is None:
            raise ColumnNotFound(f"{self.name}.{name}")
        return c

    def contains_column(self, name: str) -> bool:
        return name in self._by_name

    def column_by_id(self, cid: int) -> TableColumn | None:
        for c in self.columns:
            if c.id == cid:
                return c
        return None

    @property
    def time_column(self) -> TableColumn:
        for c in self.columns:
            if c.column_type.is_time:
                return c
        raise SchemaError(f"table {self.name} has no time column")

    @property
    def tag_columns(self) -> list[TableColumn]:
        return [c for c in self.columns if c.column_type.is_tag]

    @property
    def field_columns(self) -> list[TableColumn]:
        return [c for c in self.columns if c.column_type.is_field]

    def tag_names(self) -> list[str]:
        return [c.name for c in self.tag_columns]

    def field_names(self) -> list[str]:
        return [c.name for c in self.field_columns]

    # -- serde -----------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "tenant": self.tenant,
            "db": self.db,
            "name": self.name,
            "schema_version": self.schema_version,
            "next_column_id": self._next_id,
            "columns": [c.to_dict() for c in self.columns],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict) -> "TskvTableSchema":
        return cls(d["tenant"], d["db"], d["name"],
                   [TableColumn.from_dict(c) for c in d["columns"]],
                   d.get("schema_version", 0),
                   next_column_id=d.get("next_column_id"))

    @classmethod
    def from_json(cls, s: str) -> "TskvTableSchema":
        return cls.from_dict(json.loads(s))

    @classmethod
    def new_measurement(cls, tenant: str, db: str, name: str,
                        tags: list[str],
                        fields: list[tuple[str, ValueType]],
                        precision: Precision = Precision.NS,
                        sort_tags: bool = True) -> "TskvTableSchema":
        """Build a schema the way line-protocol auto-creation does
        (reference database.rs build_write_group schema inference).
        CREATE TABLE passes sort_tags=False: declared column order is the
        SELECT * order (reference preserves it; only line-protocol
        inference canonicalizes by sorting)."""
        cols = [TableColumn(0, TIME_FIELD_NAME, ColumnType.time(precision), Encoding.DELTA_TS)]
        nid = 1
        for t in (sorted(tags) if sort_tags else tags):
            cols.append(TableColumn(nid, t, ColumnType.tag(), Encoding.ZSTD))
            nid += 1
        for fname, vt in fields:
            c = TableColumn(nid, fname, ColumnType.field(vt))
            c.encoding = c.default_encoding()
            cols.append(c)
            nid += 1
        return cls(tenant, db, name, cols)


@dataclass
class Duration:
    """A time duration usable as TTL / vnode_duration (reference
    database_schema.rs DatabaseOptions durations, e.g. '1d', '365d', 'inf')."""

    ns: int  # 0 == INF unless zero=True
    # an EXPLICIT zero duration ('0', '0d') is distinct from INF:
    # drop_after '0' serializes as {secs:0, is_inf:false}
    # (dcl_tenant.slt) while TTL 'inf' retains forever
    zero: bool = False

    INF_NS = 0

    # humantime's unit values (the reference parses CnosDuration through
    # the humantime crate: y=365.25d, M=30.44d, m=minutes — case matters)
    _HUMANTIME_NS = {
        "ns": 1, "us": 1_000, "ms": 1_000_000,
        "s": 1_000_000_000, "sec": 1_000_000_000,
        "m": 60_000_000_000, "min": 60_000_000_000,
        "h": 3_600_000_000_000, "hr": 3_600_000_000_000,
        "d": 86_400_000_000_000, "day": 86_400_000_000_000,
        "days": 86_400_000_000_000,
        "w": 7 * 86_400_000_000_000, "week": 7 * 86_400_000_000_000,
        "M": 2_630_016_000_000_000, "month": 2_630_016_000_000_000,
        "months": 2_630_016_000_000_000,
        "y": 31_557_600_000_000_000, "year": 31_557_600_000_000_000,
        "years": 31_557_600_000_000_000,
        "minute": 60_000_000_000, "minutes": 60_000_000_000,
        "hour": 3_600_000_000_000, "hours": 3_600_000_000_000,
        "second": 1_000_000_000, "seconds": 1_000_000_000,
    }

    @classmethod
    def parse(cls, s: str) -> "Duration":
        raw = s.strip()
        if raw.lower() in ("inf", "none", ""):
            return cls(0)
        total = 0
        matched = False
        pos = 0
        for m in re.finditer(r"\s*(\d+)\s*([A-Za-z]+)\s*", raw):
            if m.start() != pos:
                raise SchemaError(f"bad duration {s!r}")
            pos = m.end()
            num, unit = m.group(1), m.group(2)
            # humantime is case-sensitive: 'M' is month, 'm' minute, and
            # '7Y' is invalid (dcl_tenant.slt pins it as an error)
            factor = cls._HUMANTIME_NS.get(unit)
            if factor is None:
                raise SchemaError(f"bad duration {s!r}")
            total += int(num) * factor
            matched = True
        if matched and pos != len(raw):
            raise SchemaError(f"bad duration {s!r}")   # trailing junk
        if not matched:
            m = re.match(r"^(\d+)$", raw)
            if not m:
                raise SchemaError(f"bad duration {s!r}")
            # unit-less number = DAYS (reference CnosDuration:
            # drop_after '7' serializes as 604800 secs)
            total = int(m.group(1)) * 86_400_000_000_000
        if total // 1_000_000_000 >= 2 ** 64:
            # the reference stores u64 SECONDS: u64::MAX days overflows
            # (dcl_tenant.slt) but '1000000d' TTLs are fine
            raise SchemaError(f"duration {s!r} overflows")
        return cls(total, zero=(total == 0))

    def humantime(self) -> str:
        """humantime::format_duration text — what the reference's
        DESCRIBE DATABASE and info-schema surfaces render."""
        if self.is_inf:
            return "INF"
        units = [("year", 31_557_600_000_000_000),
                 ("month", 2_630_016_000_000_000),
                 ("day", 86_400_000_000_000),
                 ("h", 3_600_000_000_000),
                 ("m", 60_000_000_000),
                 ("s", 1_000_000_000),
                 ("ms", 1_000_000), ("us", 1_000), ("ns", 1)]
        rem = self.ns
        parts = []
        for name, f in units:
            q, rem = divmod(rem, f)
            if q:
                if name in ("year", "month", "day"):
                    parts.append(f"{q}{name}" + ("s" if q > 1 else ""))
                else:
                    parts.append(f"{q}{name}")
        return " ".join(parts) if parts else "0s"

    @property
    def is_inf(self) -> bool:
        return self.ns == 0 and not self.zero

    def __str__(self) -> str:
        if self.is_inf:
            return "INF"
        d = 86_400_000_000_000
        if self.ns % d == 0:
            return f"{self.ns // d}d"
        return f"{self.ns}ns"


@dataclass
class DatabaseOptions:
    """Reference DatabaseOptions (database_schema.rs:109-176)."""

    ttl: Duration = dc_field(default_factory=lambda: Duration(0))
    shard_num: int = 1
    vnode_duration: Duration = dc_field(default_factory=lambda: Duration.parse("1y"))
    replica: int = 1
    precision: Precision = Precision.NS
    # storage-config surface DESCRIBE DATABASE exposes (create-time only)
    config: dict = dc_field(default_factory=dict)

    def to_dict(self) -> dict:
        # {ns, zero} shape (same as TenantOptions.drop_after): a bare int
        # loses the zero flag, so TTL '0' would reload as INF
        return {
            "ttl": {"ns": self.ttl.ns, "zero": self.ttl.zero},
            "shard_num": self.shard_num,
            "vnode_duration": {"ns": self.vnode_duration.ns,
                               "zero": self.vnode_duration.zero},
            "replica": self.replica, "precision": int(self.precision),
            "config": self.config,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DatabaseOptions":
        def dur(v) -> Duration:
            if isinstance(v, dict):
                return Duration(v["ns"], zero=bool(v.get("zero")))
            return Duration(v)   # legacy bare-int form
        out = cls(dur(d["ttl"]), d["shard_num"], dur(d["vnode_duration"]),
                  d["replica"], Precision(d["precision"]))
        out.config = dict(d.get("config") or {})
        return out


@dataclass
class DatabaseSchema:
    tenant: str
    name: str
    options: DatabaseOptions = dc_field(default_factory=DatabaseOptions)

    @property
    def owner(self) -> str:
        return make_owner(self.tenant, self.name)

    def to_dict(self) -> dict:
        return {"tenant": self.tenant, "name": self.name, "options": self.options.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "DatabaseSchema":
        return cls(d["tenant"], d["name"], DatabaseOptions.from_dict(d["options"]))


def make_owner(tenant: str, db: str) -> str:
    """owner id = 'tenant.db' (reference models::schema utils make_owner)."""
    return f"{tenant}.{db}"


@dataclass
class TenantOptions:
    comment: str = ""
    limiter: dict | None = None
    drop_after: Duration | None = None

    def to_dict(self) -> dict:
        da = None
        if self.drop_after is not None:
            da = {"ns": self.drop_after.ns, "zero": self.drop_after.zero}
        return {
            "comment": self.comment,
            "limiter": self.limiter,
            "drop_after": da,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "TenantOptions":
        da = d.get("drop_after")
        if isinstance(da, dict):
            da = Duration(da["ns"], zero=bool(da.get("zero")))
        elif da is not None:   # legacy int form
            da = Duration(da)
        return cls(d.get("comment", ""), d.get("limiter"), da)
