"""Column encodings.

Mirrors the reference's Encoding enum and per-type legal-codec tables
(common/models/src/codec.rs:5-54). Numeric discriminants are kept identical
so TSM files carry compatible ids.
"""
from __future__ import annotations

import enum


class Encoding(enum.IntEnum):
    DEFAULT = 0
    NULL = 1
    DELTA = 2
    QUANTILE = 3
    GZIP = 4
    BZIP = 5
    GORILLA = 6
    SNAPPY = 7
    ZSTD = 8
    ZLIB = 9
    BITPACK = 10
    DELTA_TS = 11
    UNKNOWN = 15

    @classmethod
    def from_str(cls, s: str) -> "Encoding":
        return cls[s.strip().upper()]


# Legal codecs per value type (codec.rs:5-34). QUANTILE maps to our
# zstd-of-deltas fallback (reference uses pco); SNAPPY maps to zlib level 1
# (no python-snappy in env) — ids preserved, implementation differs.
INTEGER_CODECS = (Encoding.DEFAULT, Encoding.NULL, Encoding.DELTA, Encoding.DELTA_TS, Encoding.QUANTILE)
TIMESTAMP_CODECS = INTEGER_CODECS
UNSIGNED_CODECS = INTEGER_CODECS
DOUBLE_CODECS = (Encoding.DEFAULT, Encoding.NULL, Encoding.GORILLA, Encoding.QUANTILE)
STRING_CODECS = (
    Encoding.DEFAULT, Encoding.NULL, Encoding.GZIP, Encoding.BZIP,
    Encoding.ZSTD, Encoding.SNAPPY, Encoding.ZLIB,
)
BOOLEAN_CODECS = (Encoding.DEFAULT, Encoding.NULL, Encoding.BITPACK)


def codecs_for(value_type: str):
    from .schema import ValueType

    vt = value_type if isinstance(value_type, str) else value_type.name
    table = {
        "TIMESTAMP": TIMESTAMP_CODECS,
        "TIME": TIMESTAMP_CODECS,
        "BIGINT": INTEGER_CODECS,
        "INTEGER": INTEGER_CODECS,
        "BIGINT_UNSIGNED": UNSIGNED_CODECS,
        "UNSIGNED": UNSIGNED_CODECS,
        "DOUBLE": DOUBLE_CODECS,
        "FLOAT": DOUBLE_CODECS,
        "STRING": STRING_CODECS,
        "GEOMETRY": STRING_CODECS,
        "BOOLEAN": BOOLEAN_CODECS,
        "TAG": STRING_CODECS,
    }
    key = vt.upper()
    if key not in table:
        return (Encoding.DEFAULT, Encoding.NULL)
    return table[key]
