"""Predicate pushdown domain algebra.

Mirrors the reference's `common/models/src/predicate/domain.rs`: a
`ColumnDomains` maps column name → Domain, where a Domain is All / None /
a set of ranges / a value set. The query planner extracts tag and time
constraints from WHERE into this algebra; the index evaluates tag domains
into series-id bitmaps (`index/ts_index.rs:397 get_series_ids_by_domains`)
and `TimeRanges` prunes buckets, files, chunks and pages
(`reader/iterator.rs:155-199`).

TPU-first: Domains also compile to vectorized numpy masks (host pruning)
and to jit-able predicate closures (device filtering in ops/filter.py).
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterable

I64_MIN = -(2 ** 63)
I64_MAX = 2 ** 63 - 1


# ---------------------------------------------------------------------------
# Time ranges
# ---------------------------------------------------------------------------
@dataclass(frozen=True, order=True)
class TimeRange:
    """Closed interval [min_ts, max_ts] in ns (reference TimeRange semantics)."""

    min_ts: int = I64_MIN
    max_ts: int = I64_MAX

    @classmethod
    def all(cls) -> "TimeRange":
        return cls(I64_MIN, I64_MAX)

    @property
    def is_empty(self) -> bool:
        return self.min_ts > self.max_ts

    def overlaps(self, other: "TimeRange") -> bool:
        return self.min_ts <= other.max_ts and other.min_ts <= self.max_ts

    def contains(self, ts: int) -> bool:
        return self.min_ts <= ts <= self.max_ts

    def includes(self, other: "TimeRange") -> bool:
        return self.min_ts <= other.min_ts and other.max_ts <= self.max_ts

    def intersect(self, other: "TimeRange") -> "TimeRange":
        return TimeRange(max(self.min_ts, other.min_ts), min(self.max_ts, other.max_ts))

    def merge(self, other: "TimeRange") -> "TimeRange":
        return TimeRange(min(self.min_ts, other.min_ts), max(self.max_ts, other.max_ts))


class TimeRanges:
    """Sorted, disjoint union of TimeRange (reference TimeRanges)."""

    def __init__(self, ranges: Iterable[TimeRange] = ()):  # normalizes
        rs = sorted(r for r in ranges if not r.is_empty)
        merged: list[TimeRange] = []
        for r in rs:
            if merged and r.min_ts <= merged[-1].max_ts + 1:
                merged[-1] = merged[-1].merge(r)
            else:
                merged.append(r)
        self.ranges: list[TimeRange] = merged

    @classmethod
    def all(cls) -> "TimeRanges":
        return cls([TimeRange.all()])

    @classmethod
    def empty(cls) -> "TimeRanges":
        return cls([])

    @property
    def is_empty(self) -> bool:
        return not self.ranges

    @property
    def is_all(self) -> bool:
        return len(self.ranges) == 1 and self.ranges[0] == TimeRange.all()

    @property
    def min_ts(self) -> int:
        return self.ranges[0].min_ts if self.ranges else I64_MAX

    @property
    def max_ts(self) -> int:
        return self.ranges[-1].max_ts if self.ranges else I64_MIN

    def overlaps(self, tr: TimeRange) -> bool:
        return any(r.overlaps(tr) for r in self.ranges)

    def contains(self, ts: int) -> bool:
        return any(r.contains(ts) for r in self.ranges)

    def includes(self, tr: TimeRange) -> bool:
        return any(r.includes(tr) for r in self.ranges)

    def intersect(self, other: "TimeRanges") -> "TimeRanges":
        out = []
        for a in self.ranges:
            for b in other.ranges:
                c = a.intersect(b)
                if not c.is_empty:
                    out.append(c)
        return TimeRanges(out)

    def union(self, other: "TimeRanges") -> "TimeRanges":
        return TimeRanges([*self.ranges, *other.ranges])

    def __iter__(self):
        return iter(self.ranges)

    def __repr__(self) -> str:
        return f"TimeRanges({self.ranges!r})"

    def to_wire(self) -> list:
        """msgpack-safe form for the cross-process scan plane."""
        return [[r.min_ts, r.max_ts] for r in self.ranges]

    @classmethod
    def from_wire(cls, w: list) -> "TimeRanges":
        return cls([TimeRange(a, b) for a, b in w])


# ---------------------------------------------------------------------------
# Value domains
# ---------------------------------------------------------------------------
class Domain:
    """Base class; subclasses: AllDomain, NoneDomain, RangeDomain, SetDomain."""

    def intersect(self, other: "Domain") -> "Domain":
        raise NotImplementedError

    def union(self, other: "Domain") -> "Domain":
        raise NotImplementedError

    def contains_value(self, v) -> bool:
        raise NotImplementedError


class AllDomain(Domain):
    def intersect(self, other: Domain) -> Domain:
        return other

    def union(self, other: Domain) -> Domain:
        return self

    def contains_value(self, v) -> bool:
        return True

    def __eq__(self, o):
        return isinstance(o, AllDomain)

    def __repr__(self):
        return "All"


class NoneDomain(Domain):
    def intersect(self, other: Domain) -> Domain:
        return self

    def union(self, other: Domain) -> Domain:
        return other

    def contains_value(self, v) -> bool:
        return False

    def __eq__(self, o):
        return isinstance(o, NoneDomain)

    def __repr__(self):
        return "None_"


@dataclass(frozen=True)
class ValueRange:
    """One range with open/closed bounds over an orderable python value."""

    low: object = None        # None = unbounded
    low_inclusive: bool = True
    high: object = None
    high_inclusive: bool = True

    @property
    def is_empty(self) -> bool:
        if self.low is None or self.high is None:
            return False
        if self.low > self.high:
            return True
        if self.low == self.high and not (self.low_inclusive and self.high_inclusive):
            return True
        return False

    def contains(self, v) -> bool:
        if self.low is not None:
            if v < self.low or (v == self.low and not self.low_inclusive):
                return False
        if self.high is not None:
            if v > self.high or (v == self.high and not self.high_inclusive):
                return False
        return True

    def intersect(self, o: "ValueRange") -> "ValueRange":
        low, li = self.low, self.low_inclusive
        if o.low is not None and (low is None or o.low > low or (o.low == low and not o.low_inclusive)):
            low, li = o.low, o.low_inclusive
        high, hi = self.high, self.high_inclusive
        if o.high is not None and (high is None or o.high < high or (o.high == high and not o.high_inclusive)):
            high, hi = o.high, o.high_inclusive
        return ValueRange(low, li, high, hi)

    def overlaps(self, o: "ValueRange") -> bool:
        return not self.intersect(o).is_empty


class RangeDomain(Domain):
    """Union of ValueRanges."""

    def __init__(self, ranges: Iterable[ValueRange]):
        self.ranges = [r for r in ranges if not r.is_empty]

    @classmethod
    def of(cls, low=None, low_inc=True, high=None, high_inc=True) -> "RangeDomain":
        return cls([ValueRange(low, low_inc, high, high_inc)])

    @classmethod
    def eq(cls, v) -> "RangeDomain":
        return cls([ValueRange(v, True, v, True)])

    @classmethod
    def gt(cls, v) -> "RangeDomain":
        return cls([ValueRange(v, False, None, True)])

    @classmethod
    def ge(cls, v) -> "RangeDomain":
        return cls([ValueRange(v, True, None, True)])

    @classmethod
    def lt(cls, v) -> "RangeDomain":
        return cls([ValueRange(None, True, v, False)])

    @classmethod
    def le(cls, v) -> "RangeDomain":
        return cls([ValueRange(None, True, v, True)])

    def intersect(self, other: Domain) -> Domain:
        if isinstance(other, AllDomain):
            return self
        if isinstance(other, NoneDomain):
            return other
        if isinstance(other, SetDomain):
            vals = {v for v in other.values if self.contains_value(v)}
            return SetDomain(vals) if vals else NoneDomain()
        if isinstance(other, LikeDomain):
            return self   # sound: the LIKE re-runs at execution
        assert isinstance(other, RangeDomain)
        out = []
        for a in self.ranges:
            for b in other.ranges:
                c = a.intersect(b)
                if not c.is_empty:
                    out.append(c)
        return RangeDomain(out) if out else NoneDomain()

    def union(self, other: Domain) -> Domain:
        if isinstance(other, (AllDomain, NoneDomain)):
            return other.union(self)
        if isinstance(other, SetDomain):
            # keep as range union (approximate upward: used for pruning, so
            # over-approximation is safe)
            return RangeDomain(self.ranges + [ValueRange(v, True, v, True) for v in other.values])
        if isinstance(other, LikeDomain):
            return other.union(self)
        assert isinstance(other, RangeDomain)
        return RangeDomain(self.ranges + other.ranges)

    def contains_value(self, v) -> bool:
        return any(r.contains(v) for r in self.ranges)

    def __eq__(self, o):
        return isinstance(o, RangeDomain) and self.ranges == o.ranges

    def __repr__(self):
        return f"Ranges({self.ranges!r})"


class SetDomain(Domain):
    """Explicit value set, e.g. tag IN ('a','b') (reference ValueEntry sets)."""

    def __init__(self, values: Iterable):
        self.values = frozenset(values)

    def intersect(self, other: Domain) -> Domain:
        if isinstance(other, (AllDomain, NoneDomain)):
            return other.intersect(self)
        if isinstance(other, SetDomain):
            vals = self.values & other.values
            return SetDomain(vals) if vals else NoneDomain()
        return other.intersect(self)

    def union(self, other: Domain) -> Domain:
        if isinstance(other, (AllDomain, NoneDomain)):
            return other.union(self)
        if isinstance(other, SetDomain):
            return SetDomain(self.values | other.values)
        return other.union(self)

    def contains_value(self, v) -> bool:
        return v in self.values

    def __eq__(self, o):
        return isinstance(o, SetDomain) and self.values == o.values

    def __repr__(self):
        return f"Set({sorted(self.values)!r})"


class LikeDomain(Domain):
    """Values matching a LIKE pattern (tag LIKE '%x%' pushed into the
    series index, evaluated per-unique over the tag dictionary). Algebra
    is a sound over-approximation: intersect keeps the more selective
    side exactly, union widens to All — rows admitted here are always
    re-checked by the full predicate at execution."""

    def __init__(self, pattern: str):
        self.pattern = pattern
        self._rx = None

    def _regex(self):
        # models/ cannot import ops/ (jax); this mirrors the host LIKE
        # automaton at sql.expr.Like._compile, pinned by a parity test
        if self._rx is None:
            out = []
            for ch in self.pattern:
                if ch == "%":
                    out.append(".*")
                elif ch == "_":
                    out.append(".")
                else:
                    out.append(re.escape(ch))
            self._rx = re.compile("^" + "".join(out) + "$", re.DOTALL)
        return self._rx

    def intersect(self, other: Domain) -> Domain:
        if isinstance(other, AllDomain):
            return self
        if isinstance(other, NoneDomain):
            return other
        if isinstance(other, SetDomain):
            vals = {v for v in other.values if self.contains_value(v)}
            return SetDomain(vals) if vals else NoneDomain()
        # range ∧ like: keep the range (sound; the LIKE re-runs at exec)
        return other

    def union(self, other: Domain) -> Domain:
        if isinstance(other, NoneDomain):
            return self
        return AllDomain()

    def contains_value(self, v) -> bool:
        return isinstance(v, str) and bool(self._regex().match(v))

    def __eq__(self, o):
        return isinstance(o, LikeDomain) and self.pattern == o.pattern

    def __repr__(self):
        return f"Like({self.pattern!r})"


class ColumnDomains:
    """column name → Domain; conjunction across columns.

    `is_all` ⇒ no constraint; `is_none` ⇒ provably empty result.
    """

    def __init__(self, domains: dict[str, Domain] | None = None, none: bool = False):
        self._none = none
        self.domains: dict[str, Domain] = dict(domains or {})

    @classmethod
    def all(cls) -> "ColumnDomains":
        return cls()

    @classmethod
    def none(cls) -> "ColumnDomains":
        return cls(none=True)

    @classmethod
    def of(cls, column: str, domain: Domain) -> "ColumnDomains":
        return cls({column: domain})

    @property
    def is_all(self) -> bool:
        return not self._none and not self.domains

    @property
    def is_none(self) -> bool:
        return self._none

    def get(self, column: str) -> Domain:
        if self._none:
            return NoneDomain()
        return self.domains.get(column, AllDomain())

    def insert_or_intersect(self, column: str, domain: Domain) -> None:
        cur = self.domains.get(column)
        d = domain if cur is None else cur.intersect(domain)
        if isinstance(d, NoneDomain):
            self._none = True
        self.domains[column] = d

    def intersect(self, other: "ColumnDomains") -> "ColumnDomains":
        if self.is_none or other.is_none:
            return ColumnDomains.none()
        out = ColumnDomains(dict(self.domains))
        for col, d in other.domains.items():
            out.insert_or_intersect(col, d)
        return out

    def union(self, other: "ColumnDomains") -> "ColumnDomains":
        """Column-wise union; only columns constrained on BOTH sides stay
        constrained (sound over-approximation for OR)."""
        if self.is_none:
            return other
        if other.is_none:
            return self
        out = ColumnDomains()
        for col in set(self.domains) & set(other.domains):
            out.domains[col] = self.domains[col].union(other.domains[col])
        return out

    def to_wire(self) -> dict:
        return {"none": self._none,
                "cols": {c: domain_to_wire(d) for c, d in self.domains.items()}}

    @classmethod
    def from_wire(cls, w: dict) -> "ColumnDomains":
        return cls({c: domain_from_wire(d) for c, d in w["cols"].items()},
                   none=w["none"])

    def __repr__(self):
        if self.is_none:
            return "ColumnDomains(NONE)"
        return f"ColumnDomains({self.domains!r})"


def domain_to_wire(d: Domain) -> list:
    """msgpack-safe tagged form mirroring the reference's domain protobufs."""
    if isinstance(d, AllDomain):
        return ["all"]
    if isinstance(d, NoneDomain):
        return ["none"]
    if isinstance(d, RangeDomain):
        return ["range", [[r.low, r.low_inclusive, r.high, r.high_inclusive]
                          for r in d.ranges]]
    if isinstance(d, SetDomain):
        return ["set", sorted(d.values)]
    if isinstance(d, LikeDomain):
        return ["like", d.pattern]
    raise TypeError(f"unknown domain {type(d).__name__}")


def domain_from_wire(w: list) -> Domain:
    tag = w[0]
    if tag == "all":
        return AllDomain()
    if tag == "none":
        return NoneDomain()
    if tag == "range":
        return RangeDomain([ValueRange(lo, li, hi, hic)
                            for lo, li, hi, hic in w[1]])
    if tag == "like":
        return LikeDomain(w[1])
    return SetDomain(w[1])
