"""Vectorized grouped-aggregation plane: key factorization, sort-based
DISTINCT, and segment reductions.

The per-row Python accumulation paths (dict-of-set DISTINCT, scalar
min/max folds, per-hole gapfill) are the slowest thing the SQL layer
does — the opposite of the design, which wants grouped reductions over
dense integer codes (the shape both numpy and the TPU segment kernels
win at). This module is the shared engine:

  factorize      value column → dense int64 codes + dictionary, once
  distinct_count unique (group, value) code pairs + bincount
  group_min_max  ufunc.at / unique-code reductions, no scalar folds
  grouped_order  argsort + boundaries → bulk per-group slices (collect)
  device_*       jax segment-sum-family kernels over the same codes
                 (ops/kernels.py), partial pairs merged host-side via
                 parallel/distributed_agg.py — the wire format of the
                 multi-chip partials is unchanged

Counters are always on (cheap dict bumps) and surface on /metrics as
cnosdb_group_agg_total{kind=...}; bench stage timings (factorize_ms,
group_count, distinct_path.*) ride utils.stages when enabled.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from ..utils import stages
from ..utils import lockwatch

_LOCK = lockwatch.Lock("group_agg.plan_cache")
_COUNTERS: dict[str, int] = {}


def _count(name: str, n: int = 1) -> None:
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def counters_snapshot() -> dict[str, int]:
    with _LOCK:
        return dict(sorted(_COUNTERS.items()))


# ---------------------------------------------------------------------------
# key factorization
# ---------------------------------------------------------------------------
@dataclass
class Factorization:
    codes: np.ndarray        # int64 [n], dense in [0, n_values)
    values: np.ndarray       # dictionary, values[codes] reproduces input
    n_values: int


def _object_kinds(arr: np.ndarray):
    """The set of element types in an object column (None excluded).
    C-level map(type) pass — the check that decides whether sort-based
    factorization preserves Python set/equality semantics."""
    return set(map(type, arr.tolist())) - {type(None)}


def factorize(arr: np.ndarray) -> Factorization | None:
    """Dense integer codes for one value column, or None when the column
    can't be factorized without changing Python equality semantics
    (mixed-type object payloads — the caller keeps its scalar fold).

    Invariants the DISTINCT/min-max paths rely on:
      - codes are dense in [0, n_values)
      - values is sorted ascending, so code order == value order
        (group min = values[min code], the string-agg rank trick)
      - equality of codes == Python `==` of the original elements
    """
    with stages.stage("factorize_ms"):
        if arr.dtype != object:
            vals, inv = np.unique(arr, return_inverse=True)
            return Factorization(inv.astype(np.int64).ravel(), vals,
                                 len(vals))
        kinds = _object_kinds(arr)
        if not kinds:
            return Factorization(np.zeros(len(arr), dtype=np.int64),
                                 np.empty(0, dtype=object), 0)
        if kinds <= {str, np.str_}:
            # homogeneous strings: numpy 'U' compare (C speed) is exactly
            # str equality
            vals, inv = np.unique(arr.astype("U"), return_inverse=True)
            dic = vals.astype(object)
        elif all(issubclass(k, (int, np.integer, np.bool_))
                 for k in kinds):
            # ints (+ bools: Python sets treat True == 1, and so does the
            # int64 cast); bigints overflow → scalar fallback
            try:
                vals, inv = np.unique(
                    np.array(arr.tolist(), dtype=np.int64),
                    return_inverse=True)
            except (OverflowError, ValueError, TypeError):
                _count("factorize_fallback")
                return None
            dic = vals.astype(object)
        elif all(issubclass(k, (int, float, np.integer, np.floating,
                                np.bool_)) for k in kinds):
            # mixed numerics: float64 compare matches Python == up to
            # 2^53; NaN payloads keep set-identity semantics → fall back
            flt = np.array([float(v) for v in arr.tolist()])
            if np.isnan(flt).any() or (np.abs(flt) >= 2.0 ** 53).any():
                _count("factorize_fallback")
                return None
            vals, inv = np.unique(flt, return_inverse=True)
            dic = vals.astype(object)
        else:
            _count("factorize_fallback")
            return None
        return Factorization(inv.astype(np.int64).ravel(), dic, len(vals))


def combine_codes(parts: list[tuple[np.ndarray, int]]) -> tuple[np.ndarray,
                                                                int]:
    """Chain per-axis dense codes into one combined code:
    ((c0·d1 + c1)·d2 + c2)… — the same layout the segment kernels use.
    Falls back to re-densifying via np.unique when the cardinality
    product would overflow int64."""
    codes = None
    dim = 1
    for c, d in parts:
        d = max(int(d), 1)
        if codes is None:
            codes, dim = c.astype(np.int64), d
            continue
        if dim > (2 ** 62) // max(d, 1):
            # re-densify the prefix before the product overflows
            uniq, codes = np.unique(codes, return_inverse=True)
            codes = codes.astype(np.int64)
            dim = len(uniq)
        codes = codes * d + c
        dim = dim * d
    if codes is None:
        return np.zeros(0, dtype=np.int64), 1
    return codes, dim


# ---------------------------------------------------------------------------
# sort-based DISTINCT
# ---------------------------------------------------------------------------
def distinct_pairs(gid: np.ndarray, vcodes: np.ndarray,
                   n_values: int) -> np.ndarray:
    """Sorted unique (group, value) pair codes: pair = gid·n_values + vc.
    This is the DISTINCT partial — mergeable across batches/shards by
    concatenate + unique (parallel.distributed_agg.merge_distinct_pairs)."""
    nv = max(int(n_values), 1)
    return np.unique(gid.astype(np.int64) * nv + vcodes)


def distinct_count(gid: np.ndarray, values: np.ndarray,
                   n_groups: int) -> np.ndarray | None:
    """count(DISTINCT values) per group — sort-based, no per-row sets.
    `values` must already be filtered to valid (non-NULL) rows aligned
    with `gid`. Returns None when the payload defeats factorization
    (caller keeps its scalar fold)."""
    f = factorize(values)
    if f is None:
        _count("distinct_fallback")
        stages.count("distinct_path.fallback")
        return None
    if device_enabled() and len(gid) >= 65536:
        out = _device_distinct_count(gid, f.codes, n_groups, f.n_values)
        if out is not None:
            _count("distinct_device")
            stages.count("distinct_path.device")
            return out
    pairs = distinct_pairs(gid, f.codes, f.n_values)
    out = np.bincount((pairs // max(f.n_values, 1)).astype(np.int64),
                      minlength=n_groups).astype(np.int64)
    _count("distinct_sort")
    stages.count("distinct_path.sort")
    return out[:n_groups]


# ---------------------------------------------------------------------------
# vectorized min / max (incl. object columns via the sorted-dictionary
# invariant: code order == value order)
# ---------------------------------------------------------------------------
def group_min_max(func: str, gid: np.ndarray, values: np.ndarray,
                  n_groups: int) -> tuple[np.ndarray, np.ndarray] | None:
    """→ (per-group result, filled mask) or None (unfactorizable object
    payload). `values` pre-filtered to valid rows aligned with gid."""
    filled = np.bincount(gid, minlength=n_groups) > 0 if len(gid) \
        else np.zeros(n_groups, dtype=bool)
    if values.dtype == object:
        f = factorize(values)
        if f is None:
            return None
        red = np.minimum if func == "min" else np.maximum
        init = f.n_values if func == "min" else -1
        best = np.full(n_groups, init, dtype=np.int64)
        red.at(best, gid, f.codes)
        out = np.full(n_groups, None, dtype=object)
        ok = filled & (best >= 0) & (best < f.n_values)
        if ok.any():
            out[ok] = f.values[best[ok]]
        return out, filled
    if np.issubdtype(values.dtype, np.floating):
        init = np.inf if func == "min" else -np.inf
        best = np.full(n_groups, init, dtype=values.dtype)
    elif values.dtype == bool:
        return group_min_max(func, gid, values.astype(np.int64), n_groups)
    else:
        info = np.iinfo(values.dtype)
        best = np.full(n_groups, info.max if func == "min" else info.min,
                       dtype=values.dtype)
    red = np.minimum if func == "min" else np.maximum
    red.at(best, gid, values)
    return best, filled


# ---------------------------------------------------------------------------
# bulk per-group slicing (collect / collect_ts / collect2)
# ---------------------------------------------------------------------------
def grouped_order(gid: np.ndarray) -> tuple[np.ndarray, np.ndarray,
                                            np.ndarray]:
    """→ (order, boundaries, group_code_per_run): a stable argsort of the
    group codes plus run boundaries, so callers slice each group's rows
    in bulk (arr[order[s:e]]) instead of appending row by row."""
    order = np.argsort(gid, kind="stable")
    sg = gid[order]
    if not len(sg):
        return order, np.zeros(1, dtype=np.int64), sg
    starts = np.nonzero(np.concatenate((
        [True], sg[1:] != sg[:-1])))[0]
    bounds = np.append(starts, len(sg)).astype(np.int64)
    return order, bounds, sg[starts]


# ---------------------------------------------------------------------------
# device path: jax segment-sum-family kernels over the same dense codes
# ---------------------------------------------------------------------------
def device_enabled() -> bool:
    """Route large dense-coded reductions through the jax segment kernels?
    Default: only on a real accelerator scan device (XLA's CPU scatter
    lowering loses to numpy); CNOSDB_TPU_GROUP_AGG=1 forces on (CI runs
    the device code on the CPU backend), =0 forces off."""
    import os

    mode = os.environ.get("CNOSDB_TPU_GROUP_AGG", "auto").lower()
    if mode in ("1", "on", "true"):
        return True
    if mode in ("0", "off", "false"):
        return False
    try:
        from .placement import scan_device

        return scan_device().platform == "tpu"
    except Exception:
        return False


def _device_distinct_count(gid: np.ndarray, vcodes: np.ndarray,
                           n_groups: int, n_values: int,
                           chunk_rows: int = 1 << 22) -> np.ndarray | None:
    """Sort-based DISTINCT on the accelerator: per chunk the device sorts
    the (group, value) pair codes (ops/kernels.segment_distinct_count for
    the single-chunk case); multi-chunk/multi-shard partial pairs merge
    host-side (parallel.distributed_agg.merge_distinct_pairs) so the
    on-wire partial shape is the plain sorted pair-code array."""
    try:
        from . import kernels
        from ..parallel.distributed_agg import merge_distinct_pairs

        nv = max(int(n_values), 1)
        n = len(gid)
        if n == 0:
            return np.zeros(n_groups, dtype=np.int64)
        if n <= chunk_rows:
            # segment_distinct_count already materializes host i64 counts
            # sliced to n_groups — re-wrapping it was a second copy
            return kernels.segment_distinct_count(gid, vcodes, n_groups, nv)
        chunks = []
        for off in range(0, n, chunk_rows):
            e = min(off + chunk_rows, n)
            chunks.append(kernels.sorted_pair_codes(
                gid[off:e], vcodes[off:e], nv))
        return merge_distinct_pairs(chunks, nv, n_groups)
    except Exception:
        _count("distinct_device_error")
        return None


def device_segment_reduce(values: np.ndarray, valid: np.ndarray,
                          seg_ids: np.ndarray, num_segments: int,
                          wants: dict) -> dict | None:
    """Dense-coded segment reductions (count/sum/min/max) through the
    jax.ops.segment_sum-family kernels with padded row/group counts —
    the TPU twin of the numpy reduceat path. Returns None when jax is
    unavailable so callers keep the host kernels."""
    try:
        from . import kernels

        return kernels.aggregate_column_host(
            values, valid, seg_ids.astype(np.int32),
            np.zeros(len(values), dtype=np.int32), num_segments, wants)
    except Exception:
        return None
