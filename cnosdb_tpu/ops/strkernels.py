"""Vectorized string/search plane (ROADMAP item 4).

String predicates on dictionary-encoded columns (`models.strcol.DictArray`)
are evaluated once per UNIQUE value and broadcast to rows through the
codes — the encoded-data evaluation argument of "GPU Acceleration of SQL
Analytics on Compressed Data" (PAPERS.md) applied to strings. Three lanes,
all reason-booked into ``cnosdb_string_filter_total{path,reason}``:

``per_unique``
    A LIKE pattern is compiled into one of five predicate classes —
    ``exact`` / ``prefix`` / ``suffix`` / ``contains`` (vectorized
    ``np.char`` kernels over the unique table) or ``regex`` (the host
    regex once per unique) — producing a boolean mask over the
    dictionary that a single integer gather (``mask[codes]``, or
    ``ops.kernels.dict_mask_gather`` when the codes live on device)
    turns into the row mask.  ``cmp`` is the same trick for comparison
    predicates over str-func chains (substr-equality et al), driven from
    ``sql.expr``.

``ngram_skip``
    Per-page trigram bloom signatures (built by ``storage.tsm`` at
    flush/compaction time, checked by ``storage.scan._page_admits``)
    prune whole string pages before decode for ``LIKE '%x%'``-shaped
    filters.  Format: byte trigrams over the UTF-8 encoding of each
    distinct page value, inserted into ``utils.bloom.BloomFilter`` sized
    at 16 bits/trigram (pow2-rounded, capped at 8 KiB per page); an
    empty signature means the page provably holds no 3-byte substring.

``host_fallback``
    The per-row host evaluator ran; the reason names why the per-unique
    lane could not (``unencoded_rows``, ``dynamic_pattern``,
    ``non_string_uniques``, ``lane_disabled``).

The module also hosts the select-then-gather top-K used by
``executor._order_limit`` (ORDER BY <key> LIMIT k): a k-th order
statistic (``np.partition`` on host, ``jax.lax.top_k`` on TPU) selects
candidate rows, which are then ordered with exactly the stable-lexsort
tie semantics of the full sort.

Accounting invariant (enforced by the ``string-filter-accounting`` lint
rule): every early return out of the lane books a path/reason — silent
per-row fallbacks are the regression this plane exists to remove.
"""
from __future__ import annotations

import functools
import os
import re
import threading

import numpy as np

from ..utils import stages
from ..utils.bloom import BloomFilter

# ---------------------------------------------------------------------------
# engagement + outcome accounting (mirrors ops.device_decode)
# ---------------------------------------------------------------------------
_LOCK = threading.Lock()
_engagements = 0
_outcomes: dict[tuple[str, str], int] = {}


def enabled() -> bool:
    """CNOSDB_STR_LANE=0 routes LIKE back to the per-unique regex path
    (the pre-plane behavior) — the bench A/B and parity-oracle knob."""
    return os.environ.get("CNOSDB_STR_LANE", "1").lower() \
        not in ("0", "off", "false")


def note_engaged(n: int = 1) -> None:
    global _engagements
    with _LOCK:
        _engagements += n


def engagements() -> int:
    """Predicates answered by the per-unique/ngram lanes this process
    (bench.py reports this as string_filter_engagements)."""
    with _LOCK:
        return _engagements


def note_path(path: str, reason: str, n: int = 1) -> None:
    """Book n predicate evaluations as handled by `path` for `reason` —
    the raw series behind cnosdb_string_filter_total."""
    with _LOCK:
        _outcomes[(path, reason)] = _outcomes.get((path, reason), 0) + n
    stages.count(f"string_path.{path}", n)
    if path in ("per_unique", "ngram_skip"):
        note_engaged(n)


def outcomes_snapshot() -> dict[tuple[str, str], int]:
    with _LOCK:
        return dict(sorted(_outcomes.items()))


# ---------------------------------------------------------------------------
# LIKE compilation
# ---------------------------------------------------------------------------
def compile_like(pattern: str):
    """The host LIKE automaton (sql.expr.Like._compile, pinned bit-for-bit
    by tests/test_strkernels.py): % → .*, _ → ., everything else literal,
    DOTALL-anchored — note `$` also accepts a trailing newline, which the
    vectorized classes below must (and do) reproduce."""
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def classify(pattern: str) -> tuple[str, str | None]:
    """→ (kind, needle): 'exact'/'prefix'/'suffix'/'contains' with the
    wildcard-free needle, or ('generic', None) for anything with `_` or
    an interior `%` (those take the per-unique regex lane)."""
    if "_" in pattern:
        return "generic", None
    a = 0
    while a < len(pattern) and pattern[a] == "%":
        a += 1
    core = pattern[a:]
    b = 0
    while core and core[-1] == "%":
        core = core[:-1]
        b += 1
    if "%" in core:
        return "generic", None
    if a and b:
        return "contains", core
    if a:
        return "suffix", core
    if b:
        return "prefix", core
    return "exact", core


def _all_str(values: np.ndarray) -> bool:
    return all(isinstance(x, str) for x in values.tolist())


def unique_mask(values: np.ndarray, pattern: str,
                rx=None) -> tuple[np.ndarray, str]:
    """Boolean LIKE mask over a dictionary's unique table → (mask, reason).

    Vectorized np.char kernels for the four literal classes; the host
    regex once per unique otherwise.  Bit-identical to the host
    evaluator, including its `$`-accepts-trailing-newline quirk (an
    exact/suffix needle also matches `needle + "\\n"`)."""
    kind, needle = classify(pattern)
    if kind != "generic" and _all_str(values):
        u = np.asarray(values, dtype=str)
        if kind == "exact":
            mask = (u == needle) | (u == needle + "\n")
        elif kind == "prefix":
            mask = np.char.startswith(u, needle)
        elif kind == "suffix":
            mask = np.char.endswith(u, needle) \
                | np.char.endswith(u, needle + "\n")
        else:   # contains
            mask = np.char.find(u, needle) >= 0
        note_path("per_unique", kind)
        return mask, kind
    if rx is None:
        rx = compile_like(pattern)
    mask = np.fromiter(
        (bool(rx.match(x)) if isinstance(x, str) else False for x in values),
        dtype=bool, count=len(values))
    reason = "regex" if kind == "generic" else "non_string_uniques"
    note_path("per_unique", reason)
    return mask, reason


def broadcast_codes(mask: np.ndarray, codes) -> np.ndarray:
    """Per-unique mask → row mask. Host codes take the numpy gather;
    device-resident codes stay on device via ops.kernels."""
    if isinstance(codes, np.ndarray):
        return mask[codes]
    from . import kernels

    return kernels.dict_mask_gather(mask, codes)


def like_rows(da, pattern: str, rx=None, negated: bool = False) -> np.ndarray:
    """Row mask for ``da LIKE pattern`` over a DictArray (sql.expr.Like's
    dictionary routing target). Negation applies to the unique mask — it
    commutes with the gather."""
    mask, _reason = unique_mask(da.values, pattern, rx)
    if negated:
        mask = ~mask
    return broadcast_codes(mask, da.codes)


def unique_surrogate(da):
    """A one-row-per-unique twin of `da`: evaluating any scalar expr tree
    against it yields per-unique results to gather through `da.codes` —
    how substr-equality and friends ride the per-unique lane without
    reimplementing host scalar semantics."""
    from ..models.strcol import DictArray

    return DictArray(np.arange(len(da.values), dtype=np.int32), da.values)


# ---------------------------------------------------------------------------
# trigram page-skip signatures
# ---------------------------------------------------------------------------
NGRAM = 3
_MAX_QUERY_TRIGRAMS = 32          # probes per page check (subset = sound)
_SIG_MIN_BITS = 1 << 10
_SIG_MAX_BITS = 1 << 16           # 8 KiB/page ceiling
_BITS_PER_TRIGRAM = 16            # fp ≈ 0.2% at k=4


def _trigrams(b: bytes) -> set[bytes]:
    return {b[i:i + NGRAM] for i in range(len(b) - (NGRAM - 1))}


def literal_runs(pattern: str) -> list[str]:
    """Wildcard-free literal substrings any match must contain, in order
    (`%` and `_` both break runs — `_` matches one arbitrary char, so
    trigrams across it are not required)."""
    runs: list[str] = []
    cur: list[str] = []
    for ch in pattern:
        if ch in ("%", "_"):
            if cur:
                runs.append("".join(cur))
                cur = []
        else:
            cur.append(ch)
    if cur:
        runs.append("".join(cur))
    return runs


@functools.lru_cache(maxsize=512)
def value_trigrams(s: str) -> tuple[bytes, ...]:
    """Required trigrams for string EQUALITY with `s` (no wildcard
    semantics — a literal '%' in s is just a byte). Memoized per
    literal: the page-admit pass re-renders the same needle for every
    page of every vnode it probes."""
    tris = _trigrams(s.encode("utf-8", "surrogatepass"))
    return tuple(sorted(tris)[:_MAX_QUERY_TRIGRAMS])


@functools.lru_cache(maxsize=512)
def required_trigrams(pattern: str) -> tuple[bytes, ...] | None:
    """Byte trigrams (over UTF-8) every LIKE match must contain, or None
    when the pattern has no ≥3-byte literal run (unusable for skipping).
    Capped at _MAX_QUERY_TRIGRAMS probes — a subset only admits more."""
    tris: set[bytes] = set()
    for run in literal_runs(pattern):
        tris |= _trigrams(run.encode("utf-8", "surrogatepass"))
    if not tris:
        return None
    return tuple(sorted(tris)[:_MAX_QUERY_TRIGRAMS])


def build_page_signature(uniques) -> bytes:
    """Bloom signature over the byte trigrams of every distinct value in
    a string page. b'' ⇒ the page provably contains no 3-byte substring
    (short strings / all-null) and any trigram probe prunes it."""
    tris: set[bytes] = set()
    for s in uniques:
        if isinstance(s, str):
            tris |= _trigrams(s.encode("utf-8", "surrogatepass"))
    if not tris:
        return b""
    bf = BloomFilter(min(_SIG_MAX_BITS,
                         max(_SIG_MIN_BITS, _BITS_PER_TRIGRAM * len(tris))))
    for t in tris:
        bf.insert(t)
    return bf.to_bytes()


def signature_admits(sig: bytes | None, trigrams) -> bool:
    """False only when the signature PROVES a required trigram absent —
    a page written before signatures existed (sig None) always admits."""
    if sig is None or not trigrams:
        return True
    if len(sig) == 0:
        return False
    bf = BloomFilter.from_bytes(sig)
    return all(bf.maybe_contains(t) for t in trigrams)


# ---------------------------------------------------------------------------
# top-K selection (ORDER BY key LIMIT k)
# ---------------------------------------------------------------------------
def _topk_device_wanted() -> bool:
    mode = os.environ.get("CNOSDB_TPU_TOPK", "auto").lower()
    if mode in ("1", "on", "true"):
        return True
    if mode in ("0", "off", "false"):
        return False
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:
        return False


def topk_order_indices(vals: np.ndarray, nulls, asc: bool,
                       k: int) -> np.ndarray | None:
    """Select-then-gather top-k: indices of the k extreme rows, ordered
    EXACTLY as the full stable-lexsort path orders them (descending ties
    break to the larger original index, ascending to the smaller), or
    None when the shape is outside the fast path (caller full-sorts).

    The k-th order statistic comes from jax.lax.top_k on TPU (only the
    scalar threshold crosses back) or np.partition on host; candidate
    rows at-or-past the threshold are then sorted exactly."""
    n = len(vals)
    if k <= 0 or k >= n:
        stages.count("topk.declined", 1)
        return None
    if nulls is not None and np.any(nulls):
        # NULLS FIRST/LAST ordering interleaves two keys — full sort
        stages.count("topk.declined", 1)
        return None
    if vals.dtype == object or vals.dtype.kind not in "iufMmbUS":
        stages.count("topk.declined", 1)
        return None
    if vals.dtype.kind == "f" and np.isnan(vals).any():
        # NaNs sort last/first asymmetrically vs the >= threshold select
        stages.count("topk.declined", 1)
        return None
    if vals.dtype.kind in "Mm" and np.isnat(vals).any():
        # NaT: np.partition sorts it last, np.lexsort by raw i64 (first)
        stages.count("topk.declined", 1)
        return None
    thr = None
    if not asc and vals.dtype.kind in "iuf" and _topk_device_wanted():
        try:
            from . import kernels

            thr = kernels.topk_threshold(vals, k)   # 0-d np scalar
            stages.count("topk.device", 1)
        except Exception:
            thr = None
    if thr is None:
        stages.count("topk.host", 1)
        part = np.partition(vals, k - 1 if asc else n - k)
        thr = part[k - 1] if asc else part[n - k]
    cand = np.flatnonzero(vals <= thr) if asc else np.flatnonzero(vals >= thr)
    order = np.lexsort((cand, vals[cand]))
    if not asc:
        order = order[::-1]
    return cand[order][:k]
