"""Device-resident scan block cache.

The reference keeps hot TSM pages in a host LRU (tskv/src/tsfamily/
version.rs TsmReader cache). On TPU the equivalent — and the dominant
performance lever, since host↔device transfer is the bottleneck — is
keeping decoded scan columns resident in HBM: a ScanBatch ships to the
device ONCE (timestamps, series ordinals, field columns + validity,
time-order rank), and every subsequent query against the same batch runs
entirely device-side (bucket/segment computation included), transferring
only group parameters in and [num_segments] partials out.

Invalidation: ScanBatches are immutable snapshots; the device arrays are
attached to the batch object itself, and batches are cached per vnode
snapshot token upstream (coordinator scan cache), so a write/flush/
compaction naturally rotates both layers. Two pipeline hooks keep the
COLD path off the critical PCIe+decode sum:

  * EagerUploader — handed into storage/scan via `upload_hook`; each
    field column device_puts as soon as its pages finish decoding, so
    transfer overlaps the decode of the remaining columns. The staged
    arrays ride along on the batch (`_preuploaded`) and DeviceBatch
    reuses them instead of re-staging.
  * merged_device_batch — after a delta rescan merged into a cached
    batch (coordinator delta path), the merged twin is built by GATHERING
    the unchanged columns from the cached twin on device; only the delta
    rows cross the wire.
"""
from __future__ import annotations

import numpy as np

import jax

from ..models.schema import ValueType
from ..utils import stages
from .kernels import pad_rows

# live device uploads, weakly held — the broker's device_uploads pool
# reads estimated resident bytes from here; no reclaim callback (device
# buffers die with their scan batch, evicting mid-query would corrupt
# the kernels referencing them)
import weakref as _weakref

_LIVE_BATCHES: "_weakref.WeakSet" = _weakref.WeakSet()


def device_bytes_used() -> int:
    return sum(getattr(b, "est_bytes", 0) for b in list(_LIVE_BATCHES))


def _register_device_pool() -> None:
    from ..server import memory as _memory

    _memory.register_pool("device_uploads", usage_fn=device_bytes_used)


_register_device_pool()


class DeviceBatch:
    """Padded, device-resident columns of one ScanBatch.

    Timestamps are stored as int32 (seconds, ns-remainder) pairs relative
    to the batch epoch — 64-bit integer/float arithmetic is software-
    emulated on TPU (measured ~1000× slower than i32 for division), so the
    device NEVER touches an i64 timestamp; bucket indices are derived from
    the i32 pair with exact integer math (see fused._bucket_arith).
    """

    __slots__ = ("n_rows", "n_pad", "n_series", "epoch_ns", "ts_sec", "ts_ns",
                 "sid_ordinal", "rank", "in_rows", "fields", "ts_min", "ts_max",
                 "i32_ok", "ns_all_zero", "field_all_valid", "_rank_np",
                 "series_params", "est_bytes", "__weakref__")

    def __init__(self, batch):
        with stages.stage("upload_ms"):
            self._init_meta(batch)
            pre = getattr(batch, "_preuploaded", None)
            pre_cols = pre[1] if pre is not None and pre[0] == self.n_pad \
                else {}
            for name, (vt, vals, valid) in batch.fields.items():
                if vt in (ValueType.STRING, ValueType.GEOMETRY):
                    continue  # strings aggregate host-side
                p = pre_cols.get(name)
                if p is not None and p[0] == vt:
                    # column staged by the scan's eager-upload pipeline
                    _vt, dev_vals, dev_valid, all_valid = p
                    self.field_all_valid[name] = all_valid
                    self.fields[name] = (vt, dev_vals, dev_valid)
                    continue
                dev_vals = vals if vt != ValueType.BOOLEAN \
                    else vals.astype(np.int64)
                all_valid = bool(valid.all())
                self.field_all_valid[name] = all_valid
                self.fields[name] = (
                    vt,
                    _put(_pad_to(dev_vals, self.n_pad, 0)),
                    None if all_valid
                    else _put(_pad_to(valid, self.n_pad, False)),
                )
            self.est_bytes = self._estimate_bytes()
            _LIVE_BATCHES.add(self)

    def _init_meta(self, batch):
        """Everything except the field columns: row counts, the i32
        timestamp pair, series ordinals, lazy rank."""
        n = batch.n_rows
        self.n_rows = n
        self.n_pad = pad_rows(max(n, 1))
        self.n_series = batch.n_series
        self.ts_min = int(batch.ts.min()) if n else 0
        self.ts_max = int(batch.ts.max()) if n else 0
        self.epoch_ns = self.ts_min
        rel = batch.ts - self.epoch_ns
        # i32 seconds covers ~68 years of batch span; beyond that the host
        # path handles it (flag checked in _device_eligible)
        self.i32_ok = n == 0 or bool(rel.max() < (2**31 - 2) * 1_000_000_000)
        sec = (rel // 1_000_000_000).astype(np.int32)
        ns = (rel - sec.astype(np.int64) * 1_000_000_000).astype(np.int32)
        # launches under the relay re-stream every passed buffer, so each
        # optional input is skipped (static kernel flag) when derivable:
        self.ns_all_zero = bool((ns == 0).all())   # second-aligned data
        self.ts_ns = None if self.ns_all_zero \
            else _put(_pad_to(ns, self.n_pad, 0))
        # Regular-series fast path: when every series is a contiguous run
        # with a constant whole-second stride (the normal telemetry shape),
        # ship ONLY [n_series, 3] params (row_start, sec0, stride_s); the
        # kernel reconstructs sid (searchsorted over row starts) and ts_sec
        # (sec0 + k*stride) — per-row timestamp/sid columns never cross the
        # wire or occupy HBM. This is TSM run-length structure carried onto
        # the device.
        self.series_params = None
        import os as _os

        # opt-in: reconstructing sid/ts_sec on device trades ~16MB of
        # transfer for extra gathers — measured a net loss on both the
        # relay-attached TPU and host XLA; wins only where HBM bandwidth is
        # real and the pipe is the bottleneck
        if n and self.ns_all_zero and _os.environ.get(
                "CNOSDB_TPU_REGULAR", "0") == "1":
            self.series_params = _regular_series_params(
                batch.sid_ordinal, sec, batch.n_series, self.n_pad)
        if self.series_params is not None:
            self.ts_sec = None
            self.sid_ordinal = None
        else:
            self.ts_sec = _put(_pad_to(sec, self.n_pad, 0))
            self.sid_ordinal = _put(_pad_to(batch.sid_ordinal, self.n_pad, 0))
        # in_rows derives from iota < n_rows inside the kernel (no buffer)
        self.in_rows = None
        # globally unique time-order rank (first/last selection key),
        # shipped lazily — only first/last kernels reference it
        order = np.argsort(batch.ts, kind="stable")
        rank = np.empty(n, dtype=np.int32)
        rank[order] = np.arange(n, dtype=np.int32)
        self._rank_np = rank
        self.rank = None
        self.fields: dict[str, tuple[ValueType, object, object]] = {}
        self.field_all_valid: dict[str, bool] = {}

    def _estimate_bytes(self) -> int:
        """Resident device-buffer bytes (feeds the broker's
        device_uploads pool; estimate only — the broker never reclaims
        uploads, they die with their scan batch)."""
        total = 0
        for a in (self.ts_sec, self.ts_ns, self.sid_ordinal, self.rank,
                  self.series_params):
            total += int(getattr(a, "nbytes", 0) or 0)
        for _vt, dev_vals, dev_valid in self.fields.values():
            total += int(getattr(dev_vals, "nbytes", 0) or 0)
            total += int(getattr(dev_valid, "nbytes", 0) or 0)
        return total

    def rank_dev(self):
        if self.rank is None:
            self.rank = _put(_pad_to(self._rank_np, self.n_pad, 0))
            self.est_bytes += int(getattr(self.rank, "nbytes", 0) or 0)
        return self.rank


class EagerUploader:
    """Receives finished scan columns from storage/scan's decode pipeline
    and stages them on device immediately (device_put enqueues are async,
    so the transfer of column N overlaps the decode of column N+1). The
    staged columns attach to the ScanBatch as `_preuploaded`, which
    DeviceBatch.__init__ consumes instead of re-staging. Failures are
    swallowed (counted) — the batch then just uploads lazily as before."""

    def __init__(self, n_rows: int):
        self.n_pad = pad_rows(max(n_rows, 1))
        self._cols: dict = {}

    def put(self, name: str, vt: ValueType, vals: np.ndarray,
            valid: np.ndarray):
        try:
            with stages.stage("upload_ms"):
                dev_vals = vals if vt != ValueType.BOOLEAN \
                    else vals.astype(np.int64)
                all_valid = bool(valid.all())
                self._cols[name] = (
                    vt,
                    _put(_pad_to(dev_vals, self.n_pad, 0)),
                    None if all_valid
                    else _put(_pad_to(valid, self.n_pad, False)),
                    all_valid,
                )
        except Exception:
            stages.count_error("scan.eager_upload")

    def put_device(self, name: str, vt: ValueType, parts: list):
        """Stage a column already ON DEVICE (ops/device_decode's lane):
        `parts` are per-page device rows in output order, null-free by
        contract (attach_device_columns filters). The decoded values
        never re-cross the pipe — this is the payoff of decoding on the
        accelerator."""
        try:
            with stages.stage("upload_ms"):
                import jax
                import jax.numpy as jnp

                cat = parts[0] if len(parts) == 1 \
                    else jnp.concatenate(parts)
                if vt == ValueType.UNSIGNED:
                    cat = jax.lax.bitcast_convert_type(cat, jnp.uint64)
                elif vt == ValueType.BOOLEAN:
                    cat = cat.astype(jnp.int64)
                n = int(cat.shape[0])  # lint: disable=host-sync (shape metadata only — no device data crosses; see EagerUploader docstring)
                if n < self.n_pad:
                    cat = jnp.concatenate(
                        [cat, jnp.zeros(self.n_pad - n, dtype=cat.dtype)])
                self._cols[name] = (vt, cat, None, True)
        except Exception:
            stages.count_error("scan.eager_upload")

    def attach(self, batch):
        if self._cols:
            batch._preuploaded = (self.n_pad, self._cols)


def merged_device_batch(merged, cached, delta,
                        append_gather: np.ndarray) -> "DeviceBatch | None":
    """Build the device twin of a delta-merged batch by gathering the
    unchanged rows from the cached twin ON DEVICE — the cached field
    columns never re-cross the host↔device pipe; only the (small) delta
    rows upload. Only valid for the pure-append merge shape
    (`append_gather` from merge_scan_batches): with duplicate (sid, ts)
    groups, each field picks its winner independently and one shared
    row-gather would be wrong — callers fall back to a lazy full build.

    The i32 timestamp pair / ordinals / rank rebuild on host (cheap i32
    work); → the attached DeviceBatch, or None when the cached twin is
    missing or shaped incompatibly."""
    old = getattr(cached, "_device_batch", None)
    if old is None or old.series_params is not None:
        return None
    import jax.numpy as jnp

    with stages.stage("upload_ms"):
        n_c, n_d = cached.n_rows, delta.n_rows
        db = DeviceBatch.__new__(DeviceBatch)
        db._init_meta(merged)
        # gather index into [cached rows | delta rows | zero sentinel];
        # pad rows hit the sentinel so they read (0, invalid) regardless
        # of kernel-side pad masking
        sent = n_c + n_d
        g = np.full(db.n_pad, sent, dtype=np.int32)
        g[:merged.n_rows] = append_gather
        g_dev = _put(g)
        pre = getattr(delta, "_preuploaded", None)
        pre_cols = pre[1] if pre is not None else {}
        for name, (vt, vals, valid) in merged.fields.items():
            if vt in (ValueType.STRING, ValueType.GEOMETRY):
                continue
            of = old.fields.get(name) if name in cached.fields else None
            if of is None or of[0] != vt or old.n_pad < n_c:
                # new/retyped column: plain upload of the merged array
                dev_vals = vals if vt != ValueType.BOOLEAN \
                    else vals.astype(np.int64)
                all_valid = bool(valid.all())
                db.field_all_valid[name] = all_valid
                db.fields[name] = (
                    vt, _put(_pad_to(dev_vals, db.n_pad, 0)),
                    None if all_valid
                    else _put(_pad_to(valid, db.n_pad, False)))
                continue
            _vt, old_vals, old_valid = of
            df = delta.fields.get(name)
            p = pre_cols.get(name)
            if p is not None and p[0] == vt and pre[0] >= n_d:
                d_vals_dev = p[1][:n_d]
                d_valid_dev = p[2][:n_d] if p[2] is not None else None
                d_all_valid = p[3]
            else:
                if df is not None:
                    d_vals = df[1] if vt != ValueType.BOOLEAN \
                        else df[1].astype(np.int64)
                    d_valid = df[2]
                else:   # field absent from the delta: all-invalid zeros
                    d_vals = np.zeros(
                        n_d, dtype=np.int64 if vt == ValueType.BOOLEAN
                        else vt.numpy_dtype())
                    d_valid = np.zeros(n_d, dtype=bool)
                d_vals_dev = _put(np.ascontiguousarray(d_vals))
                d_all_valid = bool(d_valid.all())
                d_valid_dev = None if d_all_valid \
                    else _put(np.ascontiguousarray(d_valid))
            zero = jnp.zeros(1, dtype=old_vals.dtype)
            cat = jnp.concatenate([old_vals[:n_c], d_vals_dev, zero])
            vals_dev = cat[g_dev]
            all_valid = bool(valid.all())
            db.field_all_valid[name] = all_valid
            if all_valid:
                valid_dev = None
            else:
                ov = old_valid[:n_c] if old_valid is not None \
                    else jnp.ones(n_c, dtype=bool)
                dv = d_valid_dev if d_valid_dev is not None \
                    else jnp.ones(n_d, dtype=bool)
                vcat = jnp.concatenate(
                    [ov, dv, jnp.zeros(1, dtype=bool)])
                valid_dev = vcat[g_dev]
            db.fields[name] = (vt, vals_dev, valid_dev)
        merged._device_batch = db
        return db


def _regular_series_params(sid_ordinal: np.ndarray, sec: np.ndarray,
                           n_series: int, n_pad: int) -> np.ndarray | None:
    """→ [n_series, 3] i32 (row_start, sec0, stride_s) when the batch is
    series-major with one contiguous, constant-whole-second-stride run per
    series; else None."""
    n = len(sid_ordinal)
    if n == 0 or n_series == 0:
        return None
    # series-major check: sid non-decreasing and covers 0..n_series-1
    d = np.diff(sid_ordinal)
    if (d < 0).any():
        return None
    starts = np.nonzero(np.concatenate(([True], d > 0)))[0]
    if len(starts) != n_series:
        return None
    ends = np.concatenate((starts[1:], [n]))
    params = np.empty((n_series, 3), dtype=np.int32)
    for s, (a, b) in enumerate(zip(starts, ends)):
        seg = sec[a:b]
        if len(seg) > 1:
            ds = np.diff(seg)
            stride = ds[0]
            if stride <= 0 or (ds != stride).any():
                return None
        else:
            stride = 1
        params[s] = (a, seg[0], stride)
    return params


def _pad_to(a: np.ndarray, n: int, fill) -> np.ndarray:
    if len(a) == n:
        return np.ascontiguousarray(a)
    out = np.full(n, fill, dtype=a.dtype)
    out[:len(a)] = a
    return out


def _put(a: np.ndarray):
    from ..utils import stages
    from .placement import scan_device

    stages.count("upload_bytes", int(getattr(a, "nbytes", 0)))
    return jax.device_put(a, scan_device())


def put_sharded(a: np.ndarray, mesh, spec):
    """Upload one host array laid out for the execution mesh: rows split
    over the named shard axis per `spec` (a PartitionSpec). The mesh exec
    lane (ops/mesh_exec.py) stages every operand through here so sharded
    uploads book the same `upload_bytes` the single-device path does."""
    from jax.sharding import NamedSharding

    stages.count("upload_bytes", int(getattr(a, "nbytes", 0)))
    return jax.device_put(a, NamedSharding(mesh, spec))


def device_batch(batch) -> DeviceBatch:
    """Get-or-build the device twin of a ScanBatch (attached to it)."""
    db = getattr(batch, "_device_batch", None)
    if db is None:
        db = DeviceBatch(batch)
        batch._device_batch = db
    return db
