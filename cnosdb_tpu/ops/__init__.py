"""Device-side data plane (JAX/XLA).

Importing this package configures JAX for the engine: x64 on, because
timestamps are int64 nanoseconds end-to-end (f32/i32 cannot represent them)
and integer fields are i64. Host-only layers (models/storage) do not import
this, keeping pure-metadata use of cnosdb_tpu jax-free.
"""
import jax

jax.config.update("jax_enable_x64", True)
