"""Fused device-side query kernels over cached DeviceBatches.

One jitted program per (filter expression, aggregate set, segment shape)
runs the ENTIRE per-vnode query — predicate filter, time-bucket
computation, group mapping, masked segment reductions — against
device-resident columns. Per query, only the group-of-series vector and
scalar bucket parameters cross to the device and only [num_segments]
partials come back; the row data never moves again. This is what makes
repeated analytics queries fast under a thin host↔device pipe.

Bucket math is pure int32 (64-bit integer ops are software-emulated on
TPU, measured ~1000× slower). For interval = I_s whole seconds, with batch
epoch E and query origin O:

    bucket(ts) = floor((ts - O)/interval)
    let A = E - O = qA*interval + rA,  rA = rA_s*1e9 + rA_ns  (host, exact)
    ts = E + sec*1e9 + rem             (device i32 pair)
    carry = (rem + rA_ns) >= 1e9
    bucket = qA + floor((sec + rA_s + carry) / I_s)            (all i32)

The final index subtracts bmin host-side (folded into `offset`), so no
per-query recompilation: I_s, rA_s, rA_ns, offset are traced scalars.

Segment reductions here stay on XLA's segment_sum/min/max: this kernel
derives seg ids ON DEVICE (group_of_series[sid] × n_buckets + bucket), so
the pallas windowed kernel's host-side applicability check
(pallas_kernels.applicable — per-tile span < W_WIN over a host seg array)
cannot run. The pallas route lives in kernels.aggregate_column_host,
where the host-prep device path has the seg array in host memory.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..sql.expr import Expr
from .device_cache import DeviceBatch
from .kernels import local_segment_partials, pad_segments

_kernel_cache: dict = {}

# observability: how many fused device programs launched this process
# (tests assert the device path actually engaged; bench records it)
launch_count = 0

NS_PER_SEC = 1_000_000_000


def bucket_arith_params(epoch_ns: int, origin: int, interval: int,
                        bmin: int, max_span_ns: int = 0,
                        ) -> tuple[int, int, int, int] | None:
    """Host-side derivation of the i32 bucket constants; None if the
    interval is not a whole number of seconds or any i32 step could
    overflow (host path handles those)."""
    if interval % NS_PER_SEC != 0:
        return None
    i_s = interval // NS_PER_SEC
    if i_s >= 2**31:
        return None
    a = epoch_ns - origin
    qa = a // interval
    ra = a - qa * interval
    ra_s = ra // NS_PER_SEC
    ra_ns = ra % NS_PER_SEC
    # sec_adj = ts_sec + ra_s + carry must stay inside i32
    if max_span_ns // NS_PER_SEC + ra_s + 2 >= 2**31:
        return None
    offset = qa - bmin
    if not (-(2**31) < offset < 2**31):
        return None
    return int(i_s), int(ra_s), int(ra_ns), int(offset)


class PendingFused:
    """A launched (asynchronous) fused kernel; fetch() pulls the single
    packed output matrix in ONE device→host transfer and unpacks it."""

    __slots__ = ("dev_out", "manifest", "num_segments", "int_cols", "agg_cols")

    def __init__(self, dev_out, manifest, num_segments, int_cols, agg_cols):
        self.dev_out = dev_out
        self.manifest = manifest
        self.num_segments = num_segments
        self.int_cols = int_cols
        self.agg_cols = agg_cols

    def fetch(self) -> dict[str, dict]:
        mat = np.asarray(self.dev_out)  # [n_slots, ns_pad], one transfer
        out: dict[str, dict] = {}
        for i, (col, agg) in enumerate(self.manifest):
            row = mat[i, :self.num_segments]
            if agg == "count" or agg.endswith("_rank") or col in self.int_cols:
                # exact below 2^53; integer sums beyond that would lose
                # precision in the packed f64 transfer (documented limit)
                row = row.astype(np.int64)
            out.setdefault(col, {})[agg] = row
        presence = out.get("__presence__", {}).get("count")
        if presence is not None:
            # all-valid columns elide their count slot (it IS presence); a
            # column whose ONLY slot was count must still appear in out
            for col in self.agg_cols:
                out.setdefault(col, {}).setdefault("count", presence)
        return out


def launch_fused(dbatch: DeviceBatch, filter_expr: Expr | None,
                 group_of_series: np.ndarray, n_groups: int, n_buckets: int,
                 arith: tuple[int, int, int, int] | None,
                 col_wants: dict[str, dict]) -> PendingFused:
    global launch_count
    launch_count += 1
    num_segments = n_groups * n_buckets
    ns_pad = pad_segments(max(num_segments, 1))

    filter_key = filter_expr.to_sql() if filter_expr is not None else ""
    cols_key = tuple(sorted((c, tuple(sorted(w.items())))
                            for c, w in col_wants.items()))
    # ship every column the kernel touches: aggregated ones AND columns the
    # filter references but no aggregate does
    filt_cols = filter_expr.columns() if filter_expr is not None else set()
    present = [n for n in sorted(set(col_wants) | filt_cols)
               if n in dbatch.fields]
    dtypes_key = tuple((name, str(dbatch.fields[name][1].dtype))
                       for name in present)
    i_s, ra_s, ra_ns, offset = arith if arith is not None else (1, 0, 0, 0)
    use_bucket = arith is not None
    need_rank = any(w.get("want_first") or w.get("want_last")
                    for w in col_wants.values())
    valid_flags = tuple(dbatch.fields[n][2] is not None for n in present)
    has_ts_ns = use_bucket and not dbatch.ns_all_zero
    regular = dbatch.series_params is not None
    # the divisor i_s MUST be a compile-time constant: division by a traced
    # i32 is software-emulated on TPU (~1000× slower); XLA strength-reduces
    # constant divisors to multiplies. Intervals are few (1m/5m/1h/...), so
    # keying the kernel cache on i_s costs a handful of compiles. The
    # add/compare params (ra_s/ra_ns/offset) stay traced — they change per
    # batch/origin without recompilation. Optional inputs (ts_ns, rank,
    # per-column validity) are kernel variants: every buffer passed is
    # re-streamed per launch under the relay, so absent means bytes saved.
    key = (filter_key, cols_key, dtypes_key, ns_pad, n_buckets,
           use_bucket, i_s, dbatch.n_pad, need_rank, valid_flags, has_ts_ns,
           regular)
    entry = _kernel_cache.get(key)
    if entry is None:
        entry = _build_kernel(filter_expr, col_wants, tuple(present), ns_pad,
                              n_buckets, use_bucket, i_s, need_rank,
                              valid_flags, has_ts_ns, regular, dbatch.n_pad)
        _kernel_cache[key] = entry
    fn, manifest = entry

    ns = max(dbatch.n_series, 1)
    gos = np.zeros(ns, dtype=np.int32)
    gos[:len(group_of_series)] = group_of_series

    args = []
    if not regular:
        if use_bucket:
            args.append(dbatch.ts_sec)
            if has_ts_ns:
                args.append(dbatch.ts_ns)
        args.append(dbatch.sid_ordinal)
    if need_rank:
        args.append(dbatch.rank_dev())
    # every host→device transfer costs ~45-90ms fixed under the relay: all
    # per-query scalars + the group vector + (regular mode) the per-series
    # run params ride in ONE i32 buffer
    sp = dbatch.series_params if regular else None
    sp_len = sp.size if sp is not None else 0
    params = np.empty(4 + ns + sp_len, dtype=np.int32)
    params[0] = ra_s
    params[1] = ra_ns
    params[2] = offset
    params[3] = dbatch.n_rows
    params[4:4 + ns] = gos
    if sp is not None:
        params[4 + ns:] = sp.ravel()
    from .placement import scan_device

    args.append(jax.device_put(params, scan_device()))
    for name, has_valid in zip(present, valid_flags):
        _vt, vals, valid = dbatch.fields[name]
        args.append(vals)
        if has_valid:
            args.append(valid)
    dev_out = fn(*args)
    int_cols = {name for name in present
                if jnp.issubdtype(dbatch.fields[name][1].dtype, jnp.integer)}
    agg_cols = tuple(n for n in present if n in col_wants)
    return PendingFused(dev_out, manifest, num_segments, int_cols, agg_cols)


def run_fused(dbatch: DeviceBatch, filter_expr: Expr | None,
              group_of_series: np.ndarray, n_groups: int, n_buckets: int,
              arith: tuple[int, int, int, int] | None,
              col_wants: dict[str, dict]) -> dict[str, dict]:
    return launch_fused(dbatch, filter_expr, group_of_series, n_groups,
                        n_buckets, arith, col_wants).fetch()


def _build_kernel(filter_expr: Expr | None, col_wants: dict,
                  present: tuple, ns_pad: int, n_buckets: int,
                  use_bucket: bool, i_s: int, need_rank: bool,
                  valid_flags: tuple, has_ts_ns: bool, regular: bool,
                  n_pad: int = 0):
    """→ (jitted fn, manifest). The kernel packs every partial into ONE
    [n_slots, ns_pad] float64 matrix so the host fetches a single transfer
    (small device→host pulls have ~15-90ms fixed latency through the host
    relay; one packed pull amortizes it). f64 holds counts and i32 ranks
    exactly (< 2^53). Optional inputs are compile-time variants — see
    launch_fused."""
    manifest: list[tuple[str, str]] = [("__presence__", "count")]
    agg_cols = [n for n in present if n in col_wants]
    valid_of = dict(zip(present, valid_flags))
    for name in agg_cols:
        w = col_wants[name]
        if valid_of.get(name):
            # nullable column: its count differs from presence → own slot
            manifest.append((name, "count"))
        for agg, flag in (("sum", "want_sum"), ("min", "want_min"),
                          ("max", "want_max"), ("first", "want_first"),
                          ("last", "want_last")):
            if w.get(flag):
                manifest.append((name, agg))
                if agg in ("first", "last"):
                    manifest.append((name, agg + "_rank"))

    def kernel(*args):
        i = 0
        ts_sec = ts_ns = None
        sid_ord = None
        if not regular:
            if use_bucket:
                ts_sec = args[i]; i += 1
                if has_ts_ns:
                    ts_ns = args[i]; i += 1
            sid_ord = args[i]; i += 1
        if need_rank:
            rank = args[i]; i += 1
        else:
            rank = None
        params = args[i]; i += 1
        ra_s, ra_ns, offset, n_rows = params[0], params[1], params[2], params[3]
        fields = {}
        for name, has_valid in zip(present, valid_flags):
            vals = args[i]; i += 1
            valid = None
            if has_valid:
                valid = args[i]; i += 1
            fields[name] = (vals, valid)

        row = jax.lax.iota(jnp.int32, n_pad)
        if regular:
            # reconstruct sid + ts_sec from [n_series,3] run params
            n_series = (params.shape[0] - 4) // 4
            group_of_series = params[4:4 + n_series]
            sp = params[4 + n_series:].reshape(n_series, 3)
            row_start, sec0, stride = sp[:, 0], sp[:, 1], sp[:, 2]
            sid_ord = (jnp.searchsorted(row_start, row, side="right") - 1
                       ).astype(jnp.int32)
            sid_ord = jnp.clip(sid_ord, 0, n_series - 1)
            if use_bucket:
                k = row - row_start[sid_ord]
                ts_sec = sec0[sid_ord] + k * stride[sid_ord]
        else:
            n_series = params.shape[0] - 4
            group_of_series = params[4:]
        mask = row < n_rows
        if filter_expr is not None:
            env = {}
            for name, (vals, valid) in fields.items():
                env[name] = vals
                env[f"__valid__:{name}"] = (
                    valid if valid is not None
                    else jnp.ones(vals.shape, dtype=bool))
            fmask = filter_expr.eval(env, jnp)
            mask = mask & fmask
            # null operands exclude rows (host path does the same)
            for c in filter_expr.columns():
                if c in fields and fields[c][1] is not None:
                    mask = mask & fields[c][1]
        if use_bucket:
            if ts_ns is not None:
                carry = ((ts_ns + ra_ns) >= NS_PER_SEC).astype(jnp.int32)
            else:
                carry = (ra_ns >= NS_PER_SEC).astype(jnp.int32)
            sec_adj = ts_sec + ra_s + carry
            bucket = offset + sec_adj // jnp.int32(i_s)
            bucket = jnp.clip(bucket, 0, n_buckets - 1)
        else:
            bucket = jnp.zeros_like(sid_ord)
        seg = (group_of_series[sid_ord] * n_buckets + bucket).astype(jnp.int32)
        seg = jnp.where(mask, seg, 0)
        presence = jax.ops.segment_sum(mask.astype(jnp.int32), seg, ns_pad)
        results = {("__presence__", "count"): presence}
        for name in agg_cols:
            vals, valid = fields[name]
            w = col_wants[name]
            part = local_segment_partials(
                vals, (valid & mask) if valid is not None else mask, seg,
                rank if rank is not None else seg,  # rank unused w/o first/last
                num_segments=ns_pad,
                # an all-valid column's count IS the presence count: skip
                # the extra scatter
                want_count=valid is not None,
                want_sum=w.get("want_sum", False),
                want_min=w.get("want_min", False),
                want_max=w.get("want_max", False),
                want_first=w.get("want_first", False),
                want_last=w.get("want_last", False))
            if "count" not in part:
                part["count"] = presence
            for agg, arr in part.items():
                results[(name, agg)] = arr
        rows = [results[slot].astype(jnp.float64) for slot in manifest]
        return jnp.stack(rows)

    return jax.jit(kernel), manifest
