"""Mesh-native aggregate execution: shard-parallel kernels + collective merge.

The multi-vnode aggregate path the executor uses by default
(sql/executor._exec_aggregate_batches) runs one kernel per scan batch on
a thread pool, pulls every batch's [segments] partials to the host, and
merges them with numpy (`_merge_results_vec`). This lane replaces the
whole fan-out for on-mesh batches: every batch's rows upload once with a
`NamedSharding(mesh, P("shard"))` layout (batch i → shard i//slots, so
vnode placement IS the sharding spec), and ONE jit program per column
computes per-shard segment partials and folds them across the mesh in
global batch order through XLA collectives
(parallel/distributed_agg.mesh_merge_kernel). No per-batch host partial
ever materializes — the merge happens on the interconnect, and the host
fetches only the final [segments] arrays.

Semantics contract: the output AggResult is bit-identical to
`_merge_results_vec` over the legacy per-batch results — same glab/
bucket-code row ordering, same dtypes, same fold order for f64 sums,
same (ts, batch-order) first/last tie-breaking — so
`sql/executor._finalize_single` consumes it unchanged, and CNOSDB_MESH=0
(or any decline) falls back to the byte-identical legacy path.

Every early exit books a reason via `parallel.mesh.count_outcome`
(`cnosdb_mesh_total{lane,reason}`, enforced by the mesh-accounting lint
rule); engagements book `("exec", "engaged")` + `("merge",
"collective")`, which is how the zero-host-merge acceptance is asserted.

Fault surface: `mesh.collective` fires just before the collective phase
— the nemesis `device_loss` kind arms it to kill a mesh participant
mid-collective, and the lane answers by declining (reason
`device_loss`), which IS the transparent fallback to the host/RPC merge.
"""
from __future__ import annotations

import os
import weakref

import numpy as np

from .. import faults
from ..models.schema import ValueType
from ..utils import stages
from .tpu_exec import AggResult, host_group_layout, host_row_mask

faults.register_point(
    "mesh.collective", __name__,
    desc="mesh exec lane, upload + collective merge kernel: a failure "
         "here is a device lost mid-collective — the lane books "
         "device_loss and the query transparently falls back to the "
         "legacy host-merge path")

_MESH_FUNCS = {"count", "sum", "min", "max", "first", "last"}
_NUMERIC_VTS = (ValueType.FLOAT, ValueType.INTEGER)

# cells = devices × slots × padded segments of the gathered fold operand;
# past this the collective's memory beats the host merge it replaces
_MAX_FOLD_CELLS = 1 << 24


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _declined(reason: str):
    from ..parallel import mesh

    mesh.count_outcome("exec", reason)
    return None


# ------------------------------------------------------------ prep cache
# Warm repeat queries (dashboards, the bench sweep) re-aggregate the same
# scan snapshot: the sharded device operands are pure functions of
# (batch set, group shape) for unfiltered queries, so they cache on the
# lead batch. Accounted to the memory broker as its own pool — reclaim
# drops the device arrays, the next query re-stages.
# ScanBatch is an eq-comparing dataclass (unhashable), so a WeakSet
# can't hold it — track weak refs keyed by id() instead, pruned by the
# ref callback when the batch is collected.
_PREP_REFS: dict[int, "weakref.ref"] = {}


def _live_prep_batches():
    for b in [r() for r in list(_PREP_REFS.values())]:
        if b is not None:
            yield b


def prep_bytes() -> int:
    total = 0
    for b in _live_prep_batches():
        entry = getattr(b, "_mesh_prep", None)
        if entry is not None:
            total += entry[1].get("est_bytes", 0)
    return total


def prep_clear(target_bytes: int = 0) -> int:
    freed = 0
    for b in _live_prep_batches():
        entry = getattr(b, "_mesh_prep", None)
        if entry is None:
            continue
        freed += entry[1].get("est_bytes", 0)
        b._mesh_prep = None
        if freed >= target_bytes > 0:
            break
    return freed


def _register_prep_pool() -> None:
    from ..server import memory as _memory

    _memory.register_pool("mesh_prep", usage_fn=prep_bytes,
                          reclaim=prep_clear)


_register_prep_pool()


def _canon(v):
    """NaN-canonical dict key (the executor's _canon_group_key rule)."""
    if isinstance(v, (float, np.floating)) and v != v:
        return "__nan__"
    return v


def try_mesh_aggregate(batches, query):
    """Run the whole multi-batch aggregate on the execution mesh.

    → a fully merged AggResult (bit-identical to the legacy per-batch
    kernel fan-out + `_merge_results_vec`) for `_finalize_single`, or
    None after booking a decline reason — the caller then takes the
    legacy path unchanged.
    """
    from ..parallel import mesh

    if not mesh.enabled():
        return _declined("disabled")
    aggs = query.aggs
    if any(a.func not in _MESH_FUNCS for a in aggs):
        return _declined("agg_func")
    if query.group_fields and \
            os.environ.get("CNOSDB_MESH_FIELDS", "0") != "1":
        # string/numeric field group axes merge through the dict path in
        # the legacy engine, whose row order this lane cannot reproduce;
        # opt in (parity tests and the bench do) when ORDER BY pins it
        return _declined("group_fields")
    if any(not getattr(b, "_mesh_local", False) for b in batches):
        # off-mesh replica partials arrive over RPC msgpack — the
        # coordinator merges those on the host exactly as before
        return _declined("off_mesh")
    live = [b for b in batches if b.n_rows]
    if len(live) < 2:
        return _declined("single_batch")
    total_rows = sum(b.n_rows for b in live)
    if total_rows < _env_int("CNOSDB_MESH_MIN_ROWS", 65536):
        return _declined("few_rows")
    m = mesh.get_mesh()
    if m is None:
        return _declined("no_devices")
    n_dev = mesh.mesh_size(m)
    if n_dev < _env_int("CNOSDB_MESH_MIN_DEVICES", 2):
        return _declined("few_devices")
    for b in live:
        for a in aggs:
            if a.column is None or a.column == "time":
                continue
            f = b.fields.get(a.column)
            if f is None or f[0] not in _NUMERIC_VTS:
                # absent column (could be a tag → string agg), unsigned
                # bias games, booleans, strings: legacy lanes own those
                return _declined("value_dtype")
    try:
        prep = _build_prep(live, query, m, n_dev)
    except Exception:
        stages.count_error("mesh.plan")
        return _declined("plan_error")
    if prep is None:
        return _declined("segments")
    if prep["n_out"] == 0:
        # every row filtered out: the legacy merge's empty-result shape
        res = _empty_result(query)
        mesh.count_outcome("exec", "engaged")
        mesh.count_outcome("merge", "collective")
        return res
    try:
        faults.fire("mesh.collective")
        with stages.stage("mesh.collective_ms"):
            fetched = _run_collectives(prep, m)
    except Exception:
        # a mesh participant died mid-collective (nemesis device_loss,
        # real XLA failure): fall back to the host merge transparently
        stages.count_error("mesh.collective")
        return _declined("device_loss")
    with stages.stage("mesh.assemble_ms"):
        res = _assemble_merged(prep, query, fetched)
    mesh.count_outcome("exec", "engaged")
    mesh.count_outcome("merge", "collective")
    stages.count("mesh.rows", total_rows)
    stages.count("mesh.shards", n_dev)
    return res


def _col_wants(aggs) -> dict:
    wants: dict[str | None, set] = {}
    for a in aggs:
        if a.column is not None:
            wants.setdefault(a.column, set()).add(
                "count" if a.func == "count" else a.func)
    # sum/first/last validity and min/max `has` masks all derive from the
    # per-segment valid count, so every column always wants it
    for w in wants.values():
        w.add("count")
    return wants


def _legacy_sum_runs(b, gseg, mask, valid, col_fl, needs_rank, ordered,
                     prefer_flat):
    """Replicate the branch tpu_exec.launch_scan_aggregate takes for a
    CPU float-sum column, because the branches accumulate f64 in
    different orders. Returns None when the legacy path sums with a flat
    row-order scatter, else (rows, starts): the ascending row indices the
    legacy run kernel compresses to (None = every row) and the run start
    offsets within them (kernels.run_boundaries semantics — a new run at
    every segment or series change)."""
    from . import kernels

    # string first/last never reaches the mesh lane (value_dtype gate),
    # so legacy's fl_string term is always False here
    rank_based_fl = needs_rank and not ordered
    if (col_fl and rank_based_fl) or (prefer_flat and not col_fl):
        return None   # rank/scatter fallback kernels: flat
    n = b.n_rows
    all_valid = bool(valid.all())
    all_rows = mask is None or bool(mask.all())
    sel = None if all_rows else np.flatnonzero(mask)
    if all_rows and all_valid:
        starts = kernels.run_boundaries(gseg, b.sid_ordinal)
        if not col_fl and len(starts) > (n >> 2):
            return None   # fine-grained runs: legacy flat-scatters
        return None, starts
    if all_valid and sel is not None and not prefer_flat:
        starts = kernels.run_boundaries(gseg[sel], b.sid_ordinal[sel])
        if not col_fl and len(starts) > (len(sel) >> 2):
            return None
        return sel, starts
    # nulls present (or filtered string-field grouping): legacy
    # compresses the valid∧selected rows and is always run-aware
    if sel is not None:
        vsub = valid[sel]
        idx2 = sel if vsub.all() else sel[vsub]
    else:
        idx2 = np.flatnonzero(valid)
    starts = kernels.run_boundaries(gseg[idx2], b.sid_ordinal[idx2])
    return idx2, starts


def _build_prep(live, query, m, n_dev):
    """Global segment layout + sharded device operands (cached on the
    lead batch for unfiltered repeats). → prep dict, or None when the
    fold operand would blow the segment budget."""
    from .device_cache import put_sharded
    from .kernels import pad_rows, pad_segments
    from ..parallel.mesh import SHARD_AXIS
    from jax.sharding import PartitionSpec as P

    wants = _col_wants(query.aggs)
    needs_rank = any(a.func in ("first", "last") for a in query.aggs)
    slots = -(-len(live) // n_dev)          # batches per shard, ceil
    cache_ok = query.filter is None
    key = (tuple((id(b), b.n_rows) for b in live),
           tuple(query.group_tags), tuple(query.group_fields),
           query.time_bucket, n_dev, slots, needs_rank,
           tuple(sorted((c, tuple(sorted(w))) for c, w in wants.items())))
    if cache_ok:
        hit = getattr(live[0], "_mesh_prep", None)
        if hit is not None and hit[0] == key:
            stages.count("mesh.plan_cache_hit")
            return hit[1]
    stages.count("mesh.plan_cache_miss")

    with stages.stage("mesh.plan_ms"):
        masks = [host_row_mask(b, query.filter) for b in live]
        keep = [i for i, (b, mk) in enumerate(zip(live, masks))
                if mk is None or mk.any()]
        live = [live[i] for i in keep]
        masks = [masks[i] for i in keep]
        if not live:
            prep = {"n_out": 0, "est_bytes": 0}
            return prep
        layouts = [host_group_layout(b, query.group_tags,
                                     query.group_fields, query.time_bucket)
                   for b in live]

        # ---- global tag groups: glab insertion order is batch-major over
        # each batch's local label table — _merge_results_vec's exact rule
        glab: dict[tuple, int] = {}
        tag_luts = []
        for hl in layouts:
            lut = np.empty(len(hl.group_labels), dtype=np.int64)
            for i, lab in enumerate(hl.group_labels):
                lut[i] = glab.setdefault(lab, len(glab))
            tag_luts.append(lut)
        lab_table = [None] * len(glab)
        for lab, g in glab.items():
            lab_table[g] = lab

        # ---- global field-group dictionaries (one per GROUP BY field)
        n_gf = len(query.group_fields)
        gdicts: list[dict] = [{} for _ in range(n_gf)]
        gvals: list[list] = [[] for _ in range(n_gf)]
        for hl in layouts:
            for fi in range(n_gf):
                for v in hl.gf_dicts[fi]:
                    ck = _canon(v)
                    if ck not in gdicts[fi]:
                        gdicts[fi][ck] = len(gdicts[fi])
                        gvals[fi].append(v)
        gdims = [len(d) + 1 for d in gdicts]   # +1: the NULL group slot
        gf_luts = []
        for hl in layouts:
            per_field = []
            for fi in range(n_gf):
                local = hl.gf_dicts[fi]
                lut = np.empty(len(local) + 1, dtype=np.int64)
                for i, v in enumerate(local):
                    lut[i] = gdicts[fi][_canon(v)]
                lut[len(local)] = gdims[fi] - 1   # local NULL → global NULL
                per_field.append(lut)
            gf_luts.append(per_field)

        n_groups = max(len(glab), 1)
        for d in gdims:
            n_groups *= d

        # ---- per-batch decode: local seg → (tag gid, field codes, bucket)
        per_batch_gid = []
        per_batch_bstart = []
        for bi, (b, hl) in enumerate(zip(live, layouts)):
            seg = hl.seg_ids.astype(np.int64)
            grp = seg // hl.n_buckets
            codes = []
            for fi in range(n_gf - 1, -1, -1):
                dim = hl.gf_dims[fi]
                codes.append(grp % dim)
                grp //= dim
            g = tag_luts[bi][grp]
            for fi in range(n_gf):
                g = g * gdims[fi] + gf_luts[bi][fi][codes[n_gf - 1 - fi]]
            per_batch_gid.append(g)
            if query.time_bucket is not None:
                per_batch_bstart.append(
                    hl.bucket_starts[seg % hl.n_buckets])
            else:
                per_batch_bstart.append(None)

        # ---- global bucket times: sorted union of PRESENT bucket starts
        if query.time_bucket is not None:
            parts = []
            for bs, mk in zip(per_batch_bstart, masks):
                parts.append(np.unique(bs if mk is None else bs[mk]))
            utimes = np.unique(np.concatenate(parts))
            n_t = len(utimes)
        else:
            utimes, n_t = None, 1
        n_seg = n_groups * n_t
        seg_pad = pad_segments(n_seg)
        if n_dev * slots * seg_pad > _MAX_FOLD_CELLS \
                or slots * seg_pad > np.iinfo(np.int32).max:
            return None

        # ---- per-row global segment ids + presence
        presence = np.zeros(n_seg, dtype=np.int64)
        gsegs = []
        for g, bs, mk in zip(per_batch_gid, per_batch_bstart, masks):
            gs = g * n_t
            if bs is not None:
                gs = gs + np.searchsorted(utimes, bs)
            gsegs.append(gs)
            presence += np.bincount(gs if mk is None else gs[mk],
                                    minlength=n_seg)

        # ---- global time-order rank (first/last tie-breaking: timestamp,
        # then batch order, then row order — the stable argsort of the
        # batch-order concatenation encodes all three)
        if needs_rank:
            cts = np.concatenate([b.ts for b in live])
            order = np.argsort(cts, kind="stable")
            grank = np.empty(len(cts), dtype=np.int32)
            grank[order] = np.arange(len(cts), dtype=np.int32)
            sorted_ts = cts[order]
        else:
            grank = sorted_ts = None

        # ---- shard-major padded layout: batch i → shard i//slots
        shard_rows = [0] * n_dev
        for i, b in enumerate(live):
            shard_rows[i // slots] += b.n_rows
        row_pad = pad_rows(max(max(shard_rows), 1))
        total = n_dev * row_pad
        seg_arr = np.zeros(total, dtype=np.int32)
        base_valid = np.zeros(total, dtype=bool)
        rank_arr = np.zeros(total, dtype=np.int32)
        col_host: dict[str, tuple] = {}
        for c in wants:
            vt = ValueType.INTEGER if c == "time" else live[0].fields[c][0]
            dt = np.int64 if vt == ValueType.INTEGER else np.float64
            col_host[c] = (vt, np.zeros(total, dtype=dt),
                           np.zeros(total, dtype=bool))
        cursor = [0] * n_dev
        concat_off = 0
        placements = []   # (batch idx, shard, slot, dest row offset)
        for i, b in enumerate(live):
            sh, slot = divmod(i, slots)
            d0 = sh * row_pad + cursor[sh]
            d1 = d0 + b.n_rows
            cursor[sh] += b.n_rows
            placements.append((i, sh, slot, d0))
            seg_arr[d0:d1] = (slot * seg_pad + gsegs[i]).astype(np.int32)
            mk = masks[i]
            base_valid[d0:d1] = True if mk is None else mk
            if grank is not None:
                rank_arr[d0:d1] = grank[concat_off:concat_off + b.n_rows]
            for c, (vt, vals, cvalid) in col_host.items():
                if c == "time":
                    vals[d0:d1] = b.ts
                    cvalid[d0:d1] = base_valid[d0:d1]
                else:
                    f = b.fields.get(c)
                    if f is not None:
                        vals[d0:d1] = np.asarray(f[1])
                        cvalid[d0:d1] = base_valid[d0:d1] & f[2]
            concat_off += b.n_rows

        # ---- f64 sum run plans: the legacy CPU host kernels are
        # run-aware (ufunc.reduceat per contiguous equal-segment run, run
        # partials folded per segment in run order), and reduceat's
        # within-run association is numpy's pairwise reduce — no device
        # scatter order reproduces it. So replicate the per-batch branch
        # decision tpu_exec.launch_scan_aggregate makes, stage the
        # per-run reduceat partials with the SAME numpy call, and let the
        # kernel fold runs → segments → shards on the mesh. Batches the
        # legacy path sums flat stage one run per row (bincount is a
        # sequential C loop, so row-order is exact for those). Integer
        # sums and every other aggregate are order-exact as flat scatters.
        run_host: dict[str, tuple] = {}
        from .placement import scan_device
        from .tpu_exec import _FORCE_DEVICE, _ordered_within_series
        cpu_mode = scan_device().platform == "cpu" and not _FORCE_DEVICE()
        if cpu_mode:
            ordered = [_ordered_within_series(b) for b in live]
            for c, (vt, _vals, _cvalid) in col_host.items():
                if "sum" not in wants[c] or vt != ValueType.FLOAT:
                    continue
                col_fl = bool({"first", "last"} & wants[c])
                plans = []
                for i, b in enumerate(live):
                    plans.append(_legacy_sum_runs(
                        b, gsegs[i], masks[i], b.fields[c][2], col_fl,
                        needs_rank, ordered[i],
                        bool(layouts[i].gf_dims)))
                if not any(p is not None for p in plans):
                    continue   # every batch sums flat: one-level is exact
                nruns = []
                for bi, p in enumerate(plans):
                    if p is None:   # flat batch → one run per summed row
                        b = live[bi]
                        mk = masks[bi]
                        inc = b.fields[c][2] if mk is None \
                            else (mk & b.fields[c][2])
                        rows = np.flatnonzero(inc)
                        starts = np.arange(len(rows), dtype=np.int64)
                        plans[bi] = (rows, starts)
                    nruns.append(len(plans[bi][1]))
                shard_runs = [0] * n_dev
                for (i, sh, slot, d0), nr in zip(placements, nruns):
                    shard_runs[sh] += nr
                run_pad = max(max(shard_runs), 1)
                run_sums = np.zeros(n_dev * run_pad, dtype=np.float64)
                run_segs = np.full(n_dev * run_pad, slots * seg_pad,
                                   dtype=np.int32)
                cur_r = [0] * n_dev
                for (i, sh, slot, d0), p in zip(placements, plans):
                    rows, starts = p
                    b = live[i]
                    cv = np.asarray(b.fields[c][1])
                    sub = cv if rows is None else cv[rows]
                    nr = len(starts)
                    if nr == 0:
                        continue
                    off = sh * run_pad + cur_r[sh]
                    cur_r[sh] += nr
                    run_sums[off:off + nr] = np.add.reduceat(sub, starts)
                    gs = gsegs[i] if rows is None else gsegs[i][rows]
                    run_segs[off:off + nr] = slot * seg_pad + gs[starts]
                run_host[c] = (run_sums, run_segs, run_pad)

    with stages.stage("mesh.upload_ms"):
        spec = P(SHARD_AXIS)
        seg_dev = put_sharded(seg_arr, m, spec)
        rank_dev = put_sharded(rank_arr, m, spec)
        cols_dev = {}
        for c, (vt, vals, cvalid) in col_host.items():
            cols_dev[c] = (put_sharded(vals, m, spec),
                           put_sharded(cvalid, m, spec))
        runs_dummy = put_sharded(np.zeros(n_dev, dtype=np.int32), m, spec)
        runs_dev = {}
        for c, (rids, rsegs, rpad) in run_host.items():
            runs_dev[c] = (put_sharded(rids, m, spec),
                           put_sharded(rsegs, m, spec), rpad)

    est = seg_arr.nbytes + rank_arr.nbytes + base_valid.nbytes \
        + sum(v.nbytes + cv.nbytes for _, v, cv in col_host.values()) \
        + sum(r.nbytes + s.nbytes for r, s, _ in run_host.values()) \
        + (sorted_ts.nbytes if sorted_ts is not None else 0)
    prep = {
        "n_out": int((presence > 0).sum()), "presence": presence,
        "n_seg": n_seg, "seg_pad": seg_pad, "slots": slots,
        "n_t": n_t, "utimes": utimes, "lab_table": lab_table,
        "gdims": gdims, "gvals": gvals, "sorted_ts": sorted_ts,
        "wants": {c: tuple(sorted(w)) for c, w in wants.items()},
        "seg_dev": seg_dev, "rank_dev": rank_dev, "cols_dev": cols_dev,
        "runs_dev": runs_dev, "runs_dummy": runs_dummy,
        "est_bytes": int(est * 2),   # host staging + device twin
    }
    if cache_ok:
        lead = live[0]
        lead._mesh_prep = (key, prep)
        bid = id(lead)
        _PREP_REFS[bid] = weakref.ref(
            lead, lambda _r, _bid=bid: _PREP_REFS.pop(_bid, None))
    return prep


def _run_collectives(prep, m) -> dict:
    """One collective merge program per aggregated column; fetch the
    replicated [n_seg] outputs in a single host pull each."""
    from ..parallel.distributed_agg import mesh_merge_kernel

    n_seg = prep["n_seg"]
    outs = {}
    for c, (vals_dev, valid_dev) in prep["cols_dev"].items():
        rids, rsegs, rpad = prep["runs_dev"].get(
            c, (prep["runs_dummy"], prep["runs_dummy"], 0))
        out = mesh_merge_kernel(
            vals_dev, valid_dev, prep["seg_dev"], prep["rank_dev"],
            rids, rsegs, mesh=m, slots=prep["slots"],
            num_segments=prep["seg_pad"], wants=prep["wants"][c],
            run_pad=rpad)
        outs[c] = {k: np.asarray(v)[:n_seg] for k, v in out.items()}  # lint: disable=host-sync (audited transfer point: one replicated pull per merged column)
    return outs


def _empty_result(query):
    cols = {t: np.empty(0, dtype=object) for t in query.group_tags}
    for t in query.group_fields:
        cols[t] = np.empty(0, dtype=object)
    if query.time_bucket is not None:
        cols["time"] = np.empty(0, dtype=np.int64)
    for a in query.aggs:
        cols[a.alias] = np.empty(0)
    return AggResult(cols, 0)


def _assemble_merged(prep, query, fetched) -> AggResult:
    """Merged partials → the AggResult `_merge_results_vec` would have
    produced: rows are the present segments in (group id, bucket) code
    order, with the same dtypes and validity rules."""
    presence = prep["presence"]
    n_t = prep["n_t"]
    sel = np.nonzero(presence > 0)[0]
    n_out = len(sel)
    out_cols: dict[str, np.ndarray] = {}
    out_valid: dict[str, np.ndarray] = {}
    grp = sel // n_t
    # field-group label columns peel innermost-first (NULL = top code)
    for fi in range(len(query.group_fields) - 1, -1, -1):
        dim = prep["gdims"][fi]
        codes = grp % dim
        grp = grp // dim
        vtab = np.empty(dim, dtype=object)
        vtab[:len(prep["gvals"][fi])] = prep["gvals"][fi]
        vtab[dim - 1] = None
        out_cols[query.group_fields[fi]] = vtab[codes]
    if query.group_tags:
        for i, t in enumerate(query.group_tags):
            col = np.empty(len(prep["lab_table"]), dtype=object)
            col[:] = [lab[i] for lab in prep["lab_table"]]
            out_cols[t] = col[grp]
    if query.time_bucket is not None:
        out_cols["time"] = prep["utimes"][sel % n_t]
    for a in query.aggs:
        if a.column is None:
            # count(*): presence IS the per-segment row count
            out_cols[a.alias] = presence[sel].astype(np.int64)
            continue
        col = fetched[a.column]
        cnt = col["count"][sel]
        has = cnt > 0
        if a.func == "count":
            out_cols[a.alias] = cnt.astype(np.int64)
        elif a.func in ("sum", "min", "max"):
            out_cols[a.alias] = col[a.func][sel]
            out_valid[a.alias] = has
        else:   # first / last
            out_cols[a.alias] = np.where(has, col[a.func][sel],
                                         np.zeros(1, col[a.func].dtype))
            rk = col[f"{a.func}_rank"][sel].astype(np.int64)
            ts = prep["sorted_ts"][
                np.clip(rk, 0, len(prep["sorted_ts"]) - 1)]
            out_cols[a.alias + "__ts"] = np.where(has, ts, 0)
            out_valid[a.alias] = has
    res = AggResult(out_cols, n_out, out_valid)
    return res
