"""Operator placement: choose where the fused scan kernel runs.

A database picks physical operators by cost; on a TPU host the choice is
between the accelerator and host XLA (same jit program, different
backend). The accelerator wins when data stays HBM-resident and the
PCIe/ICI pipe is real; it loses when every launch must re-stream inputs
through a thin transport (some dev environments reach the chip via a
network relay at ~100-250MB/s with tens-of-ms fixed costs per transfer —
measured in this repo's bench notes). We probe the pipe once per process
and place accordingly.

Override with CNOSDB_TPU_PLACEMENT = device | cpu | auto (default auto).
"""
from __future__ import annotations

import os
import time

import numpy as np

import jax

_placement_device = None

# below this, per-query input re-streaming dominates any kernel win
MIN_PIPE_MBS = 500.0


def _probe_pipe_mbs(dev) -> float:
    """Round-trip 4MB to `dev` twice; → effective MB/s (worst of puts/pulls)."""
    a = np.zeros(524_288, dtype=np.float64)  # 4MB
    worst = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        x = jax.device_put(a, dev)
        jax.block_until_ready(x)
        put_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        np.asarray(x)  # lint: disable=host-sync (the probe exists to time this pull)
        pull_dt = time.perf_counter() - t0
        worst = min(worst, a.nbytes / 1e6 / max(put_dt, pull_dt))
    return worst


def mesh_devices() -> list:
    """Device pool for the execution mesh (parallel/mesh.get_mesh): every
    device on the platform `scan_device()` resolved to. The same pipe
    probe that demotes single-device kernels to host numpy also governs
    the mesh — a degraded relay means the scan device is CPU, and the
    mesh then spans the (virtual) host devices instead of streaming every
    shard through the thin transport."""
    dev = scan_device()
    try:
        return list(jax.devices(dev.platform))
    except Exception:
        return [dev]


def scan_device():
    """The device the fused scan kernels (and DeviceBatches) live on."""
    global _placement_device
    if _placement_device is not None:
        return _placement_device
    mode = os.environ.get("CNOSDB_TPU_PLACEMENT", "auto").lower()
    default = jax.devices()[0]
    if mode == "device":
        _placement_device = default
        return _placement_device
    cpu = None
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:
        pass
    if mode == "cpu":
        _placement_device = cpu or default
        return _placement_device
    # auto: accelerator unless the pipe is degraded
    if default.platform == "cpu" or cpu is None:
        _placement_device = default
        return _placement_device
    mbs = _probe_pipe_mbs(default)
    _placement_device = default if mbs >= MIN_PIPE_MBS else cpu
    return _placement_device
