"""Device-side decode plane: the TSM codecs as batched accelerator kernels.

Cold scans were host-bound: every page decoded on the CPU (native or
numpy) and only the finished arrays crossed the PCIe pipe (BENCH_r05:
decode_ms 71 s cold vs 0.8 ms warm kernel time). Following "GPU
Acceleration of SQL Analytics on Compressed Data" (arxiv 2506.10092),
this module inverts that: host work stops at the byte-container stage
(zstd et al — storage/codecs.split_for_device), the still-narrow
post-container payloads ship to the device, and the per-value codec
transforms run there as batched jitted kernels:

  delta / delta_ts   widen -> unzigzag -> cumsum   (i64, u64 bit-rides)
  delta const-stride first + stride * iota          (18-byte pages)
  gorilla f64        byte-plane assembly -> log-step prefix-XOR scan
                     (native/bytetrans.h as lane-parallel u32 planes;
                     a Pallas kernel when CNOSDB_TPU_PALLAS allows,
                     else lax.associative_scan)
  bitpack bool       bit-expansion from packed u8
  string dict pages  narrow code widening (codes on device; the Python
                     dictionary itself stays host-side)

Batching: pages are padded into fixed-shape [B, L] buffers keyed by
(kind, width, pow2 length bucket) and B is padded to a pow2, so the jit
cache sees a handful of shapes regardless of page-size jitter. Outputs
are bit-identical to storage/codecs.decode (verified by the property
suite in tests/test_device_decode.py) because every transform is
integer/bitwise: XOR scans, two's-complement cumsum and bitcasts have no
rounding.

Gating mirrors pallas_kernels: CNOSDB_DEVICE_DECODE=1 forces the lane on
(interpret/XLA-on-CPU backends included — how tests engage it), =0 off,
auto enables it only when the scan device is a real TPU. The scan layer
(storage/scan) receives a DeviceDecodeLane via `decode_hook` so storage
itself stays jax-free; every page the lane examines but does not decode
books a (lane, reason) outcome — surfaced as
cnosdb_device_decode_total{lane,reason} and required by the
device-decode-accounting lint rule.
"""
from __future__ import annotations

import functools
import os
import threading

import numpy as np

import jax
import jax.numpy as jnp

from ..models.codec import Encoding
from ..models.schema import ValueType
from ..utils import stages
from . import pallas_kernels

try:  # pallas import is deferred-fail: CPU-only deployments keep working
    from jax.experimental import pallas as pl
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    pl = None
    PALLAS_AVAILABLE = False

# TPU lane width: value buckets are pow2 multiples of this, so the last
# (vectorized) dimension always tiles cleanly
_MIN_LANE = 128
_WIDTH_DTYPE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def enabled() -> bool:
    """Should scans route decodes through this plane?
    CNOSDB_DEVICE_DECODE=1 forces on (XLA/interpret on CPU backends —
    the test/bench mode), =0 off; default: only on a real TPU."""
    return disabled_reason() is None


def disabled_reason() -> str | None:
    """None when the lane is usable, else WHY not — bench.py reports it
    next to pallas_disabled_reason so a silent fallback is visible."""
    mode = os.environ.get("CNOSDB_DEVICE_DECODE", "auto").lower()
    if mode in ("1", "on", "true"):
        return None
    if mode in ("0", "off", "false"):
        return f"disabled by env CNOSDB_DEVICE_DECODE={mode}"
    from .placement import scan_device

    try:
        dev = scan_device()
    except Exception as e:  # no jax devices at all
        return f"device probe failed: {e!r}"
    if dev.platform != "tpu":
        return f"scan device is {dev.platform!r}, not tpu (auto mode)"
    return None


# ---------------------------------------------------------------------------
# engagement + outcome accounting
# ---------------------------------------------------------------------------
_LOCK = threading.Lock()
_engagements = 0
_outcomes: dict[tuple[str, str], int] = {}


def note_engaged(n: int = 1) -> None:
    global _engagements
    with _LOCK:
        _engagements += n
    stages.count("device_decode_engagements", n)


def engagements() -> int:
    """Pages decoded by the device lane this process (bench.py records
    this next to pallas_engagements so BENCH_r* shows lane adoption)."""
    with _LOCK:
        return _engagements


def count_outcome(lane: str, reason: str, n: int = 1) -> None:
    """Book n pages as handled by `lane` ("device" or "host") for
    `reason` — the raw series behind cnosdb_device_decode_total."""
    with _LOCK:
        _outcomes[(lane, reason)] = _outcomes.get((lane, reason), 0) + n


def outcomes_snapshot() -> dict[tuple[str, str], int]:
    with _LOCK:
        return dict(sorted(_outcomes.items()))


def _pow2(n: int, minimum: int) -> int:
    n = max(int(n), minimum)
    return 1 << (n - 1).bit_length()


# ---------------------------------------------------------------------------
# kernels (pure XLA; gorilla optionally via Pallas)
# ---------------------------------------------------------------------------
@jax.jit
def _delta_kernel(zz, firsts):
    """[B, L] narrow zigzag deltas + [B] firsts -> [B, L] i64 values.

    Row b carries n_b-1 deltas zero-padded to L; out[b, i] =
    first_b + sum(deltas[:i]) so out[b, :n_b] matches the host decode
    (two's-complement cumsum wraps identically to numpy's)."""
    u = zz.astype(jnp.uint64)
    one = jnp.uint64(1)
    dec = (u >> one) ^ (jnp.uint64(0) - (u & one))   # unzigzag, in u64
    d = jax.lax.bitcast_convert_type(dec, jnp.int64)
    csum = jnp.cumsum(d, axis=1)
    zero = jnp.zeros((d.shape[0], 1), jnp.int64)
    return firsts[:, None] + jnp.concatenate([zero, csum[:, :-1]], axis=1)


@functools.partial(jax.jit, static_argnames=("length",))
def _delta_const_kernel(firsts, strides, length):
    """Constant-stride timestamp fast path: first + stride * iota."""
    idx = jnp.arange(length, dtype=jnp.int64)
    return firsts[:, None] + strides[:, None] * idx[None, :]


def _assemble_planes(planes):
    """[B, 8, L] u8 byte planes (plane k = byte k of each u64, little
    endian) -> (lo, hi) u32 halves of the XOR'd u64 stream."""
    p = planes.astype(jnp.uint32)
    lo = p[:, 0] | (p[:, 1] << 8) | (p[:, 2] << 16) | (p[:, 3] << 24)
    hi = p[:, 4] | (p[:, 5] << 8) | (p[:, 6] << 16) | (p[:, 7] << 24)
    return lo, hi


def _combine_f64(lo, hi):
    u = lo.astype(jnp.uint64) | (hi.astype(jnp.uint64) << jnp.uint64(32))
    return jax.lax.bitcast_convert_type(u, jnp.float64)


@jax.jit
def _gorilla_xla_kernel(planes):
    """Gorilla f64: untranspose + prefix-XOR scan, XOR running as two
    independent u32 planes (XOR is bytewise, so the split is exact)."""
    lo, hi = _assemble_planes(planes)
    lo = jax.lax.associative_scan(jnp.bitwise_xor, lo, axis=1)
    hi = jax.lax.associative_scan(jnp.bitwise_xor, hi, axis=1)
    return _combine_f64(lo, hi)


@jax.jit
def _gorilla_pre_kernel(planes):
    return _assemble_planes(planes)


@jax.jit
def _gorilla_post_kernel(lo, hi):
    return _combine_f64(lo, hi)


def _make_xor_scan_body(steps: int):
    """Pallas kernel body: log-step (Hillis-Steele) inclusive XOR scan
    over the lane axis — `steps` = log2(bucket length) unrolled at trace
    time, each row tile VMEM-resident."""
    def body(x_ref, o_ref):
        x = x_ref[...]
        for k in range(steps):
            s = 1 << k
            x = x ^ jnp.concatenate(
                [jnp.zeros_like(x[:, :s]), x[:, :-s]], axis=1)
        o_ref[...] = x
    return body


def _pallas_xor_scan(x, interpret: bool):
    b, width = x.shape
    steps = max(width.bit_length() - 1, 0)   # width is a pow2 bucket
    return pl.pallas_call(
        _make_xor_scan_body(steps),
        grid=(b,),
        in_specs=[pl.BlockSpec((1, width), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, width), jnp.uint32),
        interpret=interpret,
    )(x)


@jax.jit
def _bitpack_kernel(packed):
    """[B, Lb] packed u8 -> [B, Lb*8] 0/1 u8 (MSB-first, np.packbits)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, :, None] >> shifts[None, None, :]) & jnp.uint8(1)
    return bits.reshape(packed.shape[0], -1)


@jax.jit
def _codes_kernel(codes):
    """Narrow dictionary codes -> i32 (the DictArray code dtype)."""
    return codes.astype(jnp.int32)


# ---------------------------------------------------------------------------
# the scan-facing lane
# ---------------------------------------------------------------------------
class _Job:
    __slots__ = ("plan", "token", "colname", "vt", "out_off", "n_rows",
                 "nm", "out_vals", "out_valid", "sink", "dev")


class DeviceDecodeLane:
    """One scan's device-decode batch builder.

    Driven by storage/scan._scan_vnode_native: `submit()` during page
    planning (plans come from codecs.split_for_device — storage stays
    jax-free, this object crosses the boundary via `decode_hook`), one
    `run()` that executes the batched kernels, writes host outputs back
    (null-mask expansion included) and returns the tokens of pages whose
    kernel failed (the caller re-routes those through the Python lane),
    then `attach_device_columns()` hands fully device-decoded, null-free,
    contiguously-covering columns to the EagerUploader ON DEVICE — the
    decoded values never re-cross the pipe, and tpu_exec's fused
    filter->segment-aggregate launch consumes them via the existing
    `_preuploaded` plumbing.
    """

    _NUMERIC_ENC = {
        int(ValueType.FLOAT): {int(Encoding.GORILLA)},
        int(ValueType.INTEGER): {int(Encoding.DELTA),
                                 int(Encoding.DELTA_TS)},
        int(ValueType.UNSIGNED): {int(Encoding.DELTA),
                                  int(Encoding.DELTA_TS)},
        int(ValueType.BOOLEAN): {int(Encoding.BITPACK),
                                 int(Encoding.NULL)},
    }

    def __init__(self, interpret: bool | None = None):
        if interpret is None:
            from .placement import scan_device

            interpret = scan_device().platform != "tpu"
        self._interpret = bool(interpret)
        self._use_pallas = PALLAS_AVAILABLE and pallas_kernels.enabled()
        self._jobs: list[_Job] = []

    def accepts(self, value_type: int, encoding: int) -> bool:
        """Cheap pre-check: does (value_type, encoding) have a device
        kernel at all? (String pages always submit — the container
        codec id is not page-visible without reading the block.)"""
        ok = self._NUMERIC_ENC.get(int(value_type))
        return ok is not None and int(encoding) in ok

    def declined(self, reason: str, n: int = 1) -> None:
        """Book n pages the scan examined but routed to a host lane."""
        count_outcome("host", reason, n)

    def pending(self) -> int:
        return len(self._jobs)

    def submit(self, plan: dict, token, colname, vt, out_off: int,
               n_rows: int, nm, out_vals, out_valid, sink=None) -> None:
        """Queue one page. Numeric/time pages write into
        out_vals/out_valid at out_off (nm = null mask, as
        read_field_page returns); string pages deliver dense i32 codes
        to `sink` instead."""
        j = _Job()
        j.plan, j.token, j.colname, j.vt = plan, token, colname, vt
        j.out_off, j.n_rows, j.nm = out_off, n_rows, nm
        j.out_vals, j.out_valid, j.sink = out_vals, out_valid, sink
        j.dev = None
        self._jobs.append(j)

    # ------------------------------------------------------------- execute
    def run(self) -> list:
        """Execute every submitted page as batched kernels; → failed
        tokens for the caller's Python lane. Every page leaves here
        either decoded or reason-booked (device-decode-accounting rule)."""
        failed: list = []
        groups: dict = {}
        for j in self._jobs:
            groups.setdefault(self._group_key(j), []).append(j)
        for key, jobs in groups.items():
            try:
                dev_rows = self._run_group(key, jobs)
            except Exception:
                stages.count_error("device_decode.kernel")
                for j in jobs:
                    count_outcome("host", "kernel_error")
                    failed.append(j.token)
                continue
            for j, dev in zip(jobs, dev_rows):
                j.dev = dev
                self._writeback(j, np.asarray(dev))  # lint: disable=host-sync (audited transfer point: the decode lane's one pull per row group)
            count_outcome("device", "ok", len(jobs))
            note_engaged(len(jobs))
        return failed

    def _group_key(self, j: _Job):
        p = j.plan
        kind = p["kind"]
        if kind == "bitpack":
            return (kind, 1, _pow2((p["n"] + 7) // 8, _MIN_LANE // 8))
        width = p.get("width", 8)
        return (kind, width, _pow2(p["n"], _MIN_LANE))

    def _run_group(self, key, jobs):
        """One (kind, width, length-bucket) batch -> per-job device rows
        (each sliced to its true value count, still on device)."""
        kind, width, lane_len = key
        b_pad = _pow2(len(jobs), 1)
        if kind == "delta_const":
            firsts = np.zeros(b_pad, np.int64)
            strides = np.zeros(b_pad, np.int64)
            for bi, j in enumerate(jobs):
                firsts[bi] = j.plan["first"]
                strides[bi] = j.plan["stride"]
            out = _delta_const_kernel(self._put(firsts),
                                      self._put(strides), length=lane_len)
        elif kind == "delta":
            zz = np.zeros((b_pad, lane_len), dtype=_WIDTH_DTYPE[width])
            firsts = np.zeros(b_pad, np.int64)
            for bi, j in enumerate(jobs):
                raw = np.frombuffer(j.plan["raw"], dtype=zz.dtype)
                zz[bi, :len(raw)] = raw
                firsts[bi] = j.plan["first"]
            out = _delta_kernel(self._put(zz), self._put(firsts))
        elif kind == "gorilla":
            planes = np.zeros((b_pad, 8, lane_len), dtype=np.uint8)
            for bi, j in enumerate(jobs):
                n = j.plan["n"]
                planes[bi, :, :n] = np.frombuffer(
                    j.plan["raw"], dtype=np.uint8).reshape(8, n)
            pd = self._put(planes)
            if self._use_pallas:
                lo, hi = _gorilla_pre_kernel(pd)
                lo = _pallas_xor_scan(lo, self._interpret)
                hi = _pallas_xor_scan(hi, self._interpret)
                out = _gorilla_post_kernel(lo, hi)
                pallas_kernels.note_engaged()
            else:
                out = _gorilla_xla_kernel(pd)
        elif kind == "bitpack":
            packed = np.zeros((b_pad, lane_len), dtype=np.uint8)
            for bi, j in enumerate(jobs):
                raw = np.frombuffer(j.plan["raw"], dtype=np.uint8)
                nb = (j.plan["n"] + 7) // 8
                packed[bi, :nb] = raw[:nb]
            out = _bitpack_kernel(self._put(packed))
        else:   # dict codes
            codes = np.zeros((b_pad, lane_len), dtype=_WIDTH_DTYPE[width])
            for bi, j in enumerate(jobs):
                raw = np.frombuffer(j.plan["raw"], dtype=codes.dtype)
                codes[bi, :len(raw)] = raw
            out = _codes_kernel(self._put(codes))
        return [out[bi, :j.plan["n"]] for bi, j in enumerate(jobs)]

    def _put(self, a: np.ndarray):
        from .device_cache import _put

        return _put(a)

    def _writeback(self, j: _Job, dense: np.ndarray) -> None:
        """Host-side landing: expand the dense kernel output through the
        page's null mask into the scan's output arrays (same contract as
        the Python page lane)."""
        if j.sink is not None:
            j.sink(dense)
            return
        if j.vt == ValueType.UNSIGNED:
            dense = dense.view(np.uint64)
        elif j.vt == ValueType.BOOLEAN:
            dense = dense.astype(np.bool_)
        off, n = j.out_off, j.n_rows
        if j.nm is None:
            j.out_vals[off:off + n] = dense
            if j.out_valid is not None:
                j.out_valid[off:off + n] = True
        else:
            j.out_vals[off:off + n][~j.nm] = dense
            j.out_valid[off:off + n] = ~j.nm

    # ------------------------------------------------------ device columns
    def attach_device_columns(self, uploader, total: int) -> None:
        """Hand columns whose EVERY page decoded on-device, null-free and
        covering [0, total) contiguously, to the EagerUploader as device
        arrays (no host round-trip). Anything else already landed in the
        host arrays and uploads lazily/eagerly as before."""
        bycol: dict[str, list[_Job]] = {}
        for j in self._jobs:
            if j.colname is None or j.sink is not None:
                continue
            bycol.setdefault(j.colname, []).append(j)
        for name, jobs in bycol.items():
            jobs.sort(key=lambda j: j.out_off)
            if any(j.dev is None or j.nm is not None for j in jobs):
                count_outcome("device", "column_not_resident")
                continue
            off = 0
            for j in jobs:
                if j.out_off != off:
                    off = -1
                    break
                off += j.n_rows
            if off != total:
                count_outcome("device", "column_not_resident")
                continue
            try:
                uploader.put_device(name, jobs[0].vt,
                                    [j.dev for j in jobs])
            except Exception:
                stages.count_error("device_decode.attach")
