"""TpuExec: the device scan-aggregate physical operator.

This is the rebuild's `TpuTableProvider`/`TpuExec` (north star in
BASELINE.json): the counterpart of the reference's TskvExec +
AggregateFilterTskvExec + DataFusion partial AggregateExec
(query_server/query/src/extension/physical/plan_node/tskv_exec.rs:36,
aggregate_filter_scan.rs:27), collapsed into one fused device program per
scanned column:

    host: ScanBatch (from storage.scan) → bucket i32 / group i32 / rank i32
    device: filter mask → segment ids → masked segment reductions
    host: segment labels (tag values, bucket starts) + presence masking

Group-by cardinality maps to segments = group × time-bucket; dense bucket
ranges index directly, sparse ones remap through np.unique.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..models.schema import ValueType
from ..models.strcol import DictArray
from ..storage.scan import ScanBatch
from ..sql.expr import Expr
from ..utils import deadline as _deadline
from . import kernels

_DENSE_BUCKET_LIMIT = 1 << 21

# Guards the per-batch derived caches (_seg_cache / _partials) hanging off
# SHARED scan-cache-resident batches: concurrent queries over one cached
# snapshot race the get-or-create, the eviction pop and the read-modify-
# write memo merge. One process-wide lock — the guarded sections are dict
# bookkeeping only (no kernel work), so contention is negligible.
import threading as _threading
from ..utils import lockwatch

_BATCH_CACHE_LOCK = lockwatch.Lock("tpu_exec.batch_cache")

# process-wide memo observability (satellite of the materialized-rollup
# plane: view-vs-memo hit rates must be comparable on /metrics). Counters
# and the live-batch set share _BATCH_CACHE_LOCK with the memo itself —
# every touch point already holds or takes that lock once.
_MEMO_COUNTERS = {"hit": 0, "miss": 0, "evict": 0}
import weakref as _weakref

# id(batch) → batch, weakly held (ScanBatch is an eq dataclass, so not
# hashable — keyed by identity; entries vanish with their batch)
_memo_batches: "_weakref.WeakValueDictionary" = _weakref.WeakValueDictionary()


def _memo_count(kind: str, n: int = 1) -> None:
    with _BATCH_CACHE_LOCK:
        _MEMO_COUNTERS[kind] = _MEMO_COUNTERS.get(kind, 0) + n


def memo_counters_snapshot() -> dict:
    with _BATCH_CACHE_LOCK:
        return dict(_MEMO_COUNTERS)


def memo_bytes() -> int:
    """Resident bytes across every live batch's partial-agg memo."""
    total = 0
    with _BATCH_CACHE_LOCK:
        batches = list(_memo_batches.values())
    for b in batches:
        partials = getattr(b, "_partials", None)
        if not partials:
            continue
        for part in list(partials.values()):
            for v in part.values():
                nb = getattr(v, "nbytes", None)
                if nb is not None:
                    total += int(nb)
    return total


def memo_clear(target_bytes: int = 0) -> int:
    """Broker reclaim: drop partial-agg memos (pure caches — a cleared
    memo recomputes on next touch). target_bytes=0 clears everything."""
    freed = 0
    with _BATCH_CACHE_LOCK:
        batches = list(_memo_batches.values())
    for b in batches:
        if target_bytes and freed >= target_bytes:
            break
        partials = getattr(b, "_partials", None)
        if not partials:
            continue
        with _BATCH_CACHE_LOCK:
            n = 0
            for part in list(partials.values()):
                for v in part.values():
                    nb = getattr(v, "nbytes", None)
                    if nb is not None:
                        n += int(nb)
            evicted = len(partials)
            partials.clear()
            _MEMO_COUNTERS["evict"] = \
                _MEMO_COUNTERS.get("evict", 0) + evicted
        freed += n
    return freed


def _register_memo_pool() -> None:
    from ..server import memory as _memory

    _memory.register_pool("agg_memo",
                          usage_fn=memo_bytes,
                          reclaim=memo_clear)


_register_memo_pool()


def _FORCE_DEVICE() -> bool:
    import os

    return os.environ.get("CNOSDB_TPU_FORCE_DEVICE_PATH", "0") == "1"


@dataclass
class AggSpec:
    func: str               # count/count_star/sum/mean/min/max/first/last
    column: str | None      # None for count(*)
    alias: str
    param: object = None    # extra constant arg (e.g. sample size k)

    _NEEDS = {
        "count": {"want_count": True},
        "sum": {"want_sum": True},
        "mean": {"want_sum": True, "want_count": True},
        "avg": {"want_sum": True, "want_count": True},
        "min": {"want_min": True},
        "max": {"want_max": True},
        "first": {"want_first": True},
        "last": {"want_last": True},
    }


@dataclass
class TpuQuery:
    filter: Expr | None = None
    # native-kernel thread budget per batch (0 = all cores); the executor
    # divides cores across concurrently-launched vnode batches so 8 pool
    # workers don't each spawn a full-width native pool (oversubscription
    # was the round-4 cold kernel bottleneck)
    kernel_threads: int = 0
    group_tags: list[str] = field(default_factory=list)
    # GROUP BY on STRING field columns: their dictionary codes extend the
    # segment id directly (group = tags × field-codes × bucket) — the
    # hits-style string group-by runs the same integer kernels as tags,
    # never the row-materializing relational fallback
    group_fields: list[str] = field(default_factory=list)
    time_bucket: tuple[int, int] | None = None   # (origin_ns, interval_ns)
    aggs: list[AggSpec] = field(default_factory=list)


@dataclass
class AggResult:
    """Columnar result: group label columns then one column per agg."""

    columns: dict[str, np.ndarray]
    n_rows: int
    # per-column validity (NULL where a group had no values for that agg)
    valid: dict[str, np.ndarray] = field(default_factory=dict)
    # tag-group identity for the VECTORIZED cross-vnode merge: per-row
    # local group index + the label table it indexes (None when string
    # field group axes are present — those merge via the generic path)
    gid: np.ndarray | None = None
    labels: list | None = None


def execute_scan_aggregate(batch: ScanBatch, query: TpuQuery) -> AggResult:
    return finish_scan_aggregate(launch_scan_aggregate(batch, query))


def finish_scan_aggregate(job) -> AggResult:
    """Complete a launched job: fetch device partials (one transfer) and
    assemble the result table."""
    if isinstance(job, AggResult):
        return job
    return job()


def _tag_group_layout(batch: ScanBatch, group_tags: list[str]):
    """series → tag-group mapping. → (group_of_series i32 [n_series],
    group_labels [tag tuples], n_groups)."""
    if group_tags:
        label_of_series = []
        group_map: dict[tuple, int] = {}
        for key in batch.series_keys:
            tags = key.tag_dict() if key is not None else {}
            label = tuple(tags.get(t) for t in group_tags)
            gid = group_map.setdefault(label, len(group_map))
            label_of_series.append(gid)
        group_of_series = np.array(label_of_series, dtype=np.int32)
        group_labels = [None] * len(group_map)
        for label, gid in group_map.items():
            group_labels[gid] = label
        return group_of_series, group_labels, len(group_map)
    return np.zeros(batch.n_series, dtype=np.int32), [()], 1


def _gf_layout(batch: ScanBatch, group_fields: list[str], n: int):
    """GROUP BY field axes: per field the dictionary-code axis (+1 slot
    for the NULL group key). Factorizations are immutable per scan
    snapshot and cached on the batch (numeric np.unique at 10M rows costs
    ~100s of ms per query) — the ScanToken-persistent half of the key
    factorization plane. → (gf_dims, gf_dicts, gf_codes)."""
    gf_dims: list[int] = []
    gf_dicts: list[np.ndarray] = []
    gf_codes: list[np.ndarray] = []
    gf_cache = getattr(batch, "_gf_cache", None)
    if gf_cache is None and group_fields:
        gf_cache = batch._gf_cache = {}
    for fcol in group_fields:
        hit = gf_cache.get(fcol)
        if hit is not None:
            dim, dic, codes = hit
            gf_dims.append(dim)
            gf_dicts.append(dic)
            gf_codes.append(codes)
            continue
        # bound sized to the query: evicting below the current key-set
        # would thrash every repeat of a multi-field GROUP BY
        gf_bound = max(2, len(group_fields))
        f = batch.fields.get(fcol)
        if f is None:  # column absent in this vnode: every row groups NULL
            while len(gf_cache) >= gf_bound:
                gf_cache.pop(next(iter(gf_cache)))
            gf_cache[fcol] = (1, np.empty(0, dtype=object),
                              np.zeros(n, dtype=np.int64))
            gf_dims.append(1)
            gf_dicts.append(np.empty(0, dtype=object))
            gf_codes.append(np.zeros(n, dtype=np.int64))
            continue
        _vt, vals, valid = f
        from ..utils import stages as _stages

        with _stages.stage("factorize_ms"):
            if _vt in (ValueType.STRING, ValueType.GEOMETRY):
                da = vals if isinstance(vals, DictArray) \
                    else DictArray.from_objects(vals)
                u = len(da.values)
                codes = da.codes.astype(np.int64)
                dic = da.values
            else:
                # numeric group keys factorize per batch (np.unique
                # collapses NaNs to one group, matching DataFusion)
                arr = np.asarray(vals)
                if _vt == ValueType.BOOLEAN:
                    arr = arr.astype(np.int64)
                uniq, inv = np.unique(arr, return_inverse=True)
                u = len(uniq)
                codes = inv.astype(np.int64)
                dic = uniq.astype(object)
                if _vt == ValueType.BOOLEAN:
                    dic = np.array([bool(x) for x in uniq], dtype=object)
            if not bool(valid.all()):
                codes = np.where(valid, codes, u)
        while len(gf_cache) >= gf_bound:
            gf_cache.pop(next(iter(gf_cache)))
        gf_cache[fcol] = (u + 1, dic, codes)
        gf_dims.append(u + 1)
        gf_dicts.append(dic)
        gf_codes.append(codes)
    return gf_dims, gf_dicts, gf_codes


def _bucket_geometry(batch: ScanBatch, time_bucket):
    """→ (ts_lo, ts_hi, origin, interval, bmin, dense_span); min/max are
    immutable per scan snapshot and cached (a 100M-row i64 min+max costs
    ~150ms — pure waste on every repeated query)."""
    mm = getattr(batch, "_ts_minmax", None)
    if mm is None:
        mm = batch._ts_minmax = (int(batch.ts.min()), int(batch.ts.max()))
    ts_lo, ts_hi = mm
    if time_bucket is not None:
        origin, interval = time_bucket
        bmin = (ts_lo - origin) // interval
        bmax = (ts_hi - origin) // interval
        return ts_lo, ts_hi, origin, interval, bmin, int(bmax - bmin + 1)
    return ts_lo, ts_hi, 0, 0, 0, 1


def _seg_layout(batch: ScanBatch, group_tags, group_fields, group_of_series,
                gf_dims, gf_codes, origin, interval, bmin, dense_span,
                cpu_mode: bool):
    """Per-row combined (tag × field × bucket) segment ids, cached on the
    batch under the same key the kernel path uses — one derivation serves
    both the segment kernels and the host distinct/collect merges.
    → (seg_ids, bucket_starts, n_buckets, seg_cache, seg_key)."""
    from ..utils import stages as _stages

    n = batch.n_rows
    seg_key = (tuple(group_tags), tuple(group_fields),
               origin, interval, bmin, dense_span)
    with _BATCH_CACHE_LOCK:
        seg_cache = getattr(batch, "_seg_cache", None)
        if seg_cache is None:
            seg_cache = batch._seg_cache = {}
        cached = seg_cache.get(seg_key)
    if cached is not None:
        _stages.count("kernel_cache.hit")
        seg_ids, bucket_starts, n_buckets = cached[:3]
        return seg_ids, bucket_starts, n_buckets, seg_cache, seg_key
    _stages.count("kernel_cache.miss")
    group_of_row = group_of_series[batch.sid_ordinal]
    if gf_dims:
        group_of_row = group_of_row.astype(np.int64)
        for dim, codes in zip(gf_dims, gf_codes):
            group_of_row = group_of_row * dim + codes
    if interval:
        b = (batch.ts - origin) // interval
        if dense_span <= _DENSE_BUCKET_LIMIT:
            bucket_ids = (b - bmin).astype(np.int32)
            bucket_starts = origin + (bmin + np.arange(
                dense_span, dtype=np.int64)) * interval
            n_buckets = dense_span
        else:
            uniq, inv = np.unique(b, return_inverse=True)
            bucket_ids = inv.astype(np.int32)
            bucket_starts = origin + uniq * interval
            n_buckets = len(uniq)
    else:
        bucket_ids = np.zeros(n, dtype=np.int32)
        bucket_starts = None
        n_buckets = 1
    # i64 on the numpy path: bincount would otherwise re-cast an
    # i32 key array to intp on EVERY call (a 40ms copy at 10M rows)
    seg_dtype = np.int64 if cpu_mode else np.int32
    seg_ids = (group_of_row.astype(np.int64) * n_buckets
               + bucket_ids.astype(np.int64)).astype(seg_dtype)
    # small LRU with eviction. NOTE this derived-cache memory rides
    # the batch outside the MemoryPool's admission accounting, so
    # the bound is deliberately tight: ≤2 shapes ≈ 2×8B/row plus
    # run layout + rank/order ≈ 8B/row — ~24B/row worst case on a
    # scan-cache-resident batch
    with _BATCH_CACHE_LOCK:
        while len(seg_cache) >= 2:
            seg_cache.pop(next(iter(seg_cache)))
        # slots: seg_ids, bucket_starts, n_buckets, counts,
        #        run_starts, run_counts (runs built lazily)
        seg_cache[seg_key] = [seg_ids, bucket_starts, n_buckets,
                              None, None, None]
    return seg_ids, bucket_starts, n_buckets, seg_cache, seg_key


@dataclass
class HostGroupLayout:
    """Decoded group/segment layout for host-side merges (_merge_distinct
    in sql/executor.py): per-row combined segment ids plus the tables
    that decode a segment back to its (tag tuple, field values, bucket
    start) group key. Built from the same per-batch caches the kernel
    path populates, so a warm rescan pays nothing."""

    seg_ids: np.ndarray
    num_segments: int
    n_buckets: int
    bucket_starts: np.ndarray | None
    group_labels: list
    gf_dims: list
    gf_dicts: list
    gf_codes: list


def host_group_layout(batch: ScanBatch, group_tags: list[str],
                      group_fields: list[str],
                      time_bucket) -> HostGroupLayout | None:
    """Segment layout for host-side distinct/collect merges, sharing the
    ScanToken-persistent _gf_cache/_seg_cache with launch_scan_aggregate
    (identical cache keys — whichever path runs first seeds the other)."""
    n = batch.n_rows
    if n == 0:
        return None
    group_of_series, group_labels, n_groups = _tag_group_layout(
        batch, group_tags)
    gf_dims, gf_dicts, gf_codes = _gf_layout(batch, group_fields, n)
    for d in gf_dims:
        n_groups *= d
    _lo, _hi, origin, interval, bmin, dense_span = _bucket_geometry(
        batch, time_bucket)
    from .placement import scan_device

    cpu_mode = scan_device().platform == "cpu" and not _FORCE_DEVICE()
    seg_ids, bucket_starts, n_buckets, _, _ = _seg_layout(
        batch, group_tags, group_fields, group_of_series, gf_dims,
        gf_codes, origin, interval, bmin, dense_span, cpu_mode)
    return HostGroupLayout(
        seg_ids=seg_ids, num_segments=n_groups * n_buckets,
        n_buckets=n_buckets, bucket_starts=bucket_starts,
        group_labels=group_labels, gf_dims=gf_dims, gf_dicts=gf_dicts,
        gf_codes=gf_codes)


def host_row_mask(batch: ScanBatch, flt) -> np.ndarray | None:
    """Filter-passing row mask with the exact semantics of the host scan
    path below (three-valued logic, missing-column handling, conjunctive
    per-column NULL masking) — shared with the mesh exec lane
    (ops/mesh_exec.py) so sharded and single-device answers agree on the
    same row set. None means no filter (every row participates)."""
    if flt is None:
        return None
    n = batch.n_rows
    env = _filter_env(batch, needed=flt.columns())
    has_is_null = _contains_is_null(flt)
    missing = [c for c in flt.columns() if c not in env]
    if missing and not has_is_null:
        # a schema column with no data in this vnode is all-NULL here:
        # any comparison on it matches nothing
        return np.zeros(n, dtype=bool)
    for c in missing:  # IS NULL paths need the env entries
        env[c] = np.zeros(n)
        env[f"__valid__:{c}"] = np.zeros(n, dtype=bool)
    row_mask = np.asarray(flt.eval(env, np), dtype=bool)
    if row_mask.shape == ():  # constant predicate
        row_mask = np.full(n, bool(row_mask))
    if is_conjunctive(flt):
        skip = is_null_columns(flt) if has_is_null else set()
        av_cache = getattr(batch, "_allvalid_cache", None)
        if av_cache is None:
            av_cache = batch._allvalid_cache = {}
        for cname in flt.columns() - skip:
            f = batch.fields.get(cname)
            if f is None:
                continue
            hit = av_cache.get(cname)
            if hit is None:
                hit = av_cache[cname] = bool(f[2].all())
            if not hit:
                row_mask &= f[2]
    return row_mask


def launch_scan_aggregate(batch: ScanBatch, query: TpuQuery):
    """Start a scan-aggregate; device kernels are dispatched asynchronously
    so a coordinator can launch every vnode's kernel before fetching any
    result (device→host pulls carry fixed relay latency)."""
    n = batch.n_rows
    if n == 0:
        names = query.group_tags + query.group_fields \
            + (["time"] if query.time_bucket else []) \
            + [a.alias for a in query.aggs]
        return AggResult({nm: np.empty(0) for nm in names}, 0)

    # ------------------------------------------------ grouping: series → group
    group_of_series, group_labels, n_groups = _tag_group_layout(
        batch, query.group_tags)

    # ---------------------------------------- string-field group dimensions
    # each GROUP BY field contributes its dictionary-code axis (+1 slot for
    # the NULL group key); combined gid = ((tag_gid·d1 + c1)·d2 + c2)…
    gf_dims, gf_dicts, gf_codes = _gf_layout(batch, query.group_fields, n)
    for d in gf_dims:
        n_groups *= d

    # ------------------------------------------------ aggregate wants
    col_wants: dict[str, dict] = {}
    for a in query.aggs:
        if a.column is None:
            continue
        w = col_wants.setdefault(a.column, {
            "want_count": False, "want_sum": False, "want_min": False,
            "want_max": False, "want_first": False, "want_last": False})
        for k, v in AggSpec._NEEDS[a.func].items():
            w[k] = w[k] or v
    needs_rank = any(a.func in ("first", "last") for a in query.aggs)

    # ------------------------------------------------ bucket geometry (meta only)
    ts_lo, ts_hi, origin, interval, bmin, dense_span = _bucket_geometry(
        batch, query.time_bucket)

    arith = None
    if query.time_bucket is not None:
        from .fused import bucket_arith_params

        arith = bucket_arith_params(ts_lo, origin, interval, int(bmin),
                                    max_span_ns=ts_hi - ts_lo)
    i32_ok = (ts_hi - ts_lo) < (2**31 - 2) * 1_000_000_000
    # placement: when the scan device resolved to CPU (no accelerator, or a
    # degraded host↔device pipe), the pure-numpy host kernels beat XLA's
    # CPU scatter lowering — the fused path is for real devices
    from .placement import scan_device

    # CNOSDB_TPU_FORCE_DEVICE_PATH=1 is a TEST override: it runs the fused
    # DeviceBatch/launch_fused program (and the aggregate_column_host XLA
    # wrapper) on whatever backend jax has — CI exercises the device
    # placement on the CPU backend, where it would otherwise never engage
    # (round-3 verdict: the device path shipped with zero test coverage)
    cpu_mode = scan_device().platform == "cpu" and not _FORCE_DEVICE()
    eff_buckets = dense_span if dense_span <= _DENSE_BUCKET_LIMIT \
        else min(n, dense_span)   # sparse remap keeps occupied buckets only
    if gf_dims and n_groups * eff_buckets > (1 << 24):
        # only the new string-field axes can blow this up — tag-only
        # queries keep the pre-existing dense/sparse bucket behavior
        from ..errors import PlanError

        e = PlanError(
            f"group-by cardinality {n_groups} groups × {dense_span} buckets "
            "exceeds the segment-kernel budget")
        e.fallback_relational = True
        raise e

    use_device = (not cpu_mode
                  and not query.group_fields
                  and _device_eligible(batch, query, col_wants, dense_span)
                  and i32_ok
                  and (query.time_bucket is None or arith is not None))

    if use_device:
        from .device_cache import device_batch
        from .fused import launch_fused

        n_buckets = dense_span if query.time_bucket is not None else 1
        if query.time_bucket is not None:
            bucket_starts = origin + (bmin + np.arange(n_buckets, dtype=np.int64)) * interval
        else:
            bucket_starts = None
        num_segments = n_groups * n_buckets
        dbatch = device_batch(batch)
        pending = launch_fused(dbatch, query.filter, group_of_series,
                               n_groups, n_buckets, arith, col_wants)

        def complete():
            res = pending.fetch()
            presence = res.pop("__presence__")["count"]
            present = presence > 0
            col_results = {c: res.get(c) for c in col_wants}
            return _assemble(batch, query, presence, present, col_results,
                             group_labels, bucket_starts, n_buckets,
                             needs_rank, order=None)

        return complete
    else:
        # ------------------------------ fused native single-pass path
        # the C++ twin of the device kernel (native/segagg.cpp): segment
        # derivation + masked reductions in ONE GIL-free multithreaded
        # sweep — this is what makes the COLD scan competitive (the
        # numpy pipeline below costs several full-array passes)
        seg_cache_probe = getattr(batch, "_seg_cache", None)
        probe_key = (tuple(query.group_tags), tuple(query.group_fields),
                     origin, interval, bmin, dense_span)
        if seg_cache_probe is None or probe_key not in seg_cache_probe:
            # cold only: a warm repeat reuses the cached numpy segment
            # layout below, which beats re-sweeping the batch; the fused
            # pass SEEDS that cache with the per-row segment ids it
            # derives anyway
            fused = _try_native_fused(batch, query, col_wants,
                                      group_of_series, n_groups, origin,
                                      interval, bmin, dense_span,
                                      group_labels, needs_rank,
                                      seg_cache_key=probe_key)
            if fused is not None:
                return fused
        # ---------------------------------------- host-prep path
        # segment-id derivation is identical across repeated queries of the
        # same (group tags, bucket) shape over one scan snapshot — cache it
        # on the batch (same rationale as the reference's TsmReader cache:
        # re-derivation, not decode, dominates repeat queries)
        seg_ids, bucket_starts, n_buckets, seg_cache, seg_key = _seg_layout(
            batch, query.group_tags, query.group_fields, group_of_series,
            gf_dims, gf_codes, origin, interval, bmin, dense_span, cpu_mode)
        num_segments = n_groups * n_buckets

        def cached_runs():
            """Run layout of the cached segment ids (storage batches are
            series-contiguous + time-ordered per series, so segments form
            runs; kernels.run_boundaries). → (starts, run_counts)."""
            entry = seg_cache.get(seg_key)
            if entry is None:
                # evicted by a concurrent query's insert: recompute locally
                entry = [seg_ids, bucket_starts, n_buckets, None, None, None]
            if entry[4] is None:
                entry[4] = kernels.run_boundaries(seg_ids, batch.sid_ordinal)
                entry[5] = np.diff(np.append(entry[4], n))
            return entry[4], entry[5]

        # string-field group keys shred the per-series run structure (a
        # run per value change): skip run-layout construction entirely
        prefer_flat = bool(gf_dims)

        def cached_counts() -> np.ndarray:
            """Group sizes over ALL rows — derived from the cached run
            layout (O(runs), not O(n)), so repeated queries pay nothing
            (count/presence of all-valid unfiltered columns)."""
            entry = seg_cache.get(seg_key)
            if entry is not None:
                if entry[3] is None or len(entry[3]) < num_segments:
                    if prefer_flat:
                        entry[3] = np.bincount(
                            seg_ids, minlength=num_segments).astype(np.int64)
                    else:
                        starts, rcounts = cached_runs()
                        entry[3] = np.bincount(
                            seg_ids[starts], weights=rcounts,
                            minlength=num_segments).astype(np.int64)
                return entry[3][:num_segments]
            return np.bincount(seg_ids, minlength=num_segments) \
                .astype(np.int64)

        # per-column validity is immutable for one scan snapshot: memoize
        # the .all() reductions (a 10M-bool reduce costs ~4ms per query)
        av_cache = getattr(batch, "_allvalid_cache", None)
        if av_cache is None:
            av_cache = batch._allvalid_cache = {}

        def col_all_valid(cname, valid):
            hit = av_cache.get(cname)
            if hit is None:
                hit = av_cache[cname] = bool(valid.all())
            return hit

        # -------------------------------------------- filter
        row_mask = None   # None = no filter, every row participates
        sel_idx = None
        zone_pruned = False
        if query.filter is not None and cpu_mode \
                and not _contains_is_null(query.filter):
            # data skipping: block min/max zone maps (the reference's page
            # statistics pruning, reader/column_group/statistics.rs) — a
            # selective filter touches only candidate blocks
            from . import zonemap

            pb = zonemap.possible_blocks(query.filter, batch)
            if pb is not None and len(pb) and pb.mean() <= 0.25:
                idx = zonemap.candidate_rows(pb, n)
                sel_idx = _eval_filter_on_rows(batch, query.filter, idx)
                zone_pruned = True
        if query.filter is not None and not zone_pruned:
            row_mask = np.ones(n, dtype=bool)
            env = _filter_env(batch, needed=query.filter.columns())
            has_is_null = _contains_is_null(query.filter)
            missing = [c for c in query.filter.columns() if c not in env]
            if missing and not has_is_null:
                # a schema column with no data in this vnode is all-NULL
                # here: any comparison on it matches nothing
                row_mask = np.zeros(n, dtype=bool)
            else:
                for c in missing:  # IS NULL paths need the env entries
                    env[c] = np.zeros(n)
                    env[f"__valid__:{c}"] = np.zeros(n, dtype=bool)
                row_mask = np.asarray(query.filter.eval(env, np), dtype=bool)
                if row_mask.shape == ():  # constant predicate
                    row_mask = np.full(n, bool(row_mask))
                # SQL three-valued logic: a NULL operand makes a comparison
                # non-matching. Comparison LEAVES are already masked in
                # sql.expr; the post-hoc pass below additionally covers
                # bare-column and NOT-wrapped predicates, and is only
                # sound for conjunctive (OR-free) filters — per-column,
                # skipping columns under an explicit IS NULL
                if is_conjunctive(query.filter):
                    skip = is_null_columns(query.filter) if has_is_null \
                        else set()
                    for cname in query.filter.columns() - skip:
                        if cname in batch.fields and not col_all_valid(
                                cname, batch.fields[cname][2]):
                            row_mask &= batch.fields[cname][2]
        if zone_pruned:
            all_rows = len(sel_idx) == n
            if all_rows:
                sel_idx = None
        else:
            all_rows = row_mask is None or bool(row_mask.all())
            if row_mask is None:
                row_mask = np.ones(n, dtype=bool) if not cpu_mode \
                    else None  # the numpy path never touches it when all_rows
            if not all_rows:
                if cpu_mode:
                    # compress ONCE under a selective filter: every kernel
                    # then touches O(selected) rows, not O(n) masked arrays
                    sel_idx = np.nonzero(row_mask)[0]
                else:
                    seg_ids = np.where(row_mask, seg_ids, 0).astype(np.int32)

        # -------------------------------------------- rank for first/last
        # run kernels resolve first/last from per-run endpoint timestamps
        # (no O(n log n) argsort); the rank machinery remains for the XLA
        # host wrapper, unordered synthetic batches, and string columns
        ordered = _ordered_within_series(batch)
        fl_string = any(
            a.func in ("first", "last")
            and ((a.column in batch.fields
                  and batch.fields[a.column][0] in (ValueType.STRING,
                                                    ValueType.GEOMETRY))
                 # TAG columns aggregate through the string path too
                 or (a.column is not None and a.column != "time"
                     and a.column not in batch.fields))
            for a in query.aggs)
        rank_based_fl = needs_rank and (not cpu_mode or not ordered
                                        or fl_string)
        if rank_based_fl:
            rank = getattr(batch, "_rank_cache", None)
            if rank is None:
                order = np.argsort(batch.ts, kind="stable")
                rank = np.empty(n, dtype=np.int32)
                rank[order] = np.arange(n, dtype=np.int32)
                batch._rank_cache = rank
                batch._order_cache = order
            order = batch._order_cache
        else:
            order = None
            rank = getattr(batch, "_zero_rank", None)
            if rank is None or len(rank) != n:
                rank = batch._zero_rank = np.zeros(n, dtype=np.int32)

        # -------------------------------------------- per-column kernels
        seg_kernel = (kernels.numpy_segment_partials if cpu_mode
                      else kernels.aggregate_column_host)
        sel_runs = None
        ts_sel = None
        if cpu_mode and sel_idx is not None and not prefer_flat:
            seg_sel = seg_ids[sel_idx]
            starts_sel = kernels.run_boundaries(
                seg_sel, batch.sid_ordinal[sel_idx])
            rcounts_sel = np.diff(np.append(starts_sel, len(seg_sel)))
            sel_runs = (seg_sel, starts_sel, rcounts_sel)
            if needs_rank and not rank_based_fl:
                ts_sel = batch.ts[sel_idx]
        if all_rows:
            presence = cached_counts()
        elif sel_runs is not None:
            seg_sel, starts_sel, rcounts_sel = sel_runs
            presence = np.bincount(
                seg_sel[starts_sel] if len(seg_sel) else seg_sel[:0],
                weights=rcounts_sel,
                minlength=num_segments).astype(np.int64)
        elif sel_idx is not None:
            presence = np.bincount(seg_ids[sel_idx],
                                   minlength=num_segments).astype(np.int64)
        else:
            presence = seg_kernel(
                np.zeros(n, dtype=np.int64), row_mask, seg_ids, rank,
                num_segments,
                {"want_count": True, "want_sum": False, "want_min": False,
                 "want_max": False})["count"]
        present = presence > 0

        # ------------------------- partial-result memoization (warm path)
        # a scan snapshot is immutable, so per-column segment partials
        # under a fixed segmentation are pure functions of (snapshot,
        # seg_key, column, wants): repeated UNFILTERED queries reuse them
        # in O(segments) instead of re-sweeping O(n) rows (the reference
        # re-reads from its TsmReader cache; this engine's warm contract
        # is the decoded snapshot + its derived partials). The cold
        # native fused pass seeds the same cache.
        memo_ok = query.filter is None and sel_idx is None \
            and (row_mask is None or all_rows)
        with _BATCH_CACHE_LOCK:
            partials = getattr(batch, "_partials", None)
            if partials is None:
                partials = batch._partials = {}

        def memo_get(cname, wants):
            if not memo_ok:
                return None
            hit = partials.get((seg_key, cname))
            if hit is not None:
                for need in _wanted_keys(wants):
                    if need not in hit:
                        hit = None
                        break
            _memo_count("hit" if hit is not None else "miss")
            return hit

        def memo_put(cname, r):
            if memo_ok and isinstance(r, dict):
                with _BATCH_CACHE_LOCK:
                    old = partials.get((seg_key, cname))
                    merged = {**old, **r} if old else dict(r)
                    while len(partials) >= 16:
                        partials.pop(next(iter(partials)))
                        _MEMO_COUNTERS["evict"] += 1
                    partials[(seg_key, cname)] = merged
                    _memo_batches[id(batch)] = batch

        col_results = {}
        for cname, wants in col_wants.items():
            # deadline checkpoint between partial-agg chunks: each column
            # is a host-staging + device-dispatch unit, so an expired or
            # killed request stops before paying for the next column
            _deadline.check_current()
            cached_r = memo_get(cname, wants)
            if cached_r is not None:
                col_results[cname] = cached_r
                continue
            if cname == "time":
                # min/max/first/last/count over the time column itself:
                # timestamps are always valid i64
                vt, vals, valid = ValueType.INTEGER, batch.ts, \
                    np.ones(n, dtype=bool)
            elif cname not in batch.fields:
                if batch.n_series:
                    # aggregate over a TAG column (count(station) etc.):
                    # synthesize per-row values from the series keys; the
                    # planner already validated the name, so a non-field
                    # here is a tag (reference: tags are Utf8 dictionary
                    # columns and aggregate like strings)
                    per = np.array(
                        [None if k is None else k.tag_value(cname)
                         for k in batch.series_keys], dtype=object)
                    vals = per[batch.sid_ordinal]
                    valid = np.array([x is not None for x in vals],
                                     dtype=bool)
                    vt = ValueType.STRING
                else:
                    col_results[cname] = None
                    continue
            else:
                vt, vals, valid = batch.fields[cname]
            if vt in (ValueType.STRING, ValueType.GEOMETRY):
                if sel_idx is not None:
                    sv = np.zeros(n, dtype=bool)
                    sv[sel_idx] = True
                    sv &= valid
                elif row_mask is not None:
                    sv = valid & row_mask
                else:
                    sv = valid
                r = _host_string_agg(
                    vals, sv, seg_ids, rank, num_segments, wants)
                memo_put(cname, r)
                col_results[cname] = r
                continue
            if vt == ValueType.BOOLEAN:
                dev_vals = vals.astype(np.int64)
            elif vt == ValueType.UNSIGNED and not cpu_mode:
                # order-preserving bias: u64 ^ 2^63 viewed as i64 keeps the
                # kernel's comparisons/min/max exact for values ≥ 2^63;
                # sums stay exact mod 2^64 and _assemble un-biases. The
                # numpy path compares/accumulates uint64 natively: no bias.
                dev_vals = (np.asarray(vals, dtype=np.uint64)
                            ^ np.uint64(1 << 63)).view(np.int64)
            else:
                dev_vals = vals
            all_valid = col_all_valid(cname, valid)
            col_fl = wants.get("want_first") or wants.get("want_last")
            if cpu_mode and not (col_fl and rank_based_fl) \
                    and not (prefer_flat and not col_fl):
                # (string-field group keys without first/last skip the
                # run-aware block entirely — the scatter kernels below do
                # flat bincounts over sel_idx/valid subsets)
                # ------------------------------- run-aware host kernels
                need_ts = bool(col_fl)
                if all_rows and all_valid:
                    starts, rcounts = cached_runs()
                    if not col_fl and len(starts) > (n >> 2):
                        # fine-grained runs (string-field group keys shred
                        # the per-series run structure): a flat bincount
                        # scatter beats reduceat over ~n tiny runs
                        r = kernels.numpy_segment_partials(
                            dev_vals, valid, seg_ids, rank, num_segments,
                            {**wants, "want_count": False},
                            assume_all_valid=True)
                    else:
                        r = kernels.run_segment_partials(
                            dev_vals, seg_ids, starts, num_segments,
                            {**wants, "want_count": False},
                            ts=batch.ts if need_ts else None,
                            run_counts=rcounts)
                    r["count"] = presence
                elif all_valid and sel_runs is not None:
                    seg_sel, starts_sel, rcounts_sel = sel_runs
                    if not col_fl and len(starts_sel) > (len(seg_sel) >> 2):
                        r = kernels.numpy_segment_partials(
                            dev_vals[sel_idx],
                            np.ones(len(seg_sel), dtype=bool), seg_sel,
                            rank[sel_idx], num_segments,
                            {**wants, "want_count": False},
                            assume_all_valid=True)
                    else:
                        r = kernels.run_segment_partials(
                            dev_vals[sel_idx], seg_sel, starts_sel,
                            num_segments, {**wants, "want_count": False},
                            ts=(ts_sel if ts_sel is not None
                                else (batch.ts[sel_idx] if need_ts else None)),
                            run_counts=rcounts_sel)
                    r["count"] = presence
                else:
                    # nulls present: compress valid rows — compression
                    # preserves the run structure
                    if sel_idx is not None:
                        vsub = valid[sel_idx]
                        idx2 = sel_idx if vsub.all() else sel_idx[vsub]
                    else:
                        idx2 = np.flatnonzero(valid)
                    seg2 = seg_ids[idx2]
                    starts2 = kernels.run_boundaries(
                        seg2, batch.sid_ordinal[idx2])
                    r = kernels.run_segment_partials(
                        dev_vals[idx2], seg2, starts2, num_segments,
                        {**wants, "want_count": True},
                        ts=batch.ts[idx2] if need_ts else None)
                memo_put(cname, r)
                col_results[cname] = r
                continue
            # --------------------------- rank/scatter fallback kernels
            if sel_idx is not None:
                # compressed path: gather selected rows once per column
                v_sel = dev_vals[sel_idx]
                valid_sel = (np.ones(len(sel_idx), dtype=bool) if all_valid
                             else valid[sel_idx])
                col_results[cname] = seg_kernel(
                    v_sel, valid_sel, seg_ids[sel_idx], rank[sel_idx],
                    num_segments, {**wants, "want_count": True})
                continue
            if all_rows and all_valid and cpu_mode:
                # count == cached group sizes; skip the redundant bincount
                r = kernels.numpy_segment_partials(
                    dev_vals, valid, seg_ids, rank, num_segments,
                    {**wants, "want_count": False}, assume_all_valid=True)
                r["count"] = presence
                memo_put(cname, r)
                col_results[cname] = r
                continue
            col_valid = valid if all_rows else (valid & row_mask)
            r = seg_kernel(
                dev_vals, col_valid, seg_ids, rank, num_segments,
                {**wants, "want_count": True})
            memo_put(cname, r)
            col_results[cname] = r

        return _assemble(batch, query, presence, present, col_results,
                         group_labels, bucket_starts, n_buckets, needs_rank,
                         order, unsigned_biased=not cpu_mode,
                         gf=(gf_dims, gf_dicts) if gf_dims else None)


def _wanted_keys(wants: dict):
    """Result-dict keys a wants spec needs (memo superset matching)."""
    out = ["count"]
    if wants.get("want_sum"):
        out.append("sum")
    if wants.get("want_min"):
        out.append("min")
    if wants.get("want_max"):
        out.append("max")
    if wants.get("want_first"):
        out += ["first"]
    if wants.get("want_last"):
        out += ["last"]
    return out


def _kernel_threads(query: TpuQuery) -> int:
    if query.kernel_threads > 0:
        return query.kernel_threads
    import os

    return min(8, os.cpu_count() or 1)


def _try_native_fused(batch, query, col_wants, group_of_series, n_groups,
                      origin, interval, bmin, dense_span, group_labels,
                      needs_rank, seg_cache_key=None):
    """Route qualifying scan-aggregates through native fused_seg_agg_f64:
    unfiltered dense-bucket queries whose aggregates are count/sum/mean/
    min/max over FLOAT columns (+ count(*)). Returns a complete() closure
    or None to fall back."""
    from ..storage import native

    if not native.available():
        return None
    if query.group_fields:
        return None
    if query.filter is not None and _contains_is_null(query.filter):
        return None   # IS NULL filters keep the classic 3VL machinery
    if query.time_bucket is not None and dense_span > _DENSE_BUCKET_LIMIT:
        return None
    for a in query.aggs:
        if a.func not in ("count", "sum", "mean", "avg", "min", "max",
                          "first", "last"):
            return None
        if a.column is not None and a.column != "time":
            f = batch.fields.get(a.column)
            if f is None or f[0] != ValueType.FLOAT:
                return None
        if a.column == "time":
            return None
    n_buckets = dense_span if query.time_bucket is not None else 1
    num_segments = n_groups * n_buckets
    if num_segments > (1 << 26):
        return None
    lut = group_of_series.astype(np.int64)
    sid = np.ascontiguousarray(batch.sid_ordinal, dtype=np.int32)
    ts = np.ascontiguousarray(batch.ts, dtype=np.int64)
    row_mask = None
    if query.filter is not None:
        # full-array eval (no index gathers): same semantics as
        # _eval_filter_on_rows with rows=None
        n = batch.n_rows
        cols = query.filter.columns()
        env = _filter_env(batch, needed=cols)
        if any(c not in env for c in cols):
            row_mask = np.zeros(n, dtype=np.uint8)
        else:
            m = np.asarray(query.filter.eval(env, np))
            if m.shape == ():
                m = np.full(n, bool(m))
            m = m.astype(bool)
            if is_conjunctive(query.filter):
                for c in cols:
                    v = env.get(f"__valid__:{c}")
                    if v is not None and not v.all():
                        m &= v
            row_mask = m.astype(np.uint8)
    col_results: dict = {}
    presence = None
    want_seg = seg_cache_key is not None
    seg_out = None
    for cname, wants in col_wants.items():
        f = batch.fields[cname]
        vals = np.ascontiguousarray(f[1], dtype=np.float64)
        valid = f[2]
        valid_u8 = None if bool(valid.all()) else \
            np.ascontiguousarray(valid, dtype=np.uint8)
        # count always rides along: _assemble derives validity (has any
        # value) from it for every aggregate
        r = native.fused_seg_agg_f64(
            ts, sid, lut, origin, interval, int(bmin),
            n_buckets if query.time_bucket is not None else 0,
            vals, valid_u8, row_mask, num_segments,
            {**wants, "want_count": True}, out_seg=want_seg,
            n_threads=_kernel_threads(query))
        if r is None:
            return None
        presence = r.pop("presence")
        seg_out = r.pop("seg", seg_out)
        want_seg = False   # one seg pass is enough
        if query.filter is None and seg_cache_key is not None:
            # seed the warm-path partials memo: the fused pass already
            # computed these over the full snapshot (same eviction cap
            # as memo_put — unbounded shapes must not pile up on one
            # long-lived cached batch)
            with _BATCH_CACHE_LOCK:
                partials = getattr(batch, "_partials", None)
                if partials is None:
                    partials = batch._partials = {}
                old = partials.get((seg_cache_key, cname))
                while len(partials) >= 16:
                    partials.pop(next(iter(partials)))
                partials[(seg_cache_key, cname)] = \
                    {**old, **r} if old else dict(r)
        col_results[cname] = r
    if presence is None:
        # count(*)-only query: presence pass without a value column
        r = native.fused_seg_agg_f64(
            ts, sid, lut, origin, interval, int(bmin),
            n_buckets if query.time_bucket is not None else 0,
            None, None, row_mask, num_segments, {},
            n_threads=_kernel_threads(query))
        if r is None:
            return None
        presence = r["presence"]
    present = presence > 0
    if query.time_bucket is not None:
        bucket_starts = origin + (int(bmin) + np.arange(
            n_buckets, dtype=np.int64)) * interval
    else:
        bucket_starts = None
    if seg_out is not None:
        # seed the warm-path segment cache (slots: seg_ids,
        # bucket_starts, n_buckets, counts, run_starts, run_counts) —
        # seg ids are filter-independent; counts only cacheable when no
        # filter shaped this presence
        with _BATCH_CACHE_LOCK:
            seg_cache = getattr(batch, "_seg_cache", None)
            if seg_cache is None:
                seg_cache = batch._seg_cache = {}
            while len(seg_cache) >= 2:
                seg_cache.pop(next(iter(seg_cache)))
            seg_cache[seg_cache_key] = [
                seg_out, bucket_starts, n_buckets,
                presence if row_mask is None else None, None, None]

    def complete():
        return _assemble(batch, query, presence, present, col_results,
                         group_labels, bucket_starts, n_buckets,
                         needs_rank=False, order=None,
                         unsigned_biased=False)

    return complete


def _assemble(batch, query, presence, present, col_results, group_labels,
              bucket_starts, n_buckets, needs_rank, order,
              unsigned_biased: bool = True, gf=None) -> AggResult:
    out_cols: dict[str, np.ndarray] = {}
    out_valid: dict[str, np.ndarray] = {}
    sel = np.nonzero(present)[0]
    grp_idx = (sel // n_buckets).astype(np.int64)
    bkt_idx = (sel % n_buckets).astype(np.int64)
    if gf is not None:
        # peel the field-code axes off the combined gid (innermost first);
        # code == U is the NULL group key
        gf_dims, gf_dicts = gf
        gid = grp_idx
        for fcol, dim, dic in zip(reversed(query.group_fields),
                                  reversed(gf_dims), reversed(gf_dicts)):
            code = gid % dim
            gid = gid // dim
            lab = np.empty(len(code), dtype=object)
            non_null = code < (dim - 1)
            if non_null.any():
                lab[non_null] = dic[code[non_null]]
            out_cols[fcol] = lab
        grp_idx = gid
    for i, t in enumerate(query.group_tags):
        lab_col = np.empty(len(group_labels), dtype=object)
        lab_col[:] = [lab[i] for lab in group_labels]
        out_cols[t] = lab_col[grp_idx]
    if bucket_starts is not None:
        out_cols["time"] = bucket_starts[bkt_idx]

    for a in query.aggs:
        if a.column is None:
            out_cols[a.alias] = presence[sel]
            continue
        r = col_results.get(a.column)
        if r is None:
            if a.func == "count":  # COUNT of an absent column is 0, never NULL
                out_cols[a.alias] = np.zeros(len(sel), dtype=np.int64)
            else:
                out_cols[a.alias] = np.zeros(len(sel))
                out_valid[a.alias] = np.zeros(len(sel), dtype=bool)
            continue
        cnt = r.get("count")
        unsigned = (unsigned_biased and a.column in batch.fields
                    and batch.fields[a.column][0] == ValueType.UNSIGNED)
        boolean = (a.column in batch.fields
                   and batch.fields[a.column][0] == ValueType.BOOLEAN)

        def unbias(x):
            return (np.ascontiguousarray(x).view(np.uint64)
                    ^ np.uint64(1 << 63))

        def unbias_sum(s, c):
            # sum of biased vals = true_sum - count·2^63 (mod 2^64)
            return (np.ascontiguousarray(s).view(np.uint64)
                    + c.astype(np.uint64) * np.uint64(1 << 63))

        if a.func == "count":
            out_cols[a.alias] = cnt[sel]
        elif a.func in ("mean", "avg"):
            c = cnt[sel]
            s = (unbias_sum(r["sum"][sel], c).astype(np.float64) if unsigned
                 else r["sum"][sel].astype(np.float64))
            with np.errstate(invalid="ignore", divide="ignore"):
                out_cols[a.alias] = np.where(c > 0, s / np.maximum(c, 1), np.nan)
            out_valid[a.alias] = c > 0
        elif a.func == "sum":
            have = cnt[sel] > 0
            s = r["sum"][sel]
            out_cols[a.alias] = unbias_sum(s, cnt[sel]) if unsigned else s
            out_valid[a.alias] = have
        elif a.func in ("min", "max"):
            have = cnt[sel] > 0
            v = r[a.func][sel]
            v = unbias(v) if unsigned else v
            if boolean:
                v = v.astype(bool)   # kernels run bools as i64; the
                # value identity is BOOLEAN (min(f2) renders 'false')
            out_cols[a.alias] = v
            out_valid[a.alias] = have
        elif a.func in ("first", "last"):
            have = cnt[sel] > 0
            v = r[a.func][sel]
            v = unbias(v) if unsigned else v
            if boolean:
                # reference first/last render BOOLEAN as 1/0 (its
                # selector accumulator widens; min/max keep true/false —
                # function/common/first.slt vs min.slt)
                v = v.astype(np.int64)
            out_cols[a.alias] = v
            out_valid[a.alias] = have
            # hidden timestamp of the selected row: lets a coordinator merge
            # first/last partials across vnodes by actual time order. Run
            # kernels return the timestamps directly; rank kernels return
            # positions into the time-sorted order.
            tsv = r.get(f"{a.func}_ts")
            if tsv is not None:
                out_cols[a.alias + "__ts"] = tsv[sel]
            else:
                rk = r.get(f"{a.func}_rank")
                if rk is not None and needs_rank:
                    sorted_ts = _sorted_ts(batch, order)
                    ranks = np.clip(rk[sel], 0, len(sorted_ts) - 1)
                    out_cols[a.alias + "__ts"] = sorted_ts[ranks]
    return AggResult(out_cols, len(sel), out_valid,
                     gid=(grp_idx if gf is None else None),
                     labels=(group_labels if gf is None else None))


def _sorted_ts(batch: ScanBatch, order) -> np.ndarray:
    cached = getattr(batch, "_sorted_ts", None)
    if cached is None:
        cached = batch.ts[order] if order is not None else np.sort(batch.ts, kind="stable")
        batch._sorted_ts = cached
    return cached


def _device_eligible(batch: ScanBatch, query: TpuQuery,
                     col_wants: dict, dense_span: int) -> bool:
    """Fused device path applies when the whole query is expressible over
    device-resident numeric columns (no strings/tags in filter or aggs, no
    IS NULL, dense bucket range)."""
    if dense_span > _DENSE_BUCKET_LIMIT:
        return False
    for cname in col_wants:
        if cname == "time":
            return False   # i64 timestamps never ride to device; host path
        f = batch.fields.get(cname)
        if f is not None and f[0] in (ValueType.STRING, ValueType.GEOMETRY):
            return False
        if f is not None and f[0] == ValueType.UNSIGNED:
            # the packed single-transfer output is f64; u64 values above
            # 2^53 would round — the host kernel path is exact (biased i64)
            return False
    if query.filter is not None:
        if _contains_is_null(query.filter):
            return False
        for c in query.filter.columns():
            f = batch.fields.get(c)
            if c == "time":
                return False  # i64 time never rides to device; host path
            if f is None:
                return False  # tag / absent column → host semantics
            if f[0] in (ValueType.STRING, ValueType.GEOMETRY):
                return False
    return True


def _contains_is_null(e) -> bool:
    from ..sql.expr import IsNull

    if isinstance(e, IsNull):
        return True
    for attr in ("left", "right", "operand", "expr", "low", "high"):
        sub = getattr(e, attr, None)
        if isinstance(sub, Expr) and _contains_is_null(sub):
            return True
    args = getattr(e, "args", None)
    if args:
        return any(_contains_is_null(a) for a in args)
    return False


def is_conjunctive(e) -> bool:
    """True when the filter tree contains no OR and no NOT: post-hoc
    validity masking (AND-ing a column's valid mask into the row mask) is
    only sound then — under a disjunction a row may match through a
    branch that never touches the NULL column, and NOT over AND is a
    disjunction by De Morgan (NOT (i = 5 AND f > 2) must match an
    i=NULL, f=0 row through the right branch). Non-conjunctive filters
    rely on the comparison-leaf masking in sql.expr instead."""
    from ..sql.expr import BinOp, UnaryOp

    if isinstance(e, BinOp) and e.op == "or":
        return False
    if isinstance(e, UnaryOp) and e.op == "not" and _contains_and(e.operand):
        return False
    from ..sql.expr import iter_child_exprs

    return all(is_conjunctive(c) for c in iter_child_exprs(e))


def _contains_and(e) -> bool:
    from ..sql.expr import BinOp, iter_child_exprs

    if isinstance(e, BinOp) and e.op == "and":
        return True
    return any(_contains_and(c) for c in iter_child_exprs(e))


def is_null_columns(e) -> set:
    """Columns referenced INSIDE NULL-aware nodes (IS NULL, CASE):
    validity masking must skip exactly these — masking them defeats the
    node's own NULL handling, while skipping masking for every other
    column lets its garbage NULL-slot values match."""
    from ..sql.expr import Case, IsNull, iter_child_exprs

    if isinstance(e, (IsNull, Case)):
        return set(e.columns())
    out: set = set()
    for c in iter_child_exprs(e):
        out |= is_null_columns(c)
    return out


def stacked_filter_masks(env: dict, filters: list, n_rows: int,
                         field_cols: set) -> np.ndarray:
    """Fused micro-batch filter stage: evaluate M member filters over ONE
    shared scan environment → an ``(M, n_rows)`` bool stack, one row mask
    per member. This is the demux half of batching — the scan (decode,
    upload, device dispatch) was paid once for the whole group; each
    member's mask applies the SAME 3VL conjunctive validity semantics as
    the solo path in `QueryExecutor._exec_raw_batches`, so fused results
    are bit-identical to solo. A ``None`` filter means "all rows"."""
    masks = np.empty((len(filters), n_rows), dtype=bool)
    for i, f in enumerate(filters):
        if f is None:
            masks[i] = True
            continue
        # full copy (np.array, not asarray): the eval result may BE a
        # shared-env column (filter `bool_field`), and the in-place
        # validity AND below must never write through to the env that
        # every other member reads
        m = np.array(f.eval(env, np), dtype=bool)
        if m.shape == ():
            m = np.full(n_rows, bool(m))
        if is_conjunctive(f):
            skip = is_null_columns(f)
            for c in f.columns() - skip:
                vk = f"__valid__:{c}"
                if c in field_cols and vk in env:
                    m &= env[vk]
        masks[i] = m
    return masks


def _ordered_within_series(batch: ScanBatch) -> bool:
    """True when (a) timestamps are non-decreasing within every series run
    AND (b) each series occupies exactly one contiguous run — the storage
    layout guarantees both for scan batches; synthetic batches are checked
    once and the result cached. Run-kernel first/last depend on both:
    without (b), filter/null compression can join two chunks of a
    recurring series into one run whose timestamps jump backwards at the
    seam, and run endpoints stop being the time extremes (sum/count/
    min/max never depend on either)."""
    cached = getattr(batch, "_ordered_ws", None)
    if cached is None:
        if batch.n_rows <= 1:
            cached = True
        else:
            changes = np.diff(batch.sid_ordinal) != 0
            ok = (np.diff(batch.ts) >= 0) | changes
            cached = bool(ok.all()) and \
                int(changes.sum()) + 1 == len(np.unique(batch.sid_ordinal))
        batch._ordered_ws = cached
    return cached


def _eval_filter_on_rows(batch: ScanBatch, flt: Expr,
                         idx: np.ndarray) -> np.ndarray:
    """Evaluate `flt` over the candidate rows only (zone-map pruning) —
    same semantics as the full-scan path sans IS NULL (callers exclude
    it): missing columns match nothing, a NULL field operand excludes the
    row. → selected row indices (subset of idx, ascending). Shares
    _filter_env so both paths build identical environments."""
    cols = flt.columns()
    env = _filter_env(batch, needed=cols, rows=idx)
    if any(c not in env for c in cols):
        return idx[:0]   # all-NULL column: comparisons match nothing
    mask = np.asarray(flt.eval(env, np), dtype=bool)
    if mask.shape == ():
        return idx if bool(mask) else idx[:0]
    if is_conjunctive(flt):   # see the 3VL notes in the classic path
        for c in cols:
            v = env.get(f"__valid__:{c}")
            if v is not None and not v.all():
                mask &= v
    return idx[np.flatnonzero(mask)]


def _filter_env(batch: ScanBatch, needed: set | None = None,
                rows: np.ndarray | None = None) -> dict:
    """Filter-evaluation env. `needed` restricts which columns materialize:
    per-row tag expansion builds 10M-element OBJECT arrays, so only tags
    the filter actually references are worth paying for. With `rows`, all
    entries are gathered to that index subset (zone-map candidate rows) —
    one construction path for both the full-scan and pruned evaluations."""
    def sub(a):
        return a if rows is None else a[rows]

    env: dict = {"time": sub(batch.ts)}
    for name, (vt, vals, valid) in batch.fields.items():
        if rows is not None and needed is not None and name not in needed:
            continue   # gathers cost O(rows); skip unreferenced fields
        env[name] = sub(vals)
        env[f"__valid__:{name}"] = sub(valid)
    tag_names = set()
    for k in batch.series_keys:
        if k is not None:
            tag_names.update(t.key for t in k.tags)
    if needed is not None:
        tag_names &= needed
    sid = None
    for t in tag_names:
        per_series = np.array(
            [(k.tag_value(t) if k is not None else None) for k in batch.series_keys],
            dtype=object)
        if sid is None:
            sid = sub(batch.sid_ordinal)
        env[t] = per_series[sid]
    return env


def _host_string_agg(vals, valid, seg_ids, rank, num_segments, wants):
    """String column aggregation on dictionary CODES (count/first/last/
    min/max): the sorted-dictionary invariant makes code order string
    order, so everything is integer ufunc.at — no per-row Python."""
    from ..models.strcol import DictArray

    if not isinstance(vals, DictArray):
        vals = DictArray.from_objects(vals)
    out = {}
    segv = seg_ids[valid]
    cv = vals.codes[valid].astype(np.int64)
    uniq = vals.values
    u = max(len(uniq), 1)
    count = np.bincount(segv, minlength=num_segments).astype(np.int64)
    out["count"] = count
    have = count > 0
    if wants.get("want_min") or wants.get("want_max"):
        mins_c = np.full(num_segments, np.iinfo(np.int64).max, dtype=np.int64)
        np.minimum.at(mins_c, segv, cv)
        maxs_c = np.full(num_segments, -1, dtype=np.int64)
        np.maximum.at(maxs_c, segv, cv)
        mins = np.empty(num_segments, dtype=object)
        maxs = np.empty(num_segments, dtype=object)
        mins[have] = uniq[mins_c[have]]
        maxs[have] = uniq[maxs_c[have]]
        out["min"], out["max"] = mins, maxs
    if wants.get("want_first") or wants.get("want_last"):
        # pack (rank, code) into one i64 so a single min/max scatter picks
        # both the extreme rank and the value it carries
        packed = rank[valid].astype(np.int64) * u + cv
        fpk = np.full(num_segments, np.iinfo(np.int64).max, dtype=np.int64)
        np.minimum.at(fpk, segv, packed)
        lpk = np.full(num_segments, -1, dtype=np.int64)
        np.maximum.at(lpk, segv, packed)
        fv = np.empty(num_segments, dtype=object)
        lv = np.empty(num_segments, dtype=object)
        fv[have] = uniq[fpk[have] % u]
        lv[have] = uniq[lpk[have] % u]
        fr = np.where(have, fpk // u, 2**31 - 1)
        lr = np.where(have, lpk // u, -(2**31))
        out["first"], out["last"] = fv, lv
        out["first_rank"], out["last_rank"] = fr, lr
    if wants.get("want_sum"):
        out["sum"] = np.zeros(num_segments)
    return out
