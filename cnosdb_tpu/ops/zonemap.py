"""Block min/max zone maps: data skipping for selective filters.

The host-side twin of the reference's page-statistics pruning
(tskv/src/reader/column_group/statistics.rs prunes ChunkReader pages by
PageMeta min/max): the scan batch is split into fixed blocks, each
column's per-block [min, max] is computed once and cached on the batch,
and a filter's conservative tri-state evaluation over those intervals
prunes blocks no row of which can match. The predicate is then evaluated
only over candidate-block rows — a selective filter touches O(matching
blocks) instead of O(n).

Conservativeness: invalid rows' slot values can only WIDEN a block's
interval (never narrow it), and NaNs are excluded via fmin/fmax, so a
pruned block provably contains no matching valid row.
"""
from __future__ import annotations

import numpy as np

from ..models.schema import ValueType
from ..models.strcol import DictArray
from ..sql.expr import Between, BinOp, Column, InList, Like, Literal
from . import strkernels

BLOCK = 8192


def _numeric(v) -> bool:
    return isinstance(v, (int, float, np.integer, np.floating)) \
        and not isinstance(v, bool)


def zone_stats(batch, cname: str):
    """Per-block (min, max) for a numeric field column or 'time', cached
    on the batch (one sequential pass, amortized across queries)."""
    cache = getattr(batch, "_zone_cache", None)
    if cache is None:
        cache = batch._zone_cache = {}
    hit = cache.get(cname)
    if hit is None:
        if cname == "time":
            vals = batch.ts
        else:
            vt, vals, _valid = batch.fields[cname]
            if vt in (ValueType.STRING, ValueType.GEOMETRY):
                return None
        starts = np.arange(0, len(vals), BLOCK)
        if vals.dtype.kind == "f":
            # fmin/fmax skip NaNs: a NaN row can never satisfy a
            # comparison, and letting it poison the interval would prune
            # blocks whose OTHER rows match
            bmin = np.fmin.reduceat(vals, starts)
            bmax = np.fmax.reduceat(vals, starts)
        else:
            bmin = np.minimum.reduceat(vals, starts)
            bmax = np.maximum.reduceat(vals, starts)
        hit = cache[cname] = (bmin, bmax)
    return hit


def _col_name(e, batch) -> str | None:
    """Column usable for zone evaluation: a numeric field or time."""
    if not isinstance(e, Column):
        return None
    if e.name == "time":
        return e.name
    f = batch.fields.get(e.name)
    if f is None or f[0] in (ValueType.STRING, ValueType.GEOMETRY):
        return None
    return e.name


def possible_blocks(e, batch) -> np.ndarray | None:
    """Conservative per-block match possibility for the filter tree, or
    None when any reachable leaf is outside the supported forms (the
    caller then evaluates the filter over every row as before)."""
    if isinstance(e, BinOp):
        if e.op in ("and", "or"):
            a = possible_blocks(e.left, batch)
            b = possible_blocks(e.right, batch)
            if e.op == "and":
                # one evaluable side suffices: AND can only shrink
                if a is None:
                    return b
                if b is None:
                    return a
                return a & b
            if a is None or b is None:
                return None
            return a | b
        if e.op in ("=", "!=", "<", "<=", ">", ">="):
            col, lit = None, None
            if isinstance(e.right, Literal):
                col, lit, op = _col_name(e.left, batch), e.right.value, e.op
            elif isinstance(e.left, Literal):
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                        "=": "=", "!=": "!="}
                col, lit, op = _col_name(e.right, batch), e.left.value, \
                    flip[e.op]
            if col is None or not _numeric(lit):
                return None
            st = zone_stats(batch, col)
            if st is None:
                return None
            bmin, bmax = st
            if op == ">":
                return bmax > lit
            if op == ">=":
                return bmax >= lit
            if op == "<":
                return bmin < lit
            if op == "<=":
                return bmin <= lit
            if op == "=":
                return (bmin <= lit) & (bmax >= lit)
            # '!=': only a constant block equal to lit can be pruned
            return ~((bmin == lit) & (bmax == lit))
        return None
    if isinstance(e, Between) and not e.negated:
        col = _col_name(e.expr, batch)
        if col is None or not isinstance(e.low, Literal) \
                or not isinstance(e.high, Literal) \
                or not _numeric(e.low.value) or not _numeric(e.high.value):
            return None
        st = zone_stats(batch, col)
        if st is None:
            return None
        bmin, bmax = st
        return (bmax >= e.low.value) & (bmin <= e.high.value)
    if isinstance(e, InList) and not e.negated:
        col = _col_name(e.expr, batch)
        if col is None or not e.values \
                or not all(_numeric(v) for v in e.values):
            return None
        st = zone_stats(batch, col)
        if st is None:
            return None
        bmin, bmax = st
        m = np.zeros(len(bmin), dtype=bool)
        for v in e.values:
            m |= (bmin <= v) & (bmax >= v)
        return m
    if isinstance(e, Like) and isinstance(e.pattern, str) \
            and isinstance(e.expr, Column):
        f = batch.fields.get(e.expr.name)
        if f is None:
            return None
        vt, vals, _valid = f
        if vt != ValueType.STRING or not isinstance(vals, DictArray) \
                or not len(vals):
            return None
        # per-unique LIKE mask, broadcast through codes, reduced per
        # block. Sound under negation too: a valid matching row always
        # sets its block; invalid rows (code 0) can only ADD blocks.
        mask, _reason = strkernels.unique_mask(vals.values, e.pattern)
        if e.negated:
            mask = ~mask
        rows = mask[vals.codes]
        starts = np.arange(0, len(rows), BLOCK)
        return np.logical_or.reduceat(rows, starts)
    return None


def candidate_rows(blocks: np.ndarray, n: int) -> np.ndarray:
    """Row indices (ascending) of the possible blocks."""
    cand = np.flatnonzero(blocks)
    if len(cand) == 0:
        return np.zeros(0, dtype=np.int64)
    idx = (cand[:, None] * BLOCK
           + np.arange(BLOCK, dtype=np.int64)).ravel()
    if idx[-1] >= n:
        idx = idx[idx < n]
    return idx
