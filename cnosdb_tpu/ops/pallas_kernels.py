"""Pallas TPU kernel for the segment-aggregate hot op.

The framework's hottest program is the masked segment reduction behind
scan-fused GROUP BY (ops/kernels.local_segment_partials). XLA lowers
`segment_sum` through sort/scatter; this kernel exploits the STORAGE
LAYOUT instead: scan batches are series-contiguous and time-ordered, so
the `group × n_buckets + bucket` segment ids each row tile touches span a
narrow contiguous window. Every grid step reduces its row tile into a
LOCAL window of `W` segments relative to a per-tile base (one VPU-masked
pass over an [R, W] broadcast — VMEM-resident, no scatter), writing an
independent [W] output block per tile; a final O(tiles·W) XLA
segment-sum/min/max folds the windows into the global segment array
(tiles·W ≪ rows, so the combine is noise).

Preconditions checked by the host wrapper (`applicable`): every R-row
tile's segment span fits in W. Storage scans guarantee this by
construction except at series boundaries, which the window absorbs; the
wrapper falls back to the XLA kernel otherwise — same contract as
ops/placement choosing between device and host.

Integration (kernels.aggregate_column_host routes here): `enabled()`
reads CNOSDB_TPU_PALLAS — "1" forces the kernel on, "0" off, unset/auto
enables it only when the scan device is a real TPU. Tests drive
segment_partials_pallas directly with interpret=True on the CPU backend
against the numpy_segment_partials oracle (tests/test_pallas_kernels.py).

Replaces the per-series reduction loop of the reference's reader tree
(tskv/src/reader/iterator.rs:94-121) on the device placement.
"""
from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

try:  # pallas import is deferred-fail: CPU-only deployments keep working
    from jax.experimental import pallas as pl
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

R_TILE = 256     # rows per grid step
W_WIN = 2048     # local segment window (16 × 128-lane groups)


def enabled() -> bool:
    """Should aggregate_column_host route through this kernel?
    CNOSDB_TPU_PALLAS=1 forces on (interpret-mode on CPU backends), =0
    off; default: only on a real TPU scan device."""
    return disabled_reason() is None


def disabled_reason() -> str | None:
    """None when the kernel is usable, else WHY it is not — the answer
    bench.py reports so a "pallas_enabled: false" line is actionable
    (env override vs broken import vs no TPU in the device probe)."""
    mode = os.environ.get("CNOSDB_TPU_PALLAS", "auto").lower()
    if mode in ("1", "on", "true"):
        return None if PALLAS_AVAILABLE \
            else "CNOSDB_TPU_PALLAS=1 but jax.experimental.pallas import failed"
    if mode in ("0", "off", "false"):
        return f"disabled by env CNOSDB_TPU_PALLAS={mode}"
    probe = os.environ.get("CNOSDB_BENCH_PROBE")
    if probe:
        # bench.py re-exec'd this process on CPU jax after its start-of-
        # bench relay probe failed; the verdict it stashed is the real
        # answer ("scan device is cpu" would bury it)
        return f"device probe failed at bench start: {probe}"
    if not PALLAS_AVAILABLE:
        return "jax.experimental.pallas import failed"
    from .placement import scan_device

    try:
        dev = scan_device()
    except Exception as e:  # no jax devices at all
        return f"device probe failed: {e!r}"
    if dev.platform != "tpu":
        return f"scan device is {dev.platform!r}, not tpu (auto mode)"
    return None


def _extrema(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype), jnp.array(-jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max, dtype), jnp.array(info.min, dtype)


def _kernel(base_ref, values_ref, valid_ref, seg_ref,
            cnt_ref, sum_ref, min_ref, max_ref):
    """One row tile → [W] partials relative to this tile's window base."""
    base = base_ref[0, 0]
    vals = values_ref[:]                        # [R]
    ok = valid_ref[:]                           # [R] int8 validity
    seg = seg_ref[:] - base                     # [R] i32, in [0, W)
    # [R, W] membership mask: row r contributes to window slot seg[r]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (R_TILE, W_WIN), 1)
    m = (seg[:, None] == lanes) & (ok[:, None] != 0)
    vcol = vals[:, None]
    zero = jnp.zeros((), vals.dtype)
    hi, lo = _extrema(vals.dtype)
    cnt_ref[0, :] = jnp.sum(m.astype(jnp.int32), axis=0)
    sum_ref[0, :] = jnp.sum(jnp.where(m, vcol, zero), axis=0)
    min_ref[0, :] = jnp.min(jnp.where(m, vcol, hi), axis=0)
    max_ref[0, :] = jnp.max(jnp.where(m, vcol, lo), axis=0)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def _windowed_partials(bases, values, valid, seg_ids, *, num_segments: int,
                       interpret: bool = False):
    """values/valid/seg_ids padded to a tile multiple; bases[t] = window
    base of tile t (padded rows carry valid=False, seg inside the tile's
    window)."""
    n = values.shape[0]
    tiles = n // R_TILE
    out_shape = [
        jax.ShapeDtypeStruct((tiles, W_WIN), jnp.int32),    # count
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # sum
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # min
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # max
    ]
    row_spec = pl.BlockSpec((R_TILE,), lambda t: (t,))
    win_spec = pl.BlockSpec((1, W_WIN), lambda t: (t, 0))
    base_spec = pl.BlockSpec((1, 1), lambda t: (t, 0))
    cnt, s, mn, mx = pl.pallas_call(
        _kernel,
        grid=(tiles,),
        in_specs=[base_spec, row_spec, row_spec, row_spec],
        out_specs=[win_spec, win_spec, win_spec, win_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(bases.reshape(-1, 1), values, valid.astype(jnp.int8), seg_ids)

    # fold tile windows into global segments: tiny combine, plain XLA.
    # Window slots past num_segments-1 clip onto the last segment carrying
    # only identity values (count/sum 0, min/max extrema) — harmless.
    gids = (bases[:, None] + jnp.arange(W_WIN, dtype=jnp.int32)[None, :])
    gids = jnp.clip(gids.reshape(-1), 0, num_segments - 1)
    out = {
        "count": jax.ops.segment_sum(cnt.reshape(-1), gids, num_segments),
        "sum": jax.ops.segment_sum(s.reshape(-1), gids, num_segments),
        "min": jax.ops.segment_min(mn.reshape(-1), gids, num_segments),
        "max": jax.ops.segment_max(mx.reshape(-1), gids, num_segments),
    }
    return out


def applicable(seg_ids: np.ndarray) -> np.ndarray | None:
    """Per-tile window bases when every tile's segment span fits W_WIN;
    None → caller uses the XLA kernel. Vectorized host check."""
    n = len(seg_ids)
    if n == 0:
        return None
    pad = (-n) % R_TILE
    s = np.pad(seg_ids, (0, pad), mode="edge").reshape(-1, R_TILE)
    lo = s.min(axis=1)
    hi = s.max(axis=1)
    if int((hi - lo).max()) >= W_WIN:
        return None
    return lo.astype(np.int32)


_WANT_OF = {"count": "want_count", "sum": "want_sum",
            "min": "want_min", "max": "want_max"}

_engagements = 0


def note_engaged() -> None:
    global _engagements
    _engagements += 1
    from ..utils import stages

    stages.count("pallas_engagements")


def engagements() -> int:
    """How many aggregations ran through the pallas kernel this process
    (bench.py records this so BENCH_r*.json shows whether it engaged)."""
    return _engagements


def segment_partials_pallas(values: np.ndarray, valid: np.ndarray,
                            seg_ids: np.ndarray, num_segments: int,
                            wants: dict | None = None,
                            interpret: bool = False) -> dict | None:
    """Host wrapper: pad to a tile multiple, run the kernel, fold windows
    into global segments. Returns None when the layout disqualifies
    (`applicable`), when pallas is unavailable, or when `wants` asks for
    first/last (rank selection stays on the XLA kernel). Output follows
    the XLA kernel's conventions: empty segments carry count 0, sum 0 and
    dtype-extrema min/max sentinels; `wants` (same keys as
    local_segment_partials) subsets the returned aggregates."""
    if not PALLAS_AVAILABLE:
        return None
    if wants and (wants.get("want_first") or wants.get("want_last")):
        return None
    seg_ids = np.asarray(seg_ids)
    bases = applicable(seg_ids)
    if bases is None:
        return None
    n = len(values)
    pad = (-n) % R_TILE
    if pad:
        values = np.concatenate([values, np.zeros(pad, values.dtype)])
        valid = np.concatenate([valid, np.zeros(pad, bool)])
        seg_ids = np.concatenate(
            [seg_ids, np.full(pad, seg_ids[-1], seg_ids.dtype)])
    out = _windowed_partials(
        jnp.asarray(bases), jnp.asarray(values), jnp.asarray(valid),
        jnp.asarray(seg_ids, dtype=jnp.int32),
        num_segments=num_segments, interpret=interpret)
    host = {k: np.asarray(v) for k, v in out.items()}  # lint: disable=host-sync (audited transfer point: one batched pull per pallas window call)
    if wants is not None:
        host = {k: v for k, v in host.items() if wants.get(_WANT_OF[k])}
    return host
