"""Pallas TPU kernel for the segment-aggregate hot op.

The framework's hottest program is the masked segment reduction behind
scan-fused GROUP BY (ops/kernels.local_segment_partials). XLA lowers
`segment_sum` through sort/scatter; this kernel exploits the STORAGE
LAYOUT instead: scan batches are series-contiguous and time-ordered, so
the `group × n_buckets + bucket` segment ids each row tile touches span a
narrow contiguous window. Every grid step reduces its row tile into a
LOCAL window of `W` segments relative to a per-tile base (one VPU-masked
pass over an [R, W] broadcast — VMEM-resident, no scatter), writing an
independent [W] output block per tile; a final O(tiles·W) XLA
segment-sum/min/max folds the windows into the global segment array
(tiles·W ≪ rows, so the combine is noise).

Preconditions checked by the host wrapper (`applicable`): every R-row
tile's segment span fits in W. Storage scans guarantee this by
construction except at series boundaries, which the window absorbs; the
wrapper falls back to the XLA kernel otherwise — same contract as
ops/placement choosing between device and host.

Run `CNOSDB_TPU_PALLAS=1` to enable on the device path; tests drive the
kernel in interpreter mode on CPU (guide: pallas_call(interpret=True)).
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

try:  # pallas import is deferred-fail: CPU-only deployments keep working
    from jax.experimental import pallas as pl
    PALLAS_AVAILABLE = True
except Exception:  # pragma: no cover
    PALLAS_AVAILABLE = False

R_TILE = 256     # rows per grid step
W_WIN = 2048     # local segment window (8 × 128-lane groups)


def _kernel(base_ref, values_ref, valid_ref, seg_ref,
            cnt_ref, sum_ref, min_ref, max_ref):
    """One row tile → [W] partials relative to this tile's window base."""
    base = base_ref[0, 0]
    vals = values_ref[:]                        # [R] f64
    ok = valid_ref[:]                           # [R] int8 validity
    seg = seg_ref[:] - base                     # [R] i32, in [0, W)
    # [R, W] membership mask: row r contributes to window slot seg[r]
    lanes = jax.lax.broadcasted_iota(jnp.int32, (R_TILE, W_WIN), 1)
    m = (seg[:, None] == lanes) & (ok[:, None] != 0)
    vcol = vals[:, None]
    zero = jnp.zeros((), vals.dtype)
    cnt_ref[0, :] = jnp.sum(m.astype(jnp.int32), axis=0)
    sum_ref[0, :] = jnp.sum(jnp.where(m, vcol, zero), axis=0)
    pinf = jnp.array(jnp.inf, vals.dtype)
    min_ref[0, :] = jnp.min(jnp.where(m, vcol, pinf), axis=0)
    max_ref[0, :] = jnp.max(jnp.where(m, vcol, -pinf), axis=0)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def _windowed_partials(bases, values, valid, seg_ids, *, num_segments: int,
                       interpret: bool = False):
    """values/valid/seg_ids padded to a tile multiple; bases[t] = window
    base of tile t (padded rows carry valid=False, seg=base)."""
    n = values.shape[0]
    tiles = n // R_TILE
    out_shape = [
        jax.ShapeDtypeStruct((tiles, W_WIN), jnp.int32),    # count
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # sum
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # min
        jax.ShapeDtypeStruct((tiles, W_WIN), values.dtype),  # max
    ]
    row_spec = pl.BlockSpec((R_TILE,), lambda t: (t,))
    win_spec = pl.BlockSpec((1, W_WIN), lambda t: (t, 0))
    base_spec = pl.BlockSpec((1, 1), lambda t: (t, 0))
    cnt, s, mn, mx = pl.pallas_call(
        _kernel,
        grid=(tiles,),
        in_specs=[base_spec, row_spec, row_spec, row_spec],
        out_specs=[win_spec, win_spec, win_spec, win_spec],
        out_shape=out_shape,
        interpret=interpret,
    )(bases.reshape(-1, 1), values, valid.astype(jnp.int8), seg_ids)

    # fold tile windows into global segments: tiny combine, plain XLA
    gids = (bases[:, None] + jnp.arange(W_WIN, dtype=jnp.int32)[None, :])
    gids = jnp.clip(gids.reshape(-1), 0, num_segments - 1)
    out = {
        "count": jax.ops.segment_sum(cnt.reshape(-1), gids, num_segments),
        "sum": jax.ops.segment_sum(s.reshape(-1), gids, num_segments),
        "min": jax.ops.segment_min(mn.reshape(-1), gids, num_segments),
        "max": jax.ops.segment_max(mx.reshape(-1), gids, num_segments),
    }
    return out


def applicable(seg_ids: np.ndarray) -> np.ndarray | None:
    """Per-tile window bases when every tile's segment span fits W_WIN;
    None → caller uses the XLA kernel. Vectorized host check."""
    n = len(seg_ids)
    if n == 0:
        return None
    pad = (-n) % R_TILE
    s = np.pad(seg_ids, (0, pad), mode="edge").reshape(-1, R_TILE)
    lo = s.min(axis=1)
    hi = s.max(axis=1)
    if int((hi - lo).max()) >= W_WIN:
        return None
    return lo.astype(np.int32)


def segment_partials_pallas(values: np.ndarray, valid: np.ndarray,
                            seg_ids: np.ndarray, num_segments: int,
                            interpret: bool = False) -> dict | None:
    """Host wrapper: pad to tile multiple, run the kernel, slice invalid
    window slots out via the combine. None when the layout disqualifies."""
    if not PALLAS_AVAILABLE:
        return None
    bases = applicable(np.asarray(seg_ids))
    if bases is None:
        return None
    n = len(values)
    pad = (-n) % R_TILE
    if pad:
        values = np.concatenate([values, np.zeros(pad, values.dtype)])
        valid = np.concatenate([valid, np.zeros(pad, bool)])
        seg_ids = np.concatenate(
            [seg_ids, np.full(pad, seg_ids[-1], seg_ids.dtype)])
    out = _windowed_partials(
        jnp.asarray(bases), jnp.asarray(values), jnp.asarray(valid),
        jnp.asarray(seg_ids, dtype=jnp.int32),
        num_segments=num_segments, interpret=interpret)
    host = {k: np.asarray(v) for k, v in out.items()}
    # empty segments: min/max carry ±inf from the identity — mirror the
    # XLA kernel's convention (callers mask by count)
    return host
