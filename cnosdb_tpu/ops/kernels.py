"""Core device kernels: masked segment aggregation.

This replaces the reference's per-series CPU reader tree + DataFusion
AggregateExec (tskv/src/reader/iterator.rs:94-121, pushdown_agg_reader.rs)
with ONE fused XLA program: every (row → segment) mapping — segment =
group_id × n_buckets + time_bucket — feeds masked segment reductions for
count/sum/min/max and rank-argmin/argmax selections for first/last.

TPU-first choices:
- No int64 timestamps on device: the host precomputes `bucket` (i32) and a
  globally unique time-order `rank` (i32) per row; first/last become
  segment-argmin/argmax over rank. This keeps the hot path free of i64
  emulation and halves PCIe traffic vs shipping raw ns timestamps.
- Static shapes: rows and segment counts are padded to size classes
  (pad_rows/pad_segments) so jit caches a handful of programs, not one per
  query.
- All aggregates in one jit: XLA fuses the mask/select/scatter pipeline
  over a single pass of the data.

`local_segment_partials` is the single implementation of the reduction
body; the single-device jit here and the shard_map body in
parallel/distributed_agg.py both call it.
"""
from __future__ import annotations

import functools

import numpy as np

# importing this module first executes the ops package __init__, which
# enables x64 before jax is used
import jax
import jax.numpy as jnp

I32_MAX = np.int32(2**31 - 1)
I32_MIN = np.int32(-(2**31) + 1)


def pad_rows(n: int, minimum: int = 1024) -> int:
    """Next power-of-two size class."""
    m = minimum
    while m < n:
        m <<= 1
    return m


def pad_segments(n: int, minimum: int = 64) -> int:
    m = minimum
    while m < n:
        m <<= 1
    return m


def type_extrema(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype), jnp.array(-jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max, dtype), jnp.array(info.min, dtype)


def local_segment_partials(values, valid, seg_ids, rank, *, num_segments: int,
                           want_count=True, want_sum=True, want_min=True,
                           want_max=True, want_first=False, want_last=False):
    """Masked segment reductions for one column (trace-time body, shared by
    the local jit and the distributed shard_map program).

    values [N], valid [N] bool, seg_ids [N] i32 (padded/filtered rows carry
    seg 0 with valid=False), rank [N] i32 globally-unique time order.
    → dict of [num_segments] arrays (plus first_rank/last_rank carrying the
    selection keys for cross-shard combination).
    """
    out = {}
    vmax, vmin = type_extrema(values.dtype)
    zero = jnp.zeros((), values.dtype)
    if want_count:
        # i32 on device (64-bit int ops are emulated on TPU); a batch is
        # bounded well below 2^31 rows, host wrappers upcast to i64
        out["count"] = jax.ops.segment_sum(
            valid.astype(jnp.int32), seg_ids, num_segments)
    if want_sum:
        out["sum"] = jax.ops.segment_sum(
            jnp.where(valid, values, zero), seg_ids, num_segments)
    if want_min:
        out["min"] = jax.ops.segment_min(
            jnp.where(valid, values, vmax), seg_ids, num_segments)
    if want_max:
        out["max"] = jax.ops.segment_max(
            jnp.where(valid, values, vmin), seg_ids, num_segments)
    if want_first:
        key = jnp.where(valid, rank, I32_MAX)
        rmin = jax.ops.segment_min(key, seg_ids, num_segments)
        sel = valid & (rank == rmin[seg_ids])
        out["first"] = jax.ops.segment_sum(
            jnp.where(sel, values, zero), seg_ids, num_segments)
        out["first_rank"] = rmin
    if want_last:
        key = jnp.where(valid, rank, I32_MIN)
        rmax = jax.ops.segment_max(key, seg_ids, num_segments)
        sel = valid & (rank == rmax[seg_ids])
        out["last"] = jax.ops.segment_sum(
            jnp.where(sel, values, zero), seg_ids, num_segments)
        out["last_rank"] = rmax
    return out


segment_aggregate = jax.jit(
    local_segment_partials,
    static_argnames=("num_segments", "want_count", "want_sum", "want_min",
                     "want_max", "want_first", "want_last"))


def numpy_segment_partials(values: np.ndarray, valid: np.ndarray,
                           seg_ids: np.ndarray, rank: np.ndarray,
                           num_segments: int, wants: dict,
                           assume_all_valid: bool = False) -> dict:
    """Pure-numpy segment reductions — the CPU-placement twin of the XLA
    kernel. On one core, bincount/ufunc.at beat XLA's scatter lowering by
    ~2×, and no padding copies are needed; the device path remains the
    jitted kernel (placement decides, ops/placement.py)."""
    if not assume_all_valid and not valid.all():
        rows = np.nonzero(valid)[0]
        values = values[rows]
        seg_ids = seg_ids[rows]
        rank = rank[rows]
    out: dict[str, np.ndarray] = {}
    ns = num_segments
    if wants.get("want_count"):
        out["count"] = np.bincount(seg_ids, minlength=ns).astype(np.int64)
    integral = values.dtype.kind in "iu"
    if wants.get("want_sum"):
        if integral:
            # bincount sums in f64 and would round past 2^53; add.at is
            # slower but exact in the column's own integer arithmetic
            acc = np.zeros(ns, dtype=values.dtype)
            np.add.at(acc, seg_ids, values)
            out["sum"] = acc
        else:
            out["sum"] = np.bincount(seg_ids, weights=values, minlength=ns)
    if wants.get("want_min"):
        init = (np.iinfo(values.dtype).max if integral
                else np.asarray(np.inf, values.dtype))
        acc = np.full(ns, init, dtype=values.dtype)
        np.minimum.at(acc, seg_ids, values)
        out["min"] = acc
    if wants.get("want_max"):
        init = (np.iinfo(values.dtype).min if integral
                else np.asarray(-np.inf, values.dtype))
        acc = np.full(ns, init, dtype=values.dtype)
        np.maximum.at(acc, seg_ids, values)
        out["max"] = acc
    if wants.get("want_first") or wants.get("want_last"):
        sel_rank = {}
        if wants.get("want_first"):
            acc = np.full(ns, I32_MAX, dtype=rank.dtype)
            np.minimum.at(acc, seg_ids, rank)
            sel_rank["first"] = acc
        if wants.get("want_last"):
            acc = np.full(ns, I32_MIN, dtype=rank.dtype)
            np.maximum.at(acc, seg_ids, rank)
            sel_rank["last"] = acc
        for name, acc in sel_rank.items():
            pick = rank == acc[seg_ids]
            vals_out = np.zeros(ns, dtype=values.dtype)
            vals_out[seg_ids[pick]] = values[pick]
            out[name] = vals_out
            out[f"{name}_rank"] = acc
    return out


def aggregate_column_host(values: np.ndarray, valid: np.ndarray,
                          seg_ids: np.ndarray, rank: np.ndarray,
                          num_segments: int, wants: dict) -> dict:
    """Host wrapper: pads rows to a size class, runs the jit kernel, pulls
    results back as numpy (sliced to num_segments by the caller)."""
    n = len(values)
    np_pad = pad_rows(max(n, 1))
    ns_pad = pad_segments(max(num_segments, 1))
    if np_pad != n:
        values = _pad(values, np_pad)
        valid = _pad(valid, np_pad, fill=False)
        seg_ids = _pad(seg_ids, np_pad, fill=0)
        rank = _pad(rank, np_pad, fill=0)
    out = segment_aggregate(values, valid, seg_ids, rank,
                            num_segments=ns_pad, **wants)
    host = {k: np.asarray(v)[:num_segments] for k, v in out.items()}
    if "count" in host:
        host["count"] = host["count"].astype(np.int64)
    return host


def _pad(a: np.ndarray, n: int, fill=0):
    out = np.full(n, fill, dtype=a.dtype)
    out[:len(a)] = a
    return out
