"""Core device kernels: masked segment aggregation.

This replaces the reference's per-series CPU reader tree + DataFusion
AggregateExec (tskv/src/reader/iterator.rs:94-121, pushdown_agg_reader.rs)
with ONE fused XLA program: every (row → segment) mapping — segment =
group_id × n_buckets + time_bucket — feeds masked segment reductions for
count/sum/min/max and rank-argmin/argmax selections for first/last.

TPU-first choices:
- No int64 timestamps on device: the host precomputes `bucket` (i32) and a
  globally unique time-order `rank` (i32) per row; first/last become
  segment-argmin/argmax over rank. This keeps the hot path free of i64
  emulation and halves PCIe traffic vs shipping raw ns timestamps.
- Static shapes: rows and segment counts are padded to size classes
  (pad_rows/pad_segments) so jit caches a handful of programs, not one per
  query.
- All aggregates in one jit: XLA fuses the mask/select/scatter pipeline
  over a single pass of the data.

`local_segment_partials` is the single implementation of the reduction
body; the single-device jit here and the shard_map body in
parallel/distributed_agg.py both call it.
"""
from __future__ import annotations

import functools

import numpy as np

# importing this module first executes the ops package __init__, which
# enables x64 before jax is used
import jax
import jax.numpy as jnp

I32_MAX = np.int32(2**31 - 1)
I32_MIN = np.int32(-(2**31) + 1)


def pad_rows(n: int, minimum: int = 1024) -> int:
    """Next power-of-two size class."""
    m = minimum
    while m < n:
        m <<= 1
    return m


def pad_segments(n: int, minimum: int = 64) -> int:
    m = minimum
    while m < n:
        m <<= 1
    return m


def type_extrema(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(jnp.inf, dtype), jnp.array(-jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.max, dtype), jnp.array(info.min, dtype)


def local_segment_partials(values, valid, seg_ids, rank, *, num_segments: int,
                           want_count=True, want_sum=True, want_min=True,
                           want_max=True, want_first=False, want_last=False):
    """Masked segment reductions for one column (trace-time body, shared by
    the local jit and the distributed shard_map program).

    values [N], valid [N] bool, seg_ids [N] i32 (padded/filtered rows carry
    seg 0 with valid=False), rank [N] i32 globally-unique time order.
    → dict of [num_segments] arrays (plus first_rank/last_rank carrying the
    selection keys for cross-shard combination).
    """
    out = {}
    vmax, vmin = type_extrema(values.dtype)
    zero = jnp.zeros((), values.dtype)
    if want_count:
        # i32 on device (64-bit int ops are emulated on TPU); a batch is
        # bounded well below 2^31 rows, host wrappers upcast to i64
        out["count"] = jax.ops.segment_sum(
            valid.astype(jnp.int32), seg_ids, num_segments)
    if want_sum:
        out["sum"] = jax.ops.segment_sum(
            jnp.where(valid, values, zero), seg_ids, num_segments)
    if want_min:
        out["min"] = jax.ops.segment_min(
            jnp.where(valid, values, vmax), seg_ids, num_segments)
    if want_max:
        out["max"] = jax.ops.segment_max(
            jnp.where(valid, values, vmin), seg_ids, num_segments)
    if want_first:
        key = jnp.where(valid, rank, I32_MAX)
        rmin = jax.ops.segment_min(key, seg_ids, num_segments)
        sel = valid & (rank == rmin[seg_ids])
        out["first"] = jax.ops.segment_sum(
            jnp.where(sel, values, zero), seg_ids, num_segments)
        out["first_rank"] = rmin
    if want_last:
        key = jnp.where(valid, rank, I32_MIN)
        rmax = jax.ops.segment_max(key, seg_ids, num_segments)
        sel = valid & (rank == rmax[seg_ids])
        out["last"] = jax.ops.segment_sum(
            jnp.where(sel, values, zero), seg_ids, num_segments)
        out["last_rank"] = rmax
    return out


segment_aggregate = jax.jit(
    local_segment_partials,
    static_argnames=("num_segments", "want_count", "want_sum", "want_min",
                     "want_max", "want_first", "want_last"))


def numpy_segment_partials(values: np.ndarray, valid: np.ndarray,
                           seg_ids: np.ndarray, rank: np.ndarray,
                           num_segments: int, wants: dict,
                           assume_all_valid: bool = False) -> dict:
    """Pure-numpy segment reductions — the CPU-placement twin of the XLA
    kernel. On one core, bincount/ufunc.at beat XLA's scatter lowering by
    ~2×, and no padding copies are needed; the device path remains the
    jitted kernel (placement decides, ops/placement.py)."""
    if not assume_all_valid and not valid.all():
        rows = np.nonzero(valid)[0]
        values = values[rows]
        seg_ids = seg_ids[rows]
        rank = rank[rows]
    out: dict[str, np.ndarray] = {}
    ns = num_segments
    if wants.get("want_count"):
        out["count"] = np.bincount(seg_ids, minlength=ns).astype(np.int64)
    integral = values.dtype.kind in "iu"
    if wants.get("want_sum"):
        if integral:
            # bincount sums in f64 and would round past 2^53; add.at is
            # slower but exact in the column's own integer arithmetic
            acc = np.zeros(ns, dtype=values.dtype)
            np.add.at(acc, seg_ids, values)
            out["sum"] = acc
        else:
            out["sum"] = np.bincount(seg_ids, weights=values, minlength=ns)
    if wants.get("want_min"):
        init = (np.iinfo(values.dtype).max if integral
                else np.asarray(np.inf, values.dtype))
        acc = np.full(ns, init, dtype=values.dtype)
        np.minimum.at(acc, seg_ids, values)
        out["min"] = acc
    if wants.get("want_max"):
        init = (np.iinfo(values.dtype).min if integral
                else np.asarray(-np.inf, values.dtype))
        acc = np.full(ns, init, dtype=values.dtype)
        np.maximum.at(acc, seg_ids, values)
        out["max"] = acc
    if wants.get("want_first") or wants.get("want_last"):
        sel_rank = {}
        if wants.get("want_first"):
            acc = np.full(ns, I32_MAX, dtype=rank.dtype)
            np.minimum.at(acc, seg_ids, rank)
            sel_rank["first"] = acc
        if wants.get("want_last"):
            acc = np.full(ns, I32_MIN, dtype=rank.dtype)
            np.maximum.at(acc, seg_ids, rank)
            sel_rank["last"] = acc
        for name, acc in sel_rank.items():
            pick = rank == acc[seg_ids]
            vals_out = np.zeros(ns, dtype=values.dtype)
            vals_out[seg_ids[pick]] = values[pick]
            out[name] = vals_out
            out[f"{name}_rank"] = acc
    return out


def run_boundaries(seg_ids: np.ndarray,
                   sid_ordinal: np.ndarray | None = None) -> np.ndarray:
    """Start indices of equal-segment runs (splitting additionally at
    series boundaries when sid_ordinal is given — first/last need time
    order WITHIN every run, which only holds per series).

    Correct for arbitrary seg arrays — a segment recurring in many runs
    just contributes several partials; the caller combines them. Fast
    when segments are contiguous, which the storage layout guarantees:
    scan batches are series-contiguous and time-ordered per series, so
    group×bucket segment ids form runs."""
    n = len(seg_ids)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    ch = np.diff(seg_ids) != 0
    if sid_ordinal is not None:
        ch = ch | (np.diff(sid_ordinal) != 0)
    return np.concatenate(([0], np.flatnonzero(ch) + 1)).astype(np.int64)


_I64_MAX = np.int64(np.iinfo(np.int64).max)
_I64_MIN = np.int64(np.iinfo(np.int64).min)


def run_segment_partials(values: np.ndarray, seg_ids: np.ndarray,
                         starts: np.ndarray, num_segments: int, wants: dict,
                         ts: np.ndarray | None = None,
                         run_counts: np.ndarray | None = None) -> dict:
    """Segment reductions over contiguous equal-segment runs.

    The storage-layout-aware twin of numpy_segment_partials: sequential
    ufunc.reduceat over runs replaces scatter bincount/ufunc.at (5-8×
    faster on one core at bench scale), then tiny per-run combines fold
    runs into segments. ALL rows are assumed valid — callers compress
    invalid rows out first (compression preserves run structure).

    first/last require `ts` (row timestamps, time-ordered within each
    run) and return companion 'first_ts'/'last_ts' arrays — actual
    timestamps, which coordinators can merge across vnodes directly.
    Tie-breaking matches the rank kernels: earliest row position wins
    `first`, latest wins `last`."""
    out: dict[str, np.ndarray] = {}
    ns = num_segments
    n = len(values)
    if n == 0:
        starts = starts[:0]
    run_seg = seg_ids[starts] if n else np.zeros(0, dtype=np.int64)
    if run_counts is None:
        run_counts = np.diff(np.append(starts, n))
    if wants.get("want_count"):
        out["count"] = np.bincount(
            run_seg, weights=run_counts, minlength=ns).astype(np.int64)
    integral = values.dtype.kind in "iu"
    if wants.get("want_sum"):
        part = np.add.reduceat(values, starts) if n else values[:0]
        if integral:
            # bincount sums in f64 and would round past 2^53; add.at over
            # the (few) runs is exact in the column's own arithmetic
            acc = np.zeros(ns, dtype=values.dtype)
            np.add.at(acc, run_seg, part)
            out["sum"] = acc
        else:
            out["sum"] = np.bincount(run_seg, weights=part, minlength=ns)
    if wants.get("want_min"):
        init = (np.iinfo(values.dtype).max if integral
                else np.asarray(np.inf, values.dtype))
        part = np.minimum.reduceat(values, starts) if n else values[:0]
        acc = np.full(ns, init, dtype=values.dtype)
        np.minimum.at(acc, run_seg, part)
        out["min"] = acc
    if wants.get("want_max"):
        init = (np.iinfo(values.dtype).min if integral
                else np.asarray(-np.inf, values.dtype))
        part = np.maximum.reduceat(values, starts) if n else values[:0]
        acc = np.full(ns, init, dtype=values.dtype)
        np.maximum.at(acc, run_seg, part)
        out["max"] = acc
    if wants.get("want_first"):
        ft = ts[starts] if n else np.zeros(0, dtype=np.int64)
        acc_t = np.full(ns, _I64_MAX, dtype=np.int64)
        np.minimum.at(acc_t, run_seg, ft)
        pick = np.flatnonzero(ft == acc_t[run_seg])
        fvals = np.zeros(ns, dtype=values.dtype)
        # reversed assignment: among ties the EARLIEST run wins (stable
        # time-sort semantics of the rank kernel)
        fvals[run_seg[pick][::-1]] = values[starts][pick][::-1]
        out["first"] = fvals
        out["first_ts"] = acc_t
    if wants.get("want_last"):
        ends = (np.append(starts[1:], n) - 1) if n \
            else np.zeros(0, dtype=np.int64)
        lt = ts[ends] if n else np.zeros(0, dtype=np.int64)
        acc_t = np.full(ns, _I64_MIN, dtype=np.int64)
        np.maximum.at(acc_t, run_seg, lt)
        pick = np.flatnonzero(lt == acc_t[run_seg])
        lvals = np.zeros(ns, dtype=values.dtype)
        lvals[run_seg[pick]] = values[ends][pick]   # latest tied run wins
        out["last"] = lvals
        out["last_ts"] = acc_t
    return out


def aggregate_column_host(values: np.ndarray, valid: np.ndarray,
                          seg_ids: np.ndarray, rank: np.ndarray,
                          num_segments: int, wants: dict) -> dict:
    """Host wrapper: pads rows to a size class, runs the jit kernel, pulls
    results back as numpy (sliced to num_segments by the caller).

    When the pallas segment kernel is enabled (ops/pallas_kernels.enabled:
    CNOSDB_TPU_PALLAS=1 or a real TPU scan device) and the batch's segment
    layout qualifies, the storage-layout-aware windowed kernel replaces
    XLA's sort/scatter segment lowering; first/last (rank selection) and
    disqualified layouts fall back to the XLA kernel below."""
    n = len(values)
    np_pad = pad_rows(max(n, 1))
    ns_pad = pad_segments(max(num_segments, 1))
    from . import pallas_kernels as pk

    if pk.enabled() and not (wants.get("want_first")
                             or wants.get("want_last")) and n \
            and pk.applicable(seg_ids) is not None:
        # cheap O(n/R_TILE) layout check BEFORE any padding copies —
        # disqualified layouts fall straight through to the XLA path.
        # Pad seg with the edge value (not 0) so trailing tiles keep
        # their narrow window; padded rows are valid=False either way
        v2 = _pad(values, np_pad)
        ok2 = _pad(valid, np_pad, fill=False)
        sg2 = _pad(seg_ids, np_pad, fill=seg_ids[n - 1])
        out = pk.segment_partials_pallas(
            v2, ok2, sg2.astype(np.int32, copy=False), ns_pad, wants=wants,
            interpret=jax.default_backend() != "tpu")
        if out is not None:
            pk.note_engaged()
            host = {k: v[:num_segments] for k, v in out.items()}
            if "count" in host:
                host["count"] = host["count"].astype(np.int64)
            return host
    if np_pad != n:
        values = _pad(values, np_pad)
        valid = _pad(valid, np_pad, fill=False)
        seg_ids = _pad(seg_ids, np_pad, fill=0)
        rank = _pad(rank, np_pad, fill=0)
    out = segment_aggregate(values, valid, seg_ids, rank,
                            num_segments=ns_pad, **wants)
    host = {k: np.asarray(v)[:num_segments] for k, v in out.items()}  # lint: disable=host-sync (THE audited transfer point: one batched pull per aggregate call)
    if "count" in host:
        host["count"] = host["count"].astype(np.int64)
    return host


def _pad(a: np.ndarray, n: int, fill=0):
    out = np.full(n, fill, dtype=a.dtype)
    out[:len(a)] = a
    return out


# ---------------------------------------------------------------------------
# sort-based DISTINCT on device (ops/group_agg.py device path)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("num_segments",))
def _segment_distinct(pairs, nv, *, num_segments: int):
    """count(DISTINCT) from (group·nv + value) pair codes: sort, mark each
    first occurrence, segment-sum the indicators by group. Padded rows
    carry pair codes whose group lands >= num_segments, which segment_sum's
    out-of-range scatter semantics drop."""
    sp = jnp.sort(pairs)
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sp[1:] != sp[:-1]])
    seg = sp // nv
    return jax.ops.segment_sum(
        first.astype(jnp.int32), seg, num_segments)


_device_sort = jax.jit(jnp.sort)


def segment_distinct_count(gid: np.ndarray, vcodes: np.ndarray,
                           num_segments: int, n_values: int) -> np.ndarray:
    """Host wrapper for the single-chunk device DISTINCT: pads rows to a
    size class (sentinel pairs map past num_segments and are dropped),
    runs the jitted sort+boundary+segment_sum kernel, returns i64 counts."""
    n = len(gid)
    if n == 0:
        return np.zeros(num_segments, dtype=np.int64)
    nv = np.int64(max(int(n_values), 1))
    pairs = gid.astype(np.int64) * nv + vcodes.astype(np.int64)
    np_pad = pad_rows(n)
    ns_pad = pad_segments(max(num_segments, 1))
    if np_pad != n:
        pairs = _pad(pairs, np_pad, fill=np.int64(ns_pad) * nv)
    out = _segment_distinct(pairs, nv, num_segments=ns_pad)
    return np.asarray(out)[:num_segments].astype(np.int64)  # lint: disable=host-sync (audited transfer point: the i64 counts are the host result)


def sorted_pair_codes(gid: np.ndarray, vcodes: np.ndarray,
                      n_values: int) -> np.ndarray:
    """One chunk's DISTINCT partial: device-sorted unique (group, value)
    pair codes. Sentinel-padded rows sort to the tail and are sliced off;
    the dedup of the sorted run happens host-side so the partial is the
    plain sorted pair array parallel.distributed_agg.merge_distinct_pairs
    expects on the wire."""
    n = len(gid)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    nv = np.int64(max(int(n_values), 1))
    pairs = gid.astype(np.int64) * nv + vcodes.astype(np.int64)
    np_pad = pad_rows(n)
    if np_pad != n:
        pairs = _pad(pairs, np_pad, fill=np.iinfo(np.int64).max)
    sp = np.asarray(_device_sort(pairs))[:n]  # lint: disable=host-sync (audited transfer point: the sorted partial IS the on-wire format)
    keep = np.concatenate(([True], sp[1:] != sp[:-1]))
    return sp[keep]


@functools.partial(jax.jit, static_argnames=("k",))
def _topk_threshold(vals, *, k: int):
    top, _ = jax.lax.top_k(vals, k)
    return top[k - 1]


def dict_mask_gather(mask: np.ndarray, codes):
    """Per-unique predicate mask → row mask on device: one integer gather
    through the dictionary codes (the strkernels broadcast for codes that
    already live on the accelerator via EagerUploader.put_device)."""
    return _dict_mask_gather(jnp.asarray(mask), codes)


_dict_mask_gather = jax.jit(lambda mask, codes: jnp.take(mask, codes, axis=0,
                                                         mode="clip"))


def topk_threshold(vals: np.ndarray, k: int):
    """k-th largest value of `vals` (descending top-K threshold) via
    jax.lax.top_k; only this scalar crosses back to host. Rows are padded
    to a size class with the dtype minimum so jit caches a handful of
    programs; caller guarantees 0 < k < len(vals) and no NaNs."""
    n = len(vals)
    np_pad = pad_rows(n)
    if np_pad != n:
        if vals.dtype.kind == "f":
            fill = vals.dtype.type(-np.inf)
        else:
            fill = np.iinfo(vals.dtype).min
        vals = _pad(vals, np_pad, fill=fill)
    return np.asarray(_topk_threshold(vals, k=int(k)))  # lint: disable=host-sync (audited transfer point: only this scalar crosses back)
