"""Layered configuration.

Role-parity with the reference's config crate (config/src/tskv/mod.rs:37-120
Figment TOML + CNOSDB_ env overrides; `cnosdb config` prints defaults,
`cnosdb check` validates): TOML file → env (`CNOSDB_SECTION_KEY`) → CLI
flags, with typed sections global/deployment/query/storage/wal/cache/
log/service/cluster.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field, fields as dc_fields

from .errors import ConfigError

try:
    import tomllib
except ImportError:  # Python < 3.11: minimal flat-TOML fallback, enough
    # for the [section] / key = scalar shape this module itself emits
    class tomllib:  # type: ignore[no-redef]
        class TOMLDecodeError(ValueError):
            pass

        @staticmethod
        def load(f):
            data: dict = {}
            section = None
            for lineno, raw in enumerate(
                    f.read().decode("utf-8").splitlines(), 1):
                line = raw.split("#", 1)[0].strip() \
                    if not raw.strip().startswith('"') else raw.strip()
                if not line:
                    continue
                if line.startswith("[") and line.endswith("]"):
                    section = data.setdefault(line[1:-1].strip(), {})
                    continue
                if "=" not in line or section is None:
                    raise tomllib.TOMLDecodeError(
                        f"line {lineno}: {raw!r}")
                k, _, v = line.partition("=")
                v = v.strip()
                if len(v) >= 2 and v[0] == '"' and v[-1] == '"':
                    val: object = v[1:-1]
                elif v in ("true", "false"):
                    val = v == "true"
                else:
                    try:
                        val = int(v)
                    except ValueError:
                        try:
                            val = float(v)
                        except ValueError:
                            raise tomllib.TOMLDecodeError(
                                f"line {lineno}: bad value {v!r}")
                section[k.strip()] = val
            return data


@dataclass
class GlobalConfig:
    node_id: int = 1
    host: str = "localhost"
    cluster_name: str = "cluster_xxx"
    store_metrics: bool = True


@dataclass
class DeploymentConfig:
    mode: str = "singleton"       # singleton | query_tskv | tskv | query
    cpu: int = 0                  # 0 = auto
    memory: int = 0


@dataclass
class QueryConfig:
    max_server_connections: int = 10240
    query_sql_limit: int = 16 * 1024 * 1024
    write_sql_limit: int = 160 * 1024 * 1024
    auth_enabled: bool = False
    # default request deadlines (overridable per request via the
    # X-CnosDB-Deadline-Ms header); the reference shipped 3_000_000 ms
    # (50 min) which in practice meant "no deadline" — 30 s read / 10 s
    # write keeps one slow replica from absorbing a node
    read_timeout_ms: int = 30_000
    write_timeout_ms: int = 10_000
    # per-node admission gate (server/admission.py): queries running at
    # once, and how many may wait in line before the node sheds with 503
    max_concurrent_queries: int = 64
    max_queued_queries: int = 128
    # shared scan/decode pool widths (utils/executor.py); 0 = auto
    scan_executor_threads: int = 0
    decode_executor_threads: int = 0
    # slow-query log: queries whose wall time meets/exceeds this
    # threshold are recorded (trace id + stage profile) into
    # usage_schema.slow_queries. 0 (the default) disables the log.
    # Env override: CNOSDB_QUERY_SLOW_QUERY_THRESHOLD_MS.
    slow_query_threshold_ms: int = 0
    # gray-failure tolerance plane (parallel/health.py): floor on the
    # adaptive per-(node, method-class) p95 hedge trigger — a warm-cache
    # microsecond p95 must not hedge every scan — and the per-coordinator
    # cap on concurrently in-flight hedges (hedges add load exactly when
    # the cluster is slow). CNOSDB_HEDGE=0 disables hedging entirely.
    # Env overrides: CNOSDB_QUERY_HEDGE_DELAY_MS_FLOOR /
    # CNOSDB_QUERY_HEDGE_MAX_INFLIGHT.
    hedge_delay_ms_floor: int = 25
    hedge_max_inflight: int = 8
    # memory-governance plane (server/memory.py): total process budget
    # arbitrated across the registered pools (0 = auto: a quarter of
    # physical RAM, floored at 1 GiB), soft/hard watermarks as percent
    # of that budget (soft starts cache reclaim + queued-query shedding,
    # hard fails writes closed), the per-query accounting budget (0 =
    # unlimited; an over-budget query dies with MemoryExceeded / HTTP
    # 413), the group-state budget above which an aggregate spills its
    # accumulator to disk, and the bounded write-path delay spent
    # waiting for flush progress before shedding with 503.
    # CNOSDB_MEMORY=0 disables the whole plane (byte-identical legacy
    # path); env overrides: CNOSDB_QUERY_MEMORY_TOTAL_BYTES etc.
    memory_total_bytes: int = 0
    memory_soft_pct: int = 70
    memory_hard_pct: int = 90
    memory_per_query_bytes: int = 0
    memory_group_bytes: int = 64 * 1024 * 1024
    memory_write_delay_ms: int = 2000


@dataclass
class StorageConfig:
    path: str = "./cnosdb-data"
    max_summary_size: int = 128 * 1024 * 1024
    base_file_size: int = 16 * 1024 * 1024
    max_level: int = 4
    compact_trigger_file_num: int = 4
    max_compact_size: int = 2 * 1024 * 1024 * 1024
    strict_write: bool = False
    reserve_space: int = 0
    # background integrity scrubber (storage/scrub.py): seconds between
    # sweeps, 0 = off (default — tests/benchmarks must opt in); read-rate
    # cap so a sweep never starves foreground scans of disk bandwidth
    scrub_interval: int = 0
    scrub_mb_per_sec: int = 8
    # cold tiering (storage/tiering.py): object-store URI (s3:// gs://
    # az:// file://; empty = tiering off), seconds between tiering sweeps
    # (0 = no background job; tier_vnode can still be driven manually),
    # and the age past which a sealed file goes cold. The reference's
    # `[storage] ttl` expires data outright; here TTL becomes
    # tier-then-expire — see ARCHITECTURE.md "Tiered storage".
    tiering_uri: str = ""
    tiering_interval: int = 0
    tiering_cold_after_s: int = 24 * 3600
    # disaster-recovery plane (storage/backup.py): object-store URI for
    # continuous WAL archiving + BACKUP/RESTORE manifests (empty = DR
    # off). May share a bucket with tiering_uri under a different prefix;
    # cold objects are referenced by backups, never copied.
    wal_archive_uri: str = ""
    # optional store credentials/overrides for wal_archive_uri: a JSON
    # object of CONNECTION-style keys (endpoint_url, access_key_id, …).
    # String-typed so the TOML fallback parser and the env override
    # (CNOSDB_STORAGE_WAL_ARCHIVE_OPTIONS) both carry it unchanged.
    wal_archive_options: str = ""


@dataclass
class WalConfig:
    enabled: bool = True
    max_file_size: int = 64 * 1024 * 1024
    sync: bool = False


@dataclass
class CacheConfig:
    max_buffer_size: int = 128 * 1024 * 1024
    partition: int = 0
    # byte cap on the coordinator's scan-snapshot cache (sum of cached
    # ScanBatch nbytes); entry count is capped separately
    scan_cache_max_bytes: int = 1024 * 1024 * 1024


@dataclass
class LogConfig:
    level: str = "info"
    path: str = "./cnosdb-logs"


@dataclass
class ServiceConfig:
    http_listen_port: int = 8902
    grpc_listen_port: int = 8903
    flight_rpc_listen_port: int = 8904
    tcp_listen_port: int = 8905
    enable_report: bool = False


@dataclass
class SecurityConfig:
    """TLS for the user HTTP API (reference config [security] tls_config)."""

    tls_cert_path: str = ""
    tls_key_path: str = ""

    @property
    def enabled(self) -> bool:
        return bool(self.tls_cert_path and self.tls_key_path)


@dataclass
class TraceConfig:
    """Distributed-tracing sinks (reference config [trace]: minitrace →
    OTLP collector, global_tracing.rs:14-60). When `otlp_endpoint` is set
    (e.g. http://collector:4318), finished spans export as OTLP/HTTP JSON
    to {endpoint}/v1/traces in the background."""

    otlp_endpoint: str = ""
    auto_generate_span: bool = False
    batch_size: int = 256
    flush_interval_s: float = 2.0


@dataclass
class ClusterConfig:
    raft_logs_to_keep: int = 5000
    snapshot_holding_time_s: int = 3600
    heartbeat_interval_ms: int = 300
    election_timeout_ms: int = 1000


@dataclass
class Config:
    global_: GlobalConfig = field(default_factory=GlobalConfig)
    deployment: DeploymentConfig = field(default_factory=DeploymentConfig)
    query: QueryConfig = field(default_factory=QueryConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    wal: WalConfig = field(default_factory=WalConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    log: LogConfig = field(default_factory=LogConfig)
    service: ServiceConfig = field(default_factory=ServiceConfig)
    security: SecurityConfig = field(default_factory=SecurityConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)

    _SECTIONS = {
        "global": "global_", "deployment": "deployment", "query": "query",
        "storage": "storage", "wal": "wal", "cache": "cache", "log": "log",
        "service": "service", "security": "security", "cluster": "cluster",
        "trace": "trace",
    }

    @classmethod
    def load(cls, path: str | None = None, env: dict | None = None) -> "Config":
        cfg = cls()
        if path:
            try:
                with open(path, "rb") as f:
                    data = tomllib.load(f)
            except FileNotFoundError:
                raise ConfigError(f"config file not found: {path}")
            except tomllib.TOMLDecodeError as e:
                raise ConfigError(f"bad TOML in {path}: {e}")
            for section, attr in cls._SECTIONS.items():
                if section in data:
                    obj = getattr(cfg, attr)
                    for k, v in data[section].items():
                        if hasattr(obj, k):
                            setattr(obj, k, v)
                        # unknown keys warn, not fail (reference check.rs warns)
        env = env if env is not None else os.environ
        for section, attr in cls._SECTIONS.items():
            obj = getattr(cfg, attr)
            for f in dc_fields(obj):
                key = f"CNOSDB_{section.upper()}_{f.name.upper()}"
                if key in env:
                    raw = env[key]
                    t = type(getattr(obj, f.name))
                    if t is bool:
                        setattr(obj, f.name, raw.lower() in ("1", "true", "yes"))
                    elif t is int:
                        setattr(obj, f.name, int(raw))
                    else:
                        setattr(obj, f.name, raw)
        return cfg

    def to_toml(self) -> str:
        out = []
        for section, attr in self._SECTIONS.items():
            out.append(f"[{section}]")
            obj = getattr(self, attr)
            for f in dc_fields(obj):
                v = getattr(obj, f.name)
                if isinstance(v, bool):
                    out.append(f"{f.name} = {'true' if v else 'false'}")
                elif isinstance(v, (int, float)):
                    out.append(f"{f.name} = {v}")
                else:
                    out.append(f'{f.name} = "{v}"')
            out.append("")
        return "\n".join(out)

    def check(self) -> list[str]:
        warnings = []
        if self.storage.compact_trigger_file_num < 2:
            warnings.append("storage.compact_trigger_file_num < 2")
        if self.cache.max_buffer_size < 1024 * 1024:
            warnings.append("cache.max_buffer_size very small")
        if self.deployment.mode not in ("singleton", "query_tskv", "tskv", "query"):
            raise ConfigError(f"bad deployment.mode {self.deployment.mode!r}")
        return warnings
