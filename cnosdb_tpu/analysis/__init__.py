"""Unified AST invariant-analysis engine.

One walk per file, a registry of project-invariant rules, inline
suppressions, and a checked-in baseline so new rules *ratchet* (existing
debt is frozen at its current count and may only shrink) instead of
demanding a flag-day cleanup.

Why this exists: the reference CnosDB leans on rustc to enforce the
invariants a distributed TSDB lives or dies by (no swallowed panics, no
blocking under a mutex the borrow checker can see, Send/Sync). The
Python/JAX rebuild had grown three ad-hoc AST tests that each re-walked
the tree with their own conventions and covered only two directories.
This package replaces them: rules live in :mod:`.rules`, every rule
names the incident that motivated it, and the whole tree is in scope.

Two rule shapes share the registry:

* per-file rules (:class:`Rule`) see one module at a time from the
  single shared AST walk;
* interprocedural rules (:class:`ProjectRule`) run once per lint run
  over the project call graph + per-function summaries built by
  :mod:`.interproc` — params/returns tagged host, device, or
  tainted-by-device, fixed-point over a worklist — so a device array
  produced two call edges away still counts as device at the sink.

Usage:

    python -m cnosdb_tpu.analysis              # lint the package, exit 0/1
    python -m cnosdb_tpu.analysis --json       # machine-readable findings
    python -m cnosdb_tpu.analysis --fix-baseline   # re-freeze current debt
    python -m cnosdb_tpu.analysis --changed REF    # findings only for files
                                                   # touched since git REF
    python -m cnosdb_tpu.analysis --callgraph      # dump the call graph +
                                                   # summaries and exit

Suppressions: append ``# lint: disable=<rule>[,<rule>…]  (reason)`` to
the offending line (the line the finding points at — the ``with``/
``except``/call header). ``disable=all`` silences every rule for that
line. A suppression with no reason is a smell; say why it is safe.

Baseline: ``baseline.json`` maps rule → file → allowed count. A file
exceeding its allowance fails; a file *under* its allowance also fails
("stale baseline") so fixed debt is locked in by running
``--fix-baseline`` — the ratchet only turns one way.
"""
from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import tokenize

PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_PARENT = os.path.dirname(PKG_DIR)
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")
_DISABLE_MARK = "lint: disable="


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # normalized: package-relative posix path when inside
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """One invariant. Subclasses set ``name``/``motivation``, declare the
    AST node types they want via ``node_types`` (dispatched from the
    single shared walk), and/or override ``begin_module`` for whole-tree
    passes. ``applies_to`` scopes the rule to part of the package."""

    name: str = ""
    motivation: str = ""          # the incident/PR that created the rule
    node_types: tuple = ()

    def applies_to(self, relpath: str) -> bool:
        return True

    def begin_module(self, ctx: "ModuleContext") -> None:
        pass

    def visit(self, node: ast.AST, ctx: "ModuleContext") -> None:
        pass


class ProjectRule(Rule):
    """Interprocedural invariant: instead of per-node visits it gets one
    ``check(project)`` call over the whole-run call graph + summaries
    (:class:`cnosdb_tpu.analysis.interproc.Project`). ``applies_to``
    scopes where findings may be *reported*; summaries are always built
    from every file in the run so taint crosses file boundaries."""

    def check(self, project) -> None:
        raise NotImplementedError


class ModuleContext:
    """Per-file state shared by every rule during the single walk."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module, sink: list):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._sink = sink
        # --changed mode: muted files contribute call-graph summaries but
        # produce no findings
        self.muted = False
        # lines where an inline disable actually absorbed a finding this
        # run — the stale-suppression audit flags the rest
        self.suppressed_lines: set = set()

    def report(self, rule: Rule, node, message: str) -> None:
        line = node if isinstance(node, int) else node.lineno
        if self._suppressed(rule.name, line):
            self.suppressed_lines.add(line)
            return
        if self.muted:
            return
        self._sink.append(Finding(rule.name, self.relpath, line, message))

    def _suppressed(self, rule_name: str, line: int) -> bool:
        if not 1 <= line <= len(self.lines):
            return False
        text = self.lines[line - 1]
        at = text.find(_DISABLE_MARK)
        if at < 0 or "#" not in text[:at]:
            return False
        spec = text[at + len(_DISABLE_MARK):]
        # the rule list ends at whitespace/'(' — the rest is the reason
        names = spec.split()[0].rstrip("(") if spec.split() else ""
        listed = {n.strip() for n in names.split(",") if n.strip()}
        return rule_name in listed or "all" in listed


def norm_relpath(path: str) -> str:
    """Stable key for baselines/test-ids: package files become
    ``cnosdb_tpu/...`` (posix); anything else stays absolute."""
    ap = os.path.abspath(path)
    if ap == PKG_PARENT or ap.startswith(PKG_PARENT + os.sep):
        return os.path.relpath(ap, PKG_PARENT).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def iter_py_files(paths=None):
    roots = list(paths) if paths else [PKG_DIR]
    for root in roots:
        if os.path.isfile(root):
            yield root
            continue
        for dirpath, dirnames, names in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for name in sorted(names):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


@dataclasses.dataclass
class Report:
    findings: list           # every finding (baselined or not)
    violations: list         # findings in cells over their baseline
    stale: list              # (rule, path, baselined, found) under-budget
    counts: dict             # (rule, path) → found count
    baseline: dict           # (rule, path) → allowed count
    rule_totals: dict = dataclasses.field(default_factory=dict)
    wall_ms: float = 0.0     # analyzer wall time for this run

    @property
    def ok(self) -> bool:
        return not self.violations and not self.stale

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "findings": [f.as_dict() for f in self.findings],
            "violations": [f.as_dict() for f in self.violations],
            "stale": [{"rule": r, "path": p, "baselined": b, "found": n}
                      for (r, p, b, n) in self.stale],
            "counts": {f"{r}:{p}": n for (r, p), n in sorted(self.counts.items())},
            # CI artifact: one-line-diffable per-rule totals (a gauge per
            # rule label, zero-filled for every registered rule)
            "metrics": {
                "cnosdb_analysis_findings_total":
                    dict(sorted(self.rule_totals.items())),
                "cnosdb_analysis_wall_ms": self.wall_ms,
            },
        }


def load_baseline(path: str = BASELINE_PATH) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    return {(rule, relpath): n
            for rule, files in raw.items()
            for relpath, n in files.items()}


def write_baseline(counts: dict, path: str = BASELINE_PATH) -> dict:
    """Freeze ``counts`` ((rule, path) → n) as the new baseline."""
    out: dict[str, dict[str, int]] = {}
    for (rule, relpath), n in sorted(counts.items()):
        if n > 0:
            out.setdefault(rule, {})[relpath] = n
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    return out


def lint_files(paths=None, rules=None, ignore_scope: bool = False,
               report_filter=None) -> list:
    """Run every rule over ``paths`` (default: the whole package) with a
    single AST walk per file; returns raw findings (suppressions already
    honored, baseline NOT yet applied).

    ``report_filter``: optional set of relpaths; files outside it are
    still parsed and indexed (interprocedural summaries need the whole
    project) but report no findings — this is the --changed mode.

    When run with the full registry (``rules is None``), a trailing
    stale-suppression audit flags ``# lint: disable=`` comments that
    absorbed no finding during this run."""
    from . import rules as rules_mod

    active = list(rules) if rules is not None else rules_mod.all_rules()
    per_file = [r for r in active if not isinstance(r, ProjectRule)]
    project_rules = [r for r in active if isinstance(r, ProjectRule)]
    audit = rules is None
    findings: list[Finding] = []
    contexts: list[ModuleContext] = []
    for path in iter_py_files(paths):
        relpath = norm_relpath(path)
        muted = report_filter is not None and relpath not in report_filter
        scoped = [] if muted else [r for r in per_file
                                   if ignore_scope or r.applies_to(relpath)]
        if not scoped and not project_rules and not audit:
            continue
        try:
            with tokenize.open(path) as f:   # honors coding cookies
                source = f.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError) as e:
            if not muted:
                findings.append(Finding("parse-error", relpath,
                                        getattr(e, "lineno", 1) or 1,
                                        repr(e)))
            continue
        ctx = ModuleContext(path, relpath, source, tree, findings)
        ctx.muted = muted
        contexts.append(ctx)
        dispatch: dict[type, list] = {}
        for rule in scoped:
            rule.begin_module(ctx)
            for nt in rule.node_types:
                dispatch.setdefault(nt, []).append(rule)
        if dispatch:
            for node in ast.walk(tree):
                for rule in dispatch.get(type(node), ()):
                    rule.visit(node, ctx)
    if project_rules and contexts:
        from . import interproc

        project = interproc.Project(contexts, ignore_scope=ignore_scope)
        for rule in project_rules:
            rule.check(project)
    if audit:
        _audit_suppressions(contexts, findings)
    return findings


def _disable_comments(source: str):
    """Yield ``(lineno, rule-list)`` for every REAL ``# lint: disable=``
    comment. Tokenized rather than text-scanned so docstrings/strings
    that merely *mention* the marker (this module's own docs, fixtures)
    don't count as suppressions."""
    reader = io.StringIO(source).readline
    try:
        for tok in tokenize.generate_tokens(reader):
            if tok.type != tokenize.COMMENT:
                continue
            at = tok.string.find(_DISABLE_MARK)
            if at < 0:
                continue
            spec = tok.string[at + len(_DISABLE_MARK):]
            names = spec.split()[0].rstrip("(") if spec.split() else ""
            yield tok.start[0], names
    except (tokenize.TokenError, IndentationError):
        return


def _audit_suppressions(contexts, findings) -> None:
    """Flag ``# lint: disable=`` comments that suppressed nothing in this
    run — dead weight at best, a typo'd rule name silently disabling
    nothing at worst. Only meaningful on full-registry runs (a subset run
    legitimately leaves other rules' suppressions idle)."""
    for ctx in contexts:
        if ctx.muted:
            continue
        for lineno, names in _disable_comments(ctx.source):
            if lineno in ctx.suppressed_lines:
                continue
            findings.append(Finding(
                "stale-suppression", ctx.relpath, lineno,
                f"suppression 'disable={names}' absorbed no finding — "
                f"the debt it excused is gone (or the rule name is "
                f"wrong); delete the comment"))


def run(paths=None, rules=None, baseline_path: str = BASELINE_PATH,
        ignore_scope: bool = False, report_filter=None) -> Report:
    import time as _time

    t0 = _time.perf_counter()
    findings = lint_files(paths, rules=rules, ignore_scope=ignore_scope,
                          report_filter=report_filter)
    baseline = load_baseline(baseline_path)
    counts: dict[tuple, int] = {}
    for f in findings:
        counts[(f.rule, f.path)] = counts.get((f.rule, f.path), 0) + 1
    violations = [f for f in findings
                  if counts[(f.rule, f.path)]
                  > baseline.get((f.rule, f.path), 0)]
    # stale cells only matter for files this run actually looked at —
    # a subset run must not flag the rest of the tree's baseline
    seen_paths = {norm_relpath(p) for p in iter_py_files(paths)}
    if report_filter is not None:
        seen_paths &= set(report_filter)
    stale = [(rule, relpath, allowed, counts.get((rule, relpath), 0))
             for (rule, relpath), allowed in sorted(baseline.items())
             if relpath in seen_paths
             and counts.get((rule, relpath), 0) < allowed]
    if rules is None:
        from . import rules as rules_mod

        rule_totals = {r.name: 0 for r in rules_mod.all_rules()}
    else:
        rule_totals = {r.name: 0 for r in rules}
    for f in findings:
        rule_totals[f.rule] = rule_totals.get(f.rule, 0) + 1
    return Report(findings=findings, violations=violations, stale=stale,
                  counts=counts, baseline=baseline,
                  rule_totals=rule_totals,
                  wall_ms=round((_time.perf_counter() - t0) * 1000.0, 1))


def finding_counts() -> dict:
    """Whole-tree summary for bench metadata: totals, per-rule finding
    counts, and the analyzer's wall time, so the cost of the static
    plane rides in the perf trajectory next to the numbers it guards."""
    rep = run()
    return {"findings": len(rep.findings),
            "baselined": len(rep.findings) - len(rep.violations),
            "violations": len(rep.violations),
            "stale_baseline_cells": len(rep.stale),
            "analyzer_wall_ms": rep.wall_ms,
            "per_rule": {r: n for r, n in sorted(rep.rule_totals.items())
                         if n}}
